"""Structured query profiler: span tree, typed events, machine-readable
QueryProfile artifacts, and a process-level metrics registry.

Three layers (README "Profiling"):

- ``spans``    per-query :class:`Profiler` — op spans with phase
  sub-timings, cross-thread attribution via capture()/activate(), typed
  events, bounded buffers. Disarmed by default (zero-allocation no-op).
- ``export``   :class:`QueryProfile` — the stable JSON artifact
  (``df.collect(profile=...)`` / ``daft_tpu.last_profile()``), per-op
  rollups, critical path, schema validation.
- ``metrics``  process-wide counters/gauges/histograms with a
  Prometheus-text dump for the future serving layer.

The chrome-trace output (``daft_tpu.tracing``) is rendered from the same
span tree — one consolidated writer, re-armed per query.
"""

from .export import (SCHEMA_VERSION, QueryProfile, build_profile,
                     validate_profile)
from .metrics import (METRICS, Counter, Gauge, Histogram, MetricsRegistry,
                      record_query_metrics)
from .spans import DISARMED, Profiler, Span

__all__ = [
    "SCHEMA_VERSION", "QueryProfile", "build_profile", "validate_profile",
    "METRICS", "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "record_query_metrics", "DISARMED", "Profiler", "Span",
]
