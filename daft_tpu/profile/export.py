"""QueryProfile: the machine-readable artifact built from a Profiler.

One profile = one executed query: the full span tree, typed events, per-op
rollups (wall/self/io_wait/queue_wait/background time, rows, partitions),
the critical path, RuntimeStats counters, and the memory-ledger snapshot —
a stable JSON schema (``SCHEMA_VERSION``) so bench artifacts and external
tooling can parse profiles across engine versions.

Rollup semantics (kept deliberately reconcilable with RuntimeStats):

- ``wall_ns``  sum of the op's span durations (inclusive)
- ``self_ns``  wall minus SAME-THREAD child op spans — the exact quantity
  ``RuntimeStats.op_wall_ns`` accumulates in the sequential driver, so the
  two agree by construction (acceptance: ±5%)
- ``io_wait_ns``/``queue_wait_ns``  phase buckets recorded where the wait
  happened, aggregated to the nearest enclosing op
- ``background``  bg-span time (async spill writes, prefetch fetches,
  readahead loads) attributed to the op that caused the work via captured
  span tokens; a bg span with no resolvable op ancestor counts into
  ``orphan_spans`` (the cross-thread attribution tests assert 0)
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from .spans import Profiler, Span

__all__ = ["SCHEMA_VERSION", "QueryProfile", "build_profile",
           "validate_profile"]

SCHEMA_VERSION = 1


def _nearest_op_ancestor(sp: Span, by_id: Dict[int, Span],
                         same_thread: bool = False) -> Optional[Span]:
    seen = set()
    cur = by_id.get(sp.parent) if sp.parent is not None else None
    while cur is not None and cur.sid not in seen:
        seen.add(cur.sid)
        if cur.kind == "op" and (not same_thread or cur.thread == sp.thread):
            return cur
        cur = by_id.get(cur.parent) if cur.parent is not None else None
    return None


class QueryProfile:
    """Built once per profiled query; serializes to the stable JSON schema
    and renders the explain_analyze timeline section."""

    def __init__(self, data: dict, spans: List[Span]):
        self._data = data
        self._spans = spans

    # ----------------------------------------------------------- access
    @property
    def query_id(self) -> str:
        return self._data["query_id"]

    @property
    def wall_ns(self) -> int:
        return self._data["wall_ns"]

    @property
    def ops(self) -> Dict[str, dict]:
        return self._data["ops"]

    @property
    def events(self) -> List[dict]:
        return self._data["events"]

    @property
    def counters(self) -> Dict[str, int]:
        return self._data["counters"]

    @property
    def critical_path(self) -> List[dict]:
        return self._data["critical_path"]

    @property
    def critical_path_op(self) -> Optional[str]:
        return self._data["critical_path_op"]

    @property
    def orphan_spans(self) -> int:
        return self._data["orphan_spans"]

    def spans(self) -> List[Span]:
        return list(self._spans)

    def top_ops(self, n: int = 3, key: str = "self_ns") -> List[dict]:
        """Top-n ops by the given rollup key, each with its name folded in."""
        ranked = sorted(self.ops.items(), key=lambda kv: -kv[1].get(key, 0))
        return [{"op": name, **stats} for name, stats in ranked[:n]]

    # ---------------------------------------------------------- exports
    def to_dict(self) -> dict:
        return dict(self._data)

    def to_json(self, path: Optional[str] = None, indent: int = 1) -> str:
        text = json.dumps(self._data, indent=indent, sort_keys=True,
                          default=str)
        if path is not None:
            with open(path, "w", encoding="utf-8") as f:
                f.write(text)
        return text

    def render_timeline(self) -> str:
        """Per-op timeline + critical path (the explain_analyze section)."""
        ops = self.ops
        if not ops:
            return "== Profile ==\n(no spans recorded)"
        names = sorted(ops, key=lambda k: -ops[k]["self_ns"])
        w = max([len(n) for n in names] + [8])
        total_self = sum(o["self_ns"] for o in ops.values()) or 1
        lines = [f"== Profile ({self.query_id}, wall "
                 f"{self.wall_ns / 1e6:.1f} ms) ==",
                 f"{'operator':<{w}}  {'wall ms':>9}  {'self ms':>9}"
                 f"  {'io ms':>7}  {'queue ms':>8}  {'bg ms':>7}"
                 f"  {'parts':>5}  self%"]
        for n in names:
            o = ops[n]
            bg = sum(o.get("background", {}).values())
            bar = "#" * max(1, round(14 * o["self_ns"] / total_self)) \
                if o["self_ns"] else ""
            lines.append(
                f"{n:<{w}}  {o['wall_ns'] / 1e6:>9.2f}"
                f"  {o['self_ns'] / 1e6:>9.2f}"
                f"  {o['io_wait_ns'] / 1e6:>7.1f}"
                f"  {o['queue_wait_ns'] / 1e6:>8.1f}"
                f"  {bg / 1e6:>7.1f}  {o['partitions']:>5}"
                f"  {100 * o['self_ns'] / total_self:>4.0f}% {bar}")
        cp = self.critical_path
        if cp:
            path = " -> ".join(step["op"] for step in cp)
            cp_ns = sum(step["self_ns"] for step in cp)
            lines.append("")
            lines.append(f"critical path: {path} "
                         f"({cp_ns / 1e6:.1f} ms self, "
                         f"{100 * cp_ns / total_self:.0f}% of op self time)")
        n_ev = len(self.events)
        if n_ev:
            kinds: Dict[str, int] = {}
            for ev in self.events:
                kinds[ev["kind"]] = kinds.get(ev["kind"], 0) + 1
            lines.append("events: " + ", ".join(
                f"{k}={v}" for k, v in sorted(kinds.items())))
        if self.orphan_spans:
            lines.append(f"WARNING: {self.orphan_spans} orphan background "
                         "span(s) (unattributed work)")
        return "\n".join(lines)


def build_profile(profiler: Profiler, stats=None) -> QueryProfile:
    """Roll a finished Profiler (plus the query's RuntimeStats) up into a
    QueryProfile."""
    if profiler.t_end_ns is None:  # execute_plan normally finished it;
        profiler.finish()          # don't extend an already-stamped wall
    spans = profiler.spans_snapshot()
    by_id = {s.sid: s for s in spans}

    # same-thread child-op durations (for self time, mirroring the
    # driver's thread-local stack accounting)
    child_op_ns: Dict[int, int] = {}
    for s in spans:
        if s.kind != "op":
            continue
        anc = _nearest_op_ancestor(s, by_id, same_thread=True)
        if anc is not None:
            child_op_ns[anc.sid] = child_op_ns.get(anc.sid, 0) + s.dur_ns

    ops: Dict[str, dict] = {}
    op_edges: Dict[str, Dict[str, int]] = {}  # parent op -> child op -> ns
    root_ops: Dict[str, int] = {}
    orphans = 0

    def op_entry(name: str) -> dict:
        o = ops.get(name)
        if o is None:
            o = ops[name] = {"wall_ns": 0, "self_ns": 0, "io_wait_ns": 0,
                             "queue_wait_ns": 0, "device_ns": 0, "rows": 0,
                             "partitions": 0, "background": {}}
        return o

    for s in spans:
        ph = s.phases or {}
        if s.kind == "op":
            name = s.op or s.name
            o = op_entry(name)
            o["wall_ns"] += s.dur_ns
            o["self_ns"] += max(s.dur_ns - child_op_ns.get(s.sid, 0), 0)
            o["io_wait_ns"] += ph.get("io_wait", 0)
            o["queue_wait_ns"] += ph.get("queue_wait", 0)
            o["device_ns"] += ph.get("device_dispatch", 0)
            o["partitions"] += 1
            if s.attrs:
                o["rows"] += s.attrs.get("rows", 0) or 0
            anc = _nearest_op_ancestor(s, by_id)
            if anc is not None:
                pname = anc.op or anc.name
                if pname != name:
                    edges = op_edges.setdefault(pname, {})
                    edges[name] = edges.get(name, 0) + s.dur_ns
            else:
                root_ops[name] = root_ops.get(name, 0) + s.dur_ns
        else:
            anc = _nearest_op_ancestor(s, by_id)
            if anc is None:
                if s.kind == "bg":
                    orphans += 1
                continue
            o = op_entry(anc.op or anc.name)
            bg = o["background"]
            bg[s.name] = bg.get(s.name, 0) + s.dur_ns
            # waits recorded inside phase/bg sub-spans (fanout dispatch
            # queue_wait, collective device time, spill io_wait) still
            # belong to the enclosing op's timeline view — without this
            # the per-op buckets undercount the RuntimeStats totals
            o["io_wait_ns"] += ph.get("io_wait", 0)
            o["queue_wait_ns"] += ph.get("queue_wait", 0)
            o["device_ns"] += ph.get("device_dispatch", 0)

    # critical path: from the hottest root op, greedily follow the child op
    # with the largest caused wall time
    critical: List[dict] = []
    if root_ops:
        cur = max(root_ops, key=lambda k: root_ops[k])
        seen = set()
        while cur is not None and cur not in seen:
            seen.add(cur)
            critical.append({"op": cur, "self_ns": ops[cur]["self_ns"],
                             "wall_ns": ops[cur]["wall_ns"]})
            nxt = op_edges.get(cur)
            cur = max(nxt, key=lambda k: nxt[k]) if nxt else None
    cp_op = (max(ops, key=lambda k: ops[k]["self_ns"]) if ops else None)

    counters: Dict[str, int] = {}
    op_rows: Dict[str, int] = {}
    if stats is not None:
        snap = stats.snapshot()
        counters = snap["counters"]
        op_rows = snap["op_rows"]
    try:
        from ..spill import MEMORY_LEDGER

        ledger = MEMORY_LEDGER.snapshot()
    except Exception:
        ledger = {}

    data = {
        "schema_version": SCHEMA_VERSION,
        "query_id": profiler.query_id,
        "started_unix": profiler.started_unix,
        "wall_ns": profiler.wall_ns,
        "ops": ops,
        "spans": [s.as_dict() for s in spans],
        "events": profiler.events_snapshot(),
        "critical_path": critical,
        "critical_path_op": cp_op,
        "counters": counters,
        "op_rows": op_rows,
        "unattributed_phases": profiler.unattributed_phases(),
        "ledger": ledger,
        "orphan_spans": orphans,
        "dropped_spans": profiler.dropped_spans,
        "dropped_events": profiler.dropped_events,
    }
    return QueryProfile(data, spans)


# required top-level keys -> type checks for validate_profile
_TOP_KEYS = {
    "schema_version": int,
    "query_id": str,
    "started_unix": (int, float),
    "wall_ns": int,
    "ops": dict,
    "spans": list,
    "events": list,
    "critical_path": list,
    "counters": dict,
    "orphan_spans": int,
    "dropped_spans": int,
    "dropped_events": int,
}
_OP_KEYS = ("wall_ns", "self_ns", "io_wait_ns", "queue_wait_ns",
            "partitions")
_SPAN_KEYS = {"id": int, "name": str, "kind": str, "thread": str,
              "t0_ns": int, "dur_ns": int}


def validate_profile(d: dict) -> List[str]:
    """Schema check for a QueryProfile dict (as loaded from JSON). Returns
    a list of violation strings — empty means valid. This is the contract
    ``make profile-smoke`` and the bench artifacts are validated against."""
    errs: List[str] = []
    if not isinstance(d, dict):
        return ["profile is not an object"]
    for key, typ in _TOP_KEYS.items():
        if key not in d:
            errs.append(f"missing key {key!r}")
        elif not isinstance(d[key], typ):
            errs.append(f"{key!r} has type {type(d[key]).__name__}")
    if errs:
        return errs
    if d["schema_version"] != SCHEMA_VERSION:
        errs.append(f"schema_version {d['schema_version']} != "
                    f"{SCHEMA_VERSION}")
    for name, o in d["ops"].items():
        for k in _OP_KEYS:
            if not isinstance(o.get(k), int):
                errs.append(f"ops[{name!r}].{k} missing or non-int")
    ids = set()
    for i, s in enumerate(d["spans"]):
        for k, typ in _SPAN_KEYS.items():
            if not isinstance(s.get(k), typ):
                errs.append(f"spans[{i}].{k} missing or mistyped")
                break
        else:
            ids.add(s["id"])
    if not d["dropped_spans"]:
        # with drops, a surviving child may reference an evicted parent
        for i, s in enumerate(d["spans"]):
            p = s.get("parent")
            if p is not None and p not in ids:
                errs.append(f"spans[{i}] parent {p} not in profile")
    for i, ev in enumerate(d["events"]):
        if not isinstance(ev.get("t_ns"), int) or \
                not isinstance(ev.get("kind"), str):
            errs.append(f"events[{i}] missing t_ns/kind")
    cp = d["critical_path"]
    for i, step in enumerate(cp):
        if step.get("op") not in d["ops"]:
            errs.append(f"critical_path[{i}] names unknown op")
    return errs
