"""Kernel layer: host (Arrow C++ / numpy) kernels and device (jax/XLA/pallas) kernels.

The host kernels mirror the reference's Rust kernel set under
`src/daft-core/src/array/ops/` and `src/daft-core/src/kernels/`; the device kernels are
the TPU-native path used by the device executor (jit-fused columnar compute).
"""
