"""Device-side hash-join probe.

Semantic spec: the reference's probe table
(/root/reference/src/daft-table/src/probe_table/mod.rs:14-28 — build one
side, stream the other, null keys never match) and hash_join
(ops/joins/hash_join.rs). The TPU formulation avoids a hash table entirely:
no data-dependent control flow fits XLA, so the build side is SORTED once
(cached with the partition, like column staging) and every probe is a
vectorized `searchsorted` — O(P log B) fully on the VPU with static shapes.

Scope: 1-4 keys — integer/date values, and plain STRING columns via
joint-dictionary recoding (_stage_key_pair) — with multi-column keys packed
into one surrogate lane via exact mixed-radix packing. An overflowing
composite key space or other key shapes (computed strings, floats) fall
back to the host acero join. Probe direction adapts:

- build = RIGHT side (right keys unique): inner/left/semi/anti with probe
  over the left rows — output already in host order (left idx, right idx).
- build = LEFT side (left keys unique): inner — output re-sorted stably by
  left idx to match the host join's deterministic order.
- duplicate keys on BOTH sides (N:M): the RANGE probe computes each probe
  row's span of matches over the sorted build keys on device
  (_range_probe_kernel); the data-dependent expansion to (lidx, ridx)
  pairs happens on host (_range_join, side tag "expanded").

The PK probe returns per-probe-row (hit, build_row_idx); the host assembles
output columns with vectorized takes (strings and other host-only payload
never stage)."""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .device import is_device_dtype, size_bucket, stage_table_columns


@functools.partial(jax.jit, static_argnames=())
def _range_probe_kernel(build_vals, build_valid, probe_vals, probe_valid):
    """Per-probe-row match RANGE over the sorted build keys: (lo [P], counts
    [P], perm [B], dup). The ONE sort serves both probe flavors — when dup
    (duplicate valid build keys) is False every count is <= 1, so the PK
    outputs are hit = counts > 0 and build row perm[lo] (_pk_outputs);
    otherwise the match set of probe row i is perm[lo[i] : lo[i]+counts[i]],
    valid lanes only, expanded on host.

    Valid lanes sort before null/padding lanes within an equal-key run
    (lexsort secondary key), so each run's valid matches are a contiguous
    prefix and the cumulative-valid counter turns [lo, hi) into an exact
    valid-match count. The variable-size expansion happens on the HOST
    (data-dependent shapes cannot live under XLA): reference semantic is the
    multi-row probe of src/daft-table/src/probe_table/mod.rs."""
    big = jnp.iinfo(build_vals.dtype).max
    k = jnp.where(build_valid, build_vals, big)
    perm = jnp.lexsort((~build_valid, k))
    sk = k[perm]
    sorted_valid = build_valid[perm]
    dup = jnp.any((sk[1:] == sk[:-1]) & sorted_valid[1:] & sorted_valid[:-1])
    vp = jnp.concatenate([jnp.zeros(1, jnp.int32),
                          jnp.cumsum(sorted_valid.astype(jnp.int32))])
    lo = jnp.searchsorted(sk, probe_vals, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(sk, probe_vals, side="right").astype(jnp.int32)
    counts = jnp.where(probe_valid, vp[hi] - vp[lo], 0)
    return lo, counts, perm.astype(jnp.int32), dup


@functools.partial(jax.jit, static_argnames=())
def _pk_outputs(lo, counts, perm):
    """PK-build view of the range probe (dup == False): per-probe-row
    (hit, build_row_idx), computed on device so the host fetches the same
    two probe-sized arrays the dedicated PK kernel used to produce."""
    b = perm.shape[0]
    return counts > 0, perm[jnp.minimum(lo, b - 1)]


def _range_join(lo_d, counts_d, perm_d, ln: int, how: str):
    """N:M join (duplicate build keys): vectorized host expansion of the
    device range probe. Returns the executor contract — ("right_build",
    hit, _) for semi/anti (only the hit mask is consumed), or ("expanded",
    lidx, ridx) index pairs for inner/left (ridx == -1 marks a left-outer
    miss).

    Order contract: rows come out left-row-major with matches in
    sorted-build-key (perm) order — which differs from the acero host
    join's order. That is fine: join output order is UNSPECIFIED
    engine-wide (see Table.hash_join), so a query flipping between device
    and host paths may legitimately reorder rows; only the multiset is
    guaranteed."""
    lo = np.asarray(jax.device_get(lo_d))[:ln].astype(np.int64)
    counts = np.asarray(jax.device_get(counts_d))[:ln].astype(np.int64)
    perm = np.asarray(jax.device_get(perm_d)).astype(np.int64)
    hit = counts > 0
    if how in ("semi", "anti"):
        return "right_build", hit, np.zeros(ln, dtype=np.int64)
    # effective row multiplicity: misses keep one output row under left-outer
    ce = counts if how == "inner" else np.where(hit, counts, 1)
    total = int(ce.sum())
    lidx = np.repeat(np.arange(ln, dtype=np.int64), ce)
    starts = np.repeat(lo, ce)
    offs = np.arange(total, dtype=np.int64) - np.repeat(np.cumsum(ce) - ce, ce)
    pos = np.minimum(starts + offs, len(perm) - 1)
    ridx = perm[pos]
    if how != "inner":
        ridx = np.where(np.repeat(hit, ce), ridx, -1)
    return "expanded", lidx, ridx


def _stage_key(table, key_expr, cache) -> Optional[Tuple]:
    """Stage one join-key column (post-normalization) -> (values, valid)."""
    from .device import normalize_and_check

    schema = table.schema
    nodes = normalize_and_check([key_expr], schema)
    if nodes is None:
        return None
    from ..expressions import required_columns

    from ..datatypes import TypeKind

    node = nodes[0]
    dt = node.to_field(schema).dtype
    if not (dt.is_integer() or dt.kind == TypeKind.DATE):
        return None
    cols = required_columns(node)
    if not cols:
        return None
    b = size_bucket(len(table))
    staged = stage_table_columns(table, cols, b, cache)
    if staged is None:
        return None
    env, dcs = staged
    from .device import (compile_projection, int64_wrap_safe,
                         string_literal_env, string_lut_env)

    if not int64_wrap_safe([node], schema, env, cache, b):
        return None  # computed int64 key could wrap in int32 lanes
    # an integer key expression may still embed a string-literal comparison
    # (e.g. (col('s') == 'a').cast(int)): the compiled closure reads the
    # literal's per-partition code bounds from the env
    env = string_literal_env([node], schema, dcs, env)
    if env is None:
        return None
    env = string_lut_env([node], schema, dcs, env)
    if env is None:
        return None
    # int-valued string transforms (length/find) inside the key compile
    # against host dictionary-evaluated lanes; cross-column transform
    # compares (e.g. (upper(a) == b).cast(int) keys) need their pairwise
    # joint remaps too — aux is SHARED so the compare env can see the
    # transform sides' dictionaries
    from .device import string_transform_env, transform_cmp_env

    aux: dict = {}
    env = string_transform_env([node], schema, table, b, cache, env, aux)
    if env is None:
        return None
    env = transform_cmp_env([node], schema, table, b, cache, dcs, env, aux)
    if env is None:
        return None
    run, _ = compile_projection([node], schema, tuple(sorted(cols)))
    (vals, valid), = run(env)
    if not jnp.issubdtype(vals.dtype, jnp.integer):
        return None
    # a null-reviving key expression (fill_null, int transforms through the
    # null slot) marks size-bucket PADDING lanes valid; the probe kernels
    # mask by validity, not row count, so phantom build rows would match —
    # force padding back invalid at THIS staging boundary (covers every
    # compiled key shape)
    n = len(table)
    if int(valid.shape[0]) > n:
        valid = valid & (jnp.arange(int(valid.shape[0]), dtype=jnp.int32) < n)
    return vals, valid


def _is_plain_string_key(table, key_expr) -> bool:
    """Cheap shape check (no staging): the key normalizes to a bare string
    Column OR a row-local transform of one, i.e. the joint-dictionary path
    could apply."""
    node = _normalized_key_node(table, key_expr)
    if node is None:
        return False
    from .device import _plain_string_column

    return (_plain_string_column(node, table.schema) is not None
            or _string_valued_transform_shape(node, table.schema) is not None)


def _string_valued_transform_shape(node, schema):
    """The transform shape ONLY when the node is string-VALUED: a join key
    like length(s) (int) or s=="x" (bool) must not reach the joint
    dictionary, whose merge casts to large_string and would silently join
    ints against their string representations."""
    try:
        if not node.to_field(schema).dtype.is_string():
            return None
    except (ValueError, KeyError):
        return None
    from .device import _string_dict_value_shape

    return _string_dict_value_shape(node, schema)


def _normalized_key_node(table, key_expr):
    """Literal-normalized + Between-rewritten key node (the same
    normalization every dictionary cache key uses), or None."""
    from ..expressions import normalize_literals
    from .device import _rewrite_between

    try:
        return _rewrite_between(
            normalize_literals(key_expr._node, table.schema), table.schema)
    except (ValueError, KeyError):
        return None


class _CodeSide:
    """(values, valid, dictionary) triple for one string join-key side —
    a plain column's dictionary codes, or a TRANSFORMED key's sorted-recode
    lane with its transformed dictionary. Duck-typed like DeviceColumn for
    _joint_remaps (which reads .values/.valid/.dictionary only)."""

    __slots__ = ("values", "valid", "dictionary")

    def __init__(self, values, valid, dictionary):
        self.values = values
        self.valid = valid
        self.dictionary = dictionary


def _string_code_side(table, key_expr, cache) -> Optional[_CodeSide]:
    """Stage one string-key side into code space: plain columns via their
    sorted dictionary, row-local transforms (upper/substr/fill_null chains,
    r5) via the sorted-recode transform lane — both yield (codes, valid,
    dictionary) and merge through the same joint dictionary."""
    from .device import (_plain_string_column, dict_transform_lane,
                         size_bucket, stage_table_columns)

    node = _normalized_key_node(table, key_expr)
    if node is None:
        return None
    cname = _plain_string_column(node, table.schema)
    if cname is not None:
        staged = stage_table_columns(table, [cname],
                                     size_bucket(len(table)), cache)
        if staged is None:
            return None
        dc = staged[1][cname]
        if dc.dictionary is None:
            return None
        return _CodeSide(dc.values, dc.valid, dc.dictionary)
    shape = _string_valued_transform_shape(node, table.schema)
    if shape is None:
        return None
    lane = dict_transform_lane(table, shape, size_bucket(len(table)), cache)
    if lane is None:
        return None
    vals, valid, tuniq = lane
    # a null-reviving transform (fill_null chain) marks the size-bucket
    # PADDING lanes valid (they gather through the null slot); the probe
    # kernels mask by validity, not row count, so phantom build rows would
    # match — force padding back invalid here
    n = len(table)
    b = int(valid.shape[0])
    if b > n:
        valid = valid & (jnp.arange(b, dtype=jnp.int32) < n)
    return _CodeSide(vals, valid, tuniq)


@jax.jit
def _recode(codes, remap):
    """Gather per-side dictionary codes into the JOINT dictionary's code
    space (remap is the small per-dictionary index array)."""
    return remap[codes]


def _joint_remaps(ldc, rdc, lcache, rcache):
    """(lremap, rremap) device arrays mapping each side's dictionary codes
    into their sorted JOINT dictionary's code space. Cached per dictionary
    PAIR in BOTH sides' caches (the entry pins both pa.Arrays, keeping the
    id-keys valid): a broadcast-shaped join of one build side against P
    probe partitions hits the build side's cache, merging the dictionaries
    once, not P times. Remaps pad to a size bucket so _recode compiles per
    bucket, not per dictionary length."""
    key = ("__jointremap__", id(ldc.dictionary), id(rdc.dictionary))
    for cache in (lcache, rcache):
        cached = cache.get(key) if cache is not None else None
        if cached is not None:
            return cached[2], cached[3]
    import pyarrow as pa
    import pyarrow.compute as pc

    from .device import joint_remap

    joint = pc.unique(pa.concat_arrays([
        ldc.dictionary.cast(pa.large_string()),
        rdc.dictionary.cast(pa.large_string())]))
    joint = joint.take(pc.sort_indices(joint))
    lremap = joint_remap(ldc.dictionary, joint)
    rremap = joint_remap(rdc.dictionary, joint)
    entry = (ldc.dictionary, rdc.dictionary, lremap, rremap)
    for cache in (lcache, rcache):
        if cache is not None:
            cache[key] = entry
    return lremap, rremap


def _stage_key_pair(ltable, rtable, lkey, rkey, lcache, rcache,
                    ls=None, rs=None):
    """((lv, lm), (rv, rm)) aligned int lanes for ONE key pair.

    Numeric/date keys stage independently (_stage_key; pass pre-staged
    sides via ls/rs to avoid re-dispatching). Plain STRING columns cannot:
    per-partition dictionary codes are incomparable across tables — so
    both sides' sorted dictionaries merge into one sorted JOINT dictionary
    (host, O(u1+u2), cached per pair) and each side's codes gather through
    a small remap array on device, giving equal strings equal ints across
    tables. The probe then runs unchanged on int lanes. Reference
    semantics: the probe table hashes raw key bytes so cross-table
    equality is inherent (probe_table/mod.rs); the TPU formulation makes
    it inherent by unifying the code space instead."""
    if ls is None:
        ls = _stage_key(ltable, lkey, lcache)
    if rs is None:
        rs = _stage_key(rtable, rkey, rcache)
    if ls is not None and rs is not None:
        return ls, rs
    ldc = _string_code_side(ltable, lkey, lcache)
    rdc = _string_code_side(rtable, rkey, rcache)
    if ldc is None or rdc is None:
        return None
    lremap, rremap = _joint_remaps(ldc, rdc, lcache, rcache)
    lv = _recode(ldc.values, lremap)
    rv = _recode(rdc.values, rremap)
    return (lv, ldc.valid), (rv, rdc.valid)


@jax.jit
def _masked_min_max_multi(vs, ms):
    """Per-column masked min/max for a tuple of key columns, ONE fused call
    (and so one host sync) per side."""
    mins = jnp.stack([jnp.min(jnp.where(m, v, jnp.iinfo(v.dtype).max))
                      for v, m in zip(vs, ms)])
    maxs = jnp.stack([jnp.max(jnp.where(m, v, jnp.iinfo(v.dtype).min))
                      for v, m in zip(vs, ms)])
    return mins, maxs


@functools.partial(jax.jit, static_argnames=("wide",))
def _pack_kernel(vs, ms, mins, strides, wide):
    """Mixed-radix composite-key packing. mins/strides are TRACED arrays —
    they vary per partition pair, so making them static would retrace and
    recompile per call; with them traced, one compilation per (shape, nkeys,
    wide) serves every partition."""
    out_dt = jnp.int64 if wide else jnp.int32
    packed = jnp.zeros(vs[0].shape, out_dt)
    valid = jnp.ones(ms[0].shape, bool)
    for i, (v, m) in enumerate(zip(vs, ms)):
        packed = packed + ((v.astype(out_dt) - mins[i].astype(out_dt))
                           * strides[i].astype(out_dt))
        valid = valid & m
    # clamp invalid lanes so padding garbage stays in-range (matching is
    # still decided by the validity masks in the probe kernel)
    return jnp.where(valid, packed, 0), valid


def _pack_composite_keys(sides):
    """Pack N integer key columns into ONE surrogate key column per side so
    the single-key sorted probe applies unchanged (reference semantic: the
    reference's probe table hashes all key columns together,
    src/daft-table/src/probe_table/mod.rs:14-28; the TPU formulation needs a
    total order, so it uses exact mixed-radix packing instead of hashing —
    collision-free by construction).

    `sides` is a list of [(vals, valid), ...] per side, all of the same key
    count. Offsets/strides come from the min/max over BOTH sides so equal
    keys pack identically. Returns [(packed, valid), ...] per side, or None
    when the combined key space overflows the lane dtype (host join then).
    A row's composite key is valid only if every component is.
    """
    from .device import x64_enabled

    nkeys = len(sides[0])
    per_side = []
    for side in sides:
        vs = tuple(v for v, _ in side)
        ms = tuple(m for _, m in side)
        mns, mxs = _masked_min_max_multi(vs, ms)
        per_side.append((np.asarray(mns), np.asarray(mxs)))  # one sync/side
    mins = []
    spans = []
    for j in range(nkeys):
        lo = min(int(mns[j]) for mns, _ in per_side)
        hi = max(int(mxs[j]) for _, mxs in per_side)
        if hi < lo:  # all-null column on both sides: nothing can match
            lo, hi = 0, 0
        mins.append(lo)
        spans.append(hi - lo + 1)
    wide = x64_enabled()
    limit = (2 ** 63 - 1) if wide else (2 ** 31 - 1)
    total = 1
    for s in spans:
        total *= s
        if total > limit:
            return None
    strides = []
    acc = 1
    for s in reversed(spans):
        strides.append(acc)
        acc *= s
    strides = tuple(reversed(strides))

    lane_np = np.int64 if wide else np.int32
    mins_arr = np.asarray(mins, dtype=lane_np)
    strides_arr = np.asarray(strides, dtype=lane_np)
    out = []
    for side in sides:
        vs = tuple(v for v, _ in side)
        ms = tuple(m for _, m in side)
        out.append(_pack_kernel(vs, ms, mins_arr, strides_arr, wide))
    return out


def _replica_cache_key(key_expr):
    from .device import x64_enabled

    return ("__join_key_replica__", key_expr._node._key(), x64_enabled())


def replicate_join_key(part, key_expr, mesh) -> bool:
    """Stage `key_expr` over `part` once and replicate it into every device of
    `mesh` (one fully-replicated `jax.device_put` — an ICI broadcast, the TPU
    form of the reference's broadcast-join small-side replication,
    daft/execution/physical_plan.py:374). The per-device copies are cached on
    the partition; `device_join_indices` then probes against the copy local
    to the probe shard's device. Returns True when replicated."""
    from jax.sharding import NamedSharding, PartitionSpec

    tbl = part.table()
    staged = _stage_key(tbl, key_expr, part.device_stage_cache())
    if staged is None:
        return False
    vals, valid = staged
    rep = NamedSharding(mesh, PartitionSpec(*([None] * vals.ndim)))
    rep1 = NamedSharding(mesh, PartitionSpec(None))
    gv = jax.device_put(vals, rep)
    gm = jax.device_put(valid, rep1)
    vmap = {s.device: s.data for s in gv.addressable_shards}
    mmap = {s.device: s.data for s in gm.addressable_shards}
    part.device_stage_cache()[_replica_cache_key(key_expr)] = {
        d: (vmap[d], mmap[d]) for d in vmap}
    return True


def join_key_replicas(part, key_expr):
    """The {device: (vals, valid)} replica map cached by replicate_join_key,
    or None."""
    if part is None:
        return None
    try:
        return part.device_stage_cache().get(_replica_cache_key(key_expr))
    except Exception:
        return None


def _device_of(arr):
    try:
        devs = arr.devices()
        if len(devs) == 1:
            return next(iter(devs))
    except Exception:
        pass
    return None


def device_join_indices(left_table, right_table, left_keys, right_keys,
                        left_cache=None, right_cache=None, how: str = "inner",
                        left_replicas=None, right_replicas=None):
    """Blocking device probe: launch + resolve in one call (see
    device_join_launch for the pipelined split). Returns (side, hit, bidx)
    or None when ineligible."""
    launch = device_join_launch(left_table, right_table, left_keys,
                                right_keys, left_cache, right_cache, how,
                                left_replicas, right_replicas)
    return None if launch is None else launch()


def device_join_launch(left_table, right_table, left_keys, right_keys,
                       left_cache=None, right_cache=None, how: str = "inner",
                       left_replicas=None, right_replicas=None):
    """Stage the keys and LAUNCH the right-build range probe WITHOUT
    blocking (jax dispatch is asynchronous); the returned zero-arg resolver
    makes the dup decision, runs any second-orientation probe, and returns
    (side, hit, bidx) — the executor stages pair i+1 while pair i probes,
    the join flavor of the double-buffered projection dispatch (PARITY
    known-gap 36). Resolver contract, or None when ineligible:

    - side == "right_build": hit/bidx are per LEFT row (bidx indexes right)
    - side == "left_build": hit/bidx are per RIGHT row (bidx indexes left)
    - side == "expanded": hit/bidx are pre-expanded (lidx, ridx) row-index
      pairs from the N:M range join (ridx == -1 marks a left-outer miss)
    or None when ineligible (non-integer keys, overflowing key space, ...).

    Accepts a single key or a list of keys per side: multi-column keys pack
    into one surrogate lane via exact mixed-radix packing
    (_pack_composite_keys) and then take the same sorted probe.

    When a side carries mesh replicas (replicate_join_key), the copy living on
    the OTHER side's device is swapped in, keeping the probe device-local.
    """
    if not isinstance(left_keys, (list, tuple)):
        left_keys = [left_keys]
    if not isinstance(right_keys, (list, tuple)):
        right_keys = [right_keys]
    if len(left_keys) != len(right_keys) or not left_keys:
        return None
    ln, rn = len(left_table), len(right_table)
    if ln == 0 or rn == 0:
        return None
    if len(left_keys) > 1:
        pairs = [_stage_key_pair(left_table, right_table, lk_, rk_,
                                 left_cache, right_cache)
                 for lk_, rk_ in zip(left_keys, right_keys)]
        if any(p is None for p in pairs):
            return None
        lks = [p[0] for p in pairs]
        rks = [p[1] for p in pairs]
        packed = _pack_composite_keys([lks, rks])
        if packed is None:
            return None
        (lv, lm), (rv, rm) = packed
        return _launch_probe(lv, lm, rv, rm, ln, rn, how)
    left_key, right_key = left_keys[0], right_keys[0]
    lk = _stage_key(left_table, left_key, left_cache)
    rk = None
    if lk is not None and right_replicas:
        # replica hit: skip staging the build side entirely — its existence
        # already proves the key passed the device-eligibility checks
        d = _device_of(lk[0])
        if d is not None and d in right_replicas:
            rk = right_replicas[d]
    if rk is not None:
        lv, lm = lk
    else:
        if lk is None and not _is_plain_string_key(left_table, left_key):
            return None  # ineligible left key: don't stage the right side
        rk0 = _stage_key(right_table, right_key, right_cache)
        if lk is None or rk0 is None:
            # string keys (or one string side): recode through the joint
            # dictionary so equal strings get equal ints across tables
            # (pre-staged non-None sides pass through)
            pair = _stage_key_pair(left_table, right_table,
                                   left_key, right_key,
                                   left_cache, right_cache,
                                   ls=lk, rs=rk0)
            if pair is None:
                return None
            (lv, lm), rk = pair
        else:
            lv, lm = lk
            rk = rk0
            if left_replicas:
                d = _device_of(rk[0])
                if d is not None and d in left_replicas:
                    lv, lm = left_replicas[d]
    rv, rm = rk
    if lv.dtype != rv.dtype:
        return None
    return _launch_probe(lv, lm, rv, rm, ln, rn, how)


def _launch_probe(lv, lm, rv, rm, ln: int, rn: int, how: str):
    """Dispatch the right-build range probe now (async); return the
    resolver that makes the dup decision and finishes the probe."""
    lo, counts, perm, dup = _range_probe_kernel(rv, rm, lv, lm)

    def resolve():
        # build=right first (probe order == host output order); ONE sort
        # serves whichever path the dup flag selects
        if not bool(dup):
            hit, bidx = _pk_outputs(lo, counts, perm)
            hit = np.asarray(jax.device_get(hit))[:ln]
            bidx = np.asarray(jax.device_get(bidx))[:ln].astype(np.int64)
            return "right_build", hit, bidx
        if how == "inner":
            lo2, counts2, perm2, dup2 = _range_probe_kernel(lv, lm, rv, rm)
            if not bool(dup2):
                hit, bidx = _pk_outputs(lo2, counts2, perm2)
                hit = np.asarray(jax.device_get(hit))[:rn]
                bidx = np.asarray(jax.device_get(bidx))[:rn].astype(np.int64)
                return "left_build", hit, bidx
        # duplicate build keys on every usable orientation: N:M range join,
        # reusing the right-build probe already on device
        return _range_join(lo, counts, perm, ln, how)

    return resolve
