"""Device-side hash-join probe.

Semantic spec: the reference's probe table
(/root/reference/src/daft-table/src/probe_table/mod.rs:14-28 — build one
side, stream the other, null keys never match) and hash_join
(ops/joins/hash_join.rs). The TPU formulation avoids a hash table entirely:
no data-dependent control flow fits XLA, so the build side is SORTED once
(cached with the partition, like column staging) and every probe is a
vectorized `searchsorted` — O(P log B) fully on the VPU with static shapes.

Scope (the TPC-H star-join shape): single integer/date key, unique keys on
the build side (primary-key side). Multiplicity >1 or multi-column keys fall
back to the host acero join. Probe direction adapts:

- build = RIGHT side (right keys unique): inner/left/semi/anti with probe
  over the left rows — output already in host order (left idx, right idx).
- build = LEFT side (left keys unique): inner — output re-sorted stably by
  left idx to match the host join's deterministic order.

The probe returns per-probe-row (hit, build_row_idx); the host assembles
output columns with vectorized takes (strings and other host-only payload
never stage)."""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .device import is_device_dtype, size_bucket, stage_table_columns


@functools.partial(jax.jit, static_argnames=())
def _probe_kernel(build_vals, build_valid, probe_vals, probe_valid):
    """(hit [P], build_idx [P], dup_flag) — sentinel-free via validity masks."""
    big = jnp.iinfo(build_vals.dtype).max
    k = jnp.where(build_valid, build_vals, big)  # nulls+padding sort to the end
    # among equal keys, valid lanes first: a real key == INT_MAX must not be
    # shadowed by a null-sentinel lane at the same value
    perm = jnp.lexsort((~build_valid, k))
    sk = k[perm]
    sorted_valid = build_valid[perm]
    # duplicate VALID keys anywhere -> not a PK side, host must handle
    dup = jnp.any((sk[1:] == sk[:-1]) & sorted_valid[1:] & sorted_valid[:-1])
    pos = jnp.clip(jnp.searchsorted(sk, probe_vals), 0, sk.shape[0] - 1)
    bidx = perm[pos]
    hit = (sk[pos] == probe_vals) & probe_valid & build_valid[bidx]
    return hit, bidx.astype(jnp.int32), dup


def _stage_key(table, key_expr, cache) -> Optional[Tuple]:
    """Stage one join-key column (post-normalization) -> (values, valid)."""
    from .device import normalize_and_check

    schema = table.schema
    nodes = normalize_and_check([key_expr], schema)
    if nodes is None:
        return None
    from ..expressions import required_columns

    from ..datatypes import TypeKind

    node = nodes[0]
    dt = node.to_field(schema).dtype
    if not (dt.is_integer() or dt.kind == TypeKind.DATE):
        return None
    cols = required_columns(node)
    if not cols:
        return None
    b = size_bucket(len(table))
    env = stage_table_columns(table, cols, b, cache)
    if env is None:
        return None
    from .device import compile_projection

    run, _ = compile_projection([node], schema, tuple(sorted(cols)))
    (vals, valid), = run(env)
    if not jnp.issubdtype(vals.dtype, jnp.integer):
        return None
    return vals, valid


def _replica_cache_key(key_expr):
    from .device import x64_enabled

    return ("__join_key_replica__", key_expr._node._key(), x64_enabled())


def replicate_join_key(part, key_expr, mesh) -> bool:
    """Stage `key_expr` over `part` once and replicate it into every device of
    `mesh` (one fully-replicated `jax.device_put` — an ICI broadcast, the TPU
    form of the reference's broadcast-join small-side replication,
    daft/execution/physical_plan.py:374). The per-device copies are cached on
    the partition; `device_join_indices` then probes against the copy local
    to the probe shard's device. Returns True when replicated."""
    from jax.sharding import NamedSharding, PartitionSpec

    tbl = part.table()
    staged = _stage_key(tbl, key_expr, part.device_stage_cache())
    if staged is None:
        return False
    vals, valid = staged
    rep = NamedSharding(mesh, PartitionSpec(*([None] * vals.ndim)))
    rep1 = NamedSharding(mesh, PartitionSpec(None))
    gv = jax.device_put(vals, rep)
    gm = jax.device_put(valid, rep1)
    vmap = {s.device: s.data for s in gv.addressable_shards}
    mmap = {s.device: s.data for s in gm.addressable_shards}
    part.device_stage_cache()[_replica_cache_key(key_expr)] = {
        d: (vmap[d], mmap[d]) for d in vmap}
    return True


def join_key_replicas(part, key_expr):
    """The {device: (vals, valid)} replica map cached by replicate_join_key,
    or None."""
    if part is None:
        return None
    try:
        return part.device_stage_cache().get(_replica_cache_key(key_expr))
    except Exception:
        return None


def _device_of(arr):
    try:
        devs = arr.devices()
        if len(devs) == 1:
            return next(iter(devs))
    except Exception:
        pass
    return None


def device_join_indices(left_table, right_table, left_key, right_key,
                        left_cache=None, right_cache=None, how: str = "inner",
                        left_replicas=None, right_replicas=None):
    """Probe on device. Returns (side, hit, bidx):

    - side == "right_build": hit/bidx are per LEFT row (bidx indexes right)
    - side == "left_build": hit/bidx are per RIGHT row (bidx indexes left)
    or None when ineligible (non-integer keys, duplicate build keys, ...).

    When a side carries mesh replicas (replicate_join_key), the copy living on
    the OTHER side's device is swapped in, keeping the probe device-local.
    """
    ln, rn = len(left_table), len(right_table)
    if ln == 0 or rn == 0:
        return None
    lk = _stage_key(left_table, left_key, left_cache)
    if lk is None:
        return None
    lv, lm = lk
    rk = None
    if right_replicas:
        # replica hit: skip staging the build side entirely — its existence
        # already proves the key passed the device-eligibility checks
        d = _device_of(lv)
        if d is not None and d in right_replicas:
            rk = right_replicas[d]
    if rk is None:
        rk = _stage_key(right_table, right_key, right_cache)
        if rk is None:
            return None
        if left_replicas:
            d = _device_of(rk[0])
            if d is not None and d in left_replicas:
                lv, lm = left_replicas[d]
    rv, rm = rk
    if lv.dtype != rv.dtype:
        return None
    # try build=right first (probe order == host output order)
    hit, bidx, dup = _probe_kernel(rv, rm, lv, lm)
    if not bool(dup):
        hit = np.asarray(hit)[:ln]
        bidx = np.asarray(bidx)[:ln].astype(np.int64)
        return "right_build", hit, bidx
    if how != "inner":
        return None
    hit, bidx, dup = _probe_kernel(lv, lm, rv, rm)
    if bool(dup):
        return None  # N:M join: host
    hit = np.asarray(hit)[:rn]
    bidx = np.asarray(bidx)[:rn].astype(np.int64)
    return "left_build", hit, bidx
