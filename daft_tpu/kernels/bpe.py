"""Byte-pair-encoding tokenizer for `.str.tokenize_encode/decode`.

Role-equivalent to the reference's tokenize functions (src/daft-functions/src/tokenize/,
tiktoken-style ranks). Loads a tiktoken-format ranks file from a local path
("<base64 token> <rank>" per line); the built-in "bytes" vocabulary (each byte is its
own token) is always available so encode/decode roundtrips work without any external
vocabulary file (this image has no network egress to fetch published rank files).
"""

from __future__ import annotations

import base64
from typing import Dict, List, Tuple

_ENCODERS: Dict[str, "BpeEncoder"] = {}


class BpeEncoder:
    def __init__(self, ranks: Dict[bytes, int]):
        self.ranks = ranks
        self.decoder = {v: k for k, v in ranks.items()}

    def _bpe_merge(self, piece: bytes) -> List[int]:
        """Heap + linked-list merge: O(n log n) instead of the quadratic
        rescan-per-merge loop (each merge pushes at most two new candidate
        pairs; stale heap entries are skipped by checking the stored pair
        against the list's current tokens). Merge ORDER matches the old
        loop: lowest rank first, leftmost on ties."""
        import heapq

        n = len(piece)
        if n == 0:
            return []
        parts: List[bytes] = [piece[i:i + 1] for i in range(n)]
        nxt = list(range(1, n)) + [-1]
        prv = [-1] + list(range(n - 1))
        alive = [True] * n
        heap: List[tuple] = []

        def push(i: int) -> None:
            j = nxt[i]
            if j < 0:
                return
            r = self.ranks.get(parts[i] + parts[j])
            if r is not None:
                heapq.heappush(heap, (r, i, parts[i], parts[j]))

        for i in range(n - 1):
            push(i)
        while heap:
            _r, i, left, right = heapq.heappop(heap)
            if not alive[i] or parts[i] != left:
                continue  # stale: this slot already merged
            j = nxt[i]
            if j < 0 or parts[j] != right:
                continue  # stale: the right neighbor changed
            parts[i] = left + right
            alive[j] = False
            nxt[i] = nxt[j]
            if nxt[j] >= 0:
                prv[nxt[j]] = i
            if prv[i] >= 0:
                push(prv[i])
            push(i)
        out: List[int] = []
        i = 0
        while i >= 0:
            out.append(self.ranks[parts[i]])
            i = nxt[i]
        return out

    def encode(self, text: str) -> List[int]:
        return self._bpe_merge(text.encode("utf-8"))

    def decode(self, tokens: List[int]) -> str:
        return b"".join(self.decoder[t] for t in tokens).decode("utf-8", errors="replace")


def _bytes_encoder() -> BpeEncoder:
    return BpeEncoder({bytes([i]): i for i in range(256)})


def load_tiktoken_ranks(path: str) -> BpeEncoder:
    ranks: Dict[bytes, int] = {}
    with open(path, "rb") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            tok_b64, rank = line.split()
            ranks[base64.b64decode(tok_b64)] = int(rank)
    return BpeEncoder(ranks)


#: names that resolve to the built-in byte-level vocabulary
BUILTIN_VOCABS = ("bytes",)


def get_encoder(name_or_path: str) -> BpeEncoder:
    if name_or_path not in _ENCODERS:
        import os

        if name_or_path in BUILTIN_VOCABS:
            _ENCODERS[name_or_path] = _bytes_encoder()
        elif os.path.exists(name_or_path):
            _ENCODERS[name_or_path] = load_tiktoken_ranks(name_or_path)
        else:
            raise FileNotFoundError(
                f"tokenizer vocabulary {name_or_path!r} not found: pass a local "
                f"tiktoken-format ranks file path, or one of the builtins {BUILTIN_VOCABS} "
                f"(published rank files cannot be fetched in this environment)"
            )
    return _ENCODERS[name_or_path]
