"""Device (TPU/XLA) kernel layer: Arrow <-> jax staging and jit'd columnar kernels.

This is the TPU-native replacement for the reference's Rust kernel library
(src/daft-core/src/array/ops/, ~60 kernel files). Design principles:

- A device column is a pair of dense jax arrays: `values` (padded to a size bucket so
  XLA compiles once per bucket, not once per row count) and `valid` (bool mask).
  Nulls never use sentinel values in kernels; every kernel threads validity.
- Whole expression trees compile to ONE jitted function per (expr, schema, bucket)
  via `compile_projection` — XLA fuses the elementwise chain into a single kernel,
  the analog of the reference's fused `pipeline_instruction`.
- Aggregations are masked segment reductions (`jax.ops.segment_sum` family) with
  group codes computed host-side by dictionary encoding: the host does the O(groups)
  bookkeeping, the MXU/VPU does the O(rows) FLOPs. Static `num_segments` keeps
  shapes compile-time constant.
- Sorting uses `jax.lax.sort` on bit-transformed keys (total order incl. nulls).
- No data-dependent shapes anywhere: filters for aggregation stay as masks; explicit
  compaction happens host-side only when a materialized filtered table is required.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

import jax
import jax.numpy as jnp

from ..datatypes import DataType, TypeKind

# Pad row counts up to one of these buckets (TPU lane width friendly: multiples of
# 8*128). Each bucket compiles once; growth factor 2 bounds waste at 2x.
_MIN_BUCKET = 1024


def size_bucket(n: int) -> int:
    b = _MIN_BUCKET
    while b < n:
        b <<= 1
    return b


_JNP_DTYPES = {
    TypeKind.BOOL: jnp.bool_,
    TypeKind.INT8: jnp.int8, TypeKind.INT16: jnp.int16,
    TypeKind.INT32: jnp.int32, TypeKind.INT64: jnp.int64,
    TypeKind.UINT8: jnp.uint8, TypeKind.UINT16: jnp.uint16,
    TypeKind.UINT32: jnp.uint32, TypeKind.UINT64: jnp.uint64,
    TypeKind.FLOAT32: jnp.float32, TypeKind.FLOAT64: jnp.float64,
}


def x64_enabled() -> bool:
    return bool(jax.config.jax_enable_x64)


def reduced_precision_ok() -> bool:
    """With x64 off (real TPUs), float64 data may run as float32 compute when
    the plan declares reduced precision (ExecutionConfig.device_reduced_precision,
    default on — the TPU-native norm; sums recover accuracy by combining
    per-partition partials in float64 on the host)."""
    from ..context import get_context

    return bool(get_context().execution_config.device_reduced_precision)


# 64-bit logical kinds and their 32-bit compute stand-ins when x64 is off.
# int64/uint64 narrow losslessly (range-checked at stage time); float64 is
# reduced-precision (gated by config); epoch-based temporals cannot fit 32
# bits and stay on the host path.
_NARROW_64 = {TypeKind.INT64: jnp.int32, TypeKind.UINT64: jnp.uint32,
              TypeKind.FLOAT64: jnp.float32}
_EPOCH_KINDS = {TypeKind.TIMESTAMP, TypeKind.DURATION, TypeKind.TIME}


def is_device_dtype(dt: DataType) -> bool:
    """Device-representable under the CURRENT x64 mode. With x64 off (real
    TPUs), int64/uint64 are eligible via lossless int32 narrowing (verified
    per-column at stage time), float64 via reduced-precision float32 compute
    (config-gated), and epoch temporals are host-only."""
    if dt.kind in _EPOCH_KINDS:
        return x64_enabled()
    if dt.kind == TypeKind.FLOAT64:
        return x64_enabled() or reduced_precision_ok()
    if dt.kind in (TypeKind.INT64, TypeKind.UINT64):
        return True
    if dt.kind in _JNP_DTYPES:
        return True
    if dt.kind == TypeKind.DATE:
        return True
    if dt.kind in (TypeKind.EMBEDDING, TypeKind.FIXED_SHAPE_TENSOR, TypeKind.FIXED_SHAPE_IMAGE):
        return is_device_dtype(dt.params[0]) if dt.kind != TypeKind.FIXED_SHAPE_IMAGE else True
    return False


def _physical_np(arr: pa.Array) -> np.ndarray:
    """Dense physical values of a primitive arrow array (nulls filled with 0)."""
    t = arr.type
    if pa.types.is_date32(t):
        arr = arr.cast(pa.int32())
    elif pa.types.is_timestamp(t) or pa.types.is_duration(t) or pa.types.is_time64(t):
        arr = arr.cast(pa.int64())
    elif pa.types.is_time32(t):
        arr = arr.cast(pa.int32())
    if arr.null_count:
        zero = pa.scalar(0, arr.type) if not pa.types.is_boolean(arr.type) else pa.scalar(False)
        arr = pc.fill_null(arr, zero)
    return np.asarray(arr)


class DeviceColumn:
    """values + validity on device, padded to `bucket` rows (valid[n:] == False).

    String columns stage as int32 DICTIONARY CODES against a SORTED
    per-partition dictionary (host-side pa.Array kept on `dictionary`):
    sorted codes are order-isomorphic to the strings, so equality AND
    ordering comparisons, sorts, and group codes all run on device over
    plain int lanes; decode happens at unstage (reference semantics:
    src/daft-core/src/array/ops/groups.rs dictionary grouping)."""

    __slots__ = ("values", "valid", "length", "dtype", "dictionary",
                 "_dict_list")

    def __init__(self, values: jax.Array, valid: jax.Array, length: int,
                 dtype: DataType, dictionary=None):
        self.values = values
        self.valid = valid
        self.length = length
        self.dtype = dtype
        self.dictionary = dictionary  # pa.Array of sorted uniques (strings)
        self._dict_list = None

    def dict_list(self):
        """Python-list view of the dictionary (cached — bisected per query
        for literal code bounds)."""
        if self._dict_list is None and self.dictionary is not None:
            self._dict_list = self.dictionary.to_pylist()
        return self._dict_list

    @property
    def bucket(self) -> int:
        return self.values.shape[0]


def stage_np(s, bucket: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray, int]:
    """Host-side staging core: (values [bucket,*trailing], valid [bucket], n).

    Shared by the single-device path (stage_series) and the mesh shuffle
    (parallel/mesh_exec.py) so padding/fixed-shape/validity logic lives once.
    """
    from ..series import Series

    assert isinstance(s, Series)
    dt = s.dtype
    if not is_device_dtype(dt):
        raise ValueError(f"{dt} is not device-representable")
    n = len(s)
    b = bucket or size_bucket(n)
    arr = s.to_arrow()
    if dt.kind in (TypeKind.EMBEDDING, TypeKind.FIXED_SHAPE_TENSOR, TypeKind.FIXED_SHAPE_IMAGE):
        shape = (dt.params[1],) if dt.kind == TypeKind.EMBEDDING else dt.tensor_shape
        size = int(np.prod(shape))
        child = arr.values.slice(arr.offset * size, n * size)
        vals = _physical_np(child).reshape((n,) + tuple(shape))
        vals = _narrow_staged(vals, dt)
        pad_shape = (b - n,) + tuple(shape)
        vals = np.concatenate([vals, np.zeros(pad_shape, vals.dtype)]) if b > n else vals
    else:
        vals = _narrow_staged(_physical_np(arr), dt)
        if b > n:
            vals = np.concatenate([vals, np.zeros(b - n, dtype=vals.dtype)])
    return vals, _staged_validity(arr, n, b), n


def _staged_validity(arr: pa.Array, n: int, b: int) -> np.ndarray:
    """Validity lane of a staged column, padding lanes False — shared by the
    numeric and string (dictionary-code) staging paths so null/padding
    semantics live once."""
    valid = np.zeros(b, dtype=bool)
    if n:
        valid[:n] = np.asarray(pc.is_valid(arr)) if arr.null_count else True
    return valid


_NARROW_NP = {TypeKind.INT64: np.int32, TypeKind.UINT64: np.uint32,
              TypeKind.FLOAT64: np.float32}


def _narrow_staged(vals: np.ndarray, dt: DataType) -> np.ndarray:
    """32-bit staging when x64 is off: ints narrow only when every value fits
    (lossless — raises otherwise so callers fall back to host); float64
    narrows to float32 (reduced precision, config-gated in is_device_dtype)."""
    inner = dt.params[0] if dt.kind in (TypeKind.EMBEDDING, TypeKind.FIXED_SHAPE_TENSOR) else dt
    if x64_enabled() or inner.kind not in _NARROW_NP:
        return vals
    target = _NARROW_NP[inner.kind]
    if vals.dtype.kind in "iu":
        info = np.iinfo(target)
        if len(vals) and (vals.min() < info.min or vals.max() > info.max):
            raise ValueError(f"{dt} values exceed int32 range; host path")
    return vals.astype(target, copy=False)


def stageable_dtype(dt: DataType) -> bool:
    """Device-stageable: device-representable numerics OR strings (which
    stage as dictionary codes)."""
    return is_device_dtype(dt) or dt.is_string()


def _stage_string_series(s, bucket: Optional[int]) -> DeviceColumn:
    """Stage a string Series as sorted-dictionary codes.

    The dictionary is sorted so code order == lexicographic order (UTF-8
    byte order and codepoint order coincide), which is also pyarrow's
    string ordering — host/device comparison and sort semantics agree."""
    n = len(s)
    b = bucket or size_bucket(n)
    arr = s.to_arrow()
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    uniq = pc.unique(arr.drop_null())
    uniq = uniq.take(pc.sort_indices(uniq))
    codes = pc.index_in(arr, value_set=uniq)  # null where arr is null
    vals = np.asarray(pc.fill_null(codes, 0), dtype=np.int32)
    if b > n:
        vals = np.concatenate([vals, np.zeros(b - n, dtype=np.int32)])
    valid = _staged_validity(arr, n, b)
    return DeviceColumn(jnp.asarray(vals), jnp.asarray(valid), n, s.dtype,
                        dictionary=uniq)


def stage_series(s, bucket: Optional[int] = None) -> DeviceColumn:
    """Stage a host Series onto the device (values + validity, padded)."""
    if s.dtype.is_string():
        return _stage_string_series(s, bucket)
    vals, valid, n = stage_np(s, bucket)
    return DeviceColumn(jnp.asarray(vals), jnp.asarray(valid), n, s.dtype)


def unstage(col: DeviceColumn):
    """Bring a DeviceColumn back to a host Series."""
    from ..series import Series

    vals = np.asarray(jax.device_get(col.values))[:col.length]
    valid = np.asarray(jax.device_get(col.valid))[:col.length]
    dt = col.dtype
    if col.dictionary is not None:
        uniq = col.dictionary
        if len(uniq) == 0:
            out = pa.nulls(col.length, pa.large_string())
        else:
            codes = np.clip(vals.astype(np.int64), 0, len(uniq) - 1)
            out = uniq.take(pa.array(codes))
            if not valid.all():
                out = pc.if_else(pa.array(valid), out,
                                 pa.nulls(col.length, out.type))
        return Series.from_arrow(out, "device", dt)
    if dt.kind in (TypeKind.EMBEDDING, TypeKind.FIXED_SHAPE_TENSOR, TypeKind.FIXED_SHAPE_IMAGE):
        shape = (dt.params[1],) if dt.kind == TypeKind.EMBEDDING else dt.tensor_shape
        size = int(np.prod(shape))
        flat = pa.array(vals.reshape(col.length, size).ravel())
        out = pa.FixedSizeListArray.from_arrays(flat, size or 1)
        if not valid.all():
            out = pc.if_else(pa.array(valid), out, pa.nulls(col.length, out.type))
        return Series.from_arrow(out, "device", dt)
    storage = dt.to_arrow()
    out = pa.array(vals)
    if out.type != storage:
        if pa.types.is_timestamp(storage) or pa.types.is_duration(storage) or pa.types.is_time64(storage):
            out = out.cast(pa.int64()).view(storage) if out.type.bit_width == 64 else out.cast(storage)
        elif pa.types.is_date32(storage):
            out = out.cast(pa.int32()).view(storage)
        else:
            out = out.cast(storage)
    if not valid.all():
        out = pc.if_else(pa.array(valid), out, pa.nulls(col.length, out.type))
    return Series.from_arrow(out, "device", dt)


# ---------------------------------------------------------------------------
# Expression -> jax compiler
# ---------------------------------------------------------------------------

_V = Tuple[jax.Array, jax.Array]  # (values, valid)


def _literal_to_physical(value, dt: DataType):
    """Convert a python literal to its device physical value (temporal -> epoch int)."""
    if dt.is_temporal():
        scalar = pa.scalar(value, type=dt.to_arrow())
        if dt.kind == TypeKind.DATE:
            return int(scalar.cast(pa.int32()).as_py())
        return int(scalar.value)
    return value


def _jdt(dt: DataType):
    """COMPUTE dtype for a logical dtype under the current x64 mode: 64-bit
    logical types narrow to their 32-bit stand-ins when x64 is off."""
    if not x64_enabled() and dt.kind in _NARROW_64:
        return _NARROW_64[dt.kind]
    if dt.kind in _JNP_DTYPES:
        return _JNP_DTYPES[dt.kind]
    if dt.kind == TypeKind.DATE:
        return jnp.int32
    if dt.kind in _EPOCH_KINDS:
        if not x64_enabled():
            raise ValueError(f"{dt} needs 64-bit epochs; host path with x64 off")
        return jnp.int64
    raise ValueError(f"{dt} has no device dtype")


def _wf():
    """Widest float compute dtype in the current mode."""
    return jnp.float64 if x64_enabled() else jnp.float32


def _literal_fits_device(lit) -> bool:
    """A literal is device-usable if its dtype has a compute dtype and, for
    int literals narrowing to 32-bit (x64 off), the value fits."""
    if lit.value is None or lit.dtype.is_null():
        return True
    if not is_device_dtype(lit.dtype):
        return False
    try:
        jd = _jdt(lit.dtype)
    except ValueError:
        return False
    if isinstance(lit.value, int) and not isinstance(lit.value, bool) \
            and jnp.issubdtype(jd, jnp.integer):
        info = jnp.iinfo(jd)
        return info.min <= lit.value <= info.max
    return True


_CMP_OPS = ("==", "!=", "<", "<=", ">", ">=")
_CMP_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "==": "==", "!=": "!="}
_CMP_FNS = {
    "==": lambda a, b: a == b, "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
}


def _plain_column(node, schema, pred) -> Optional[str]:
    """Column name when `node` is a bare Column (through Aliases) whose
    schema dtype satisfies `pred` — shared by the string-dictionary and
    f64-sort-lane paths so 'what counts as a plain column' lives once."""
    from ..expressions import Alias, Column

    while isinstance(node, Alias):
        node = node.child
    if isinstance(node, Column):
        try:
            if pred(schema[node.cname].dtype):
                return node.cname
        except KeyError:
            return None
    return None


def _plain_string_column(node, schema) -> Optional[str]:
    """Bare string Column (through Aliases) — the only string-VALUED shape
    the device supports (codes decode at unstage against that column's
    dictionary)."""
    return _plain_column(node, schema, lambda dt: dt.is_string())


def _plain_epoch_column(node, schema) -> Optional[str]:
    """Bare timestamp/duration/time Column (through Aliases) — 64-bit epoch
    kinds that cannot narrow to int32 but CAN compare/sort exactly via
    order-preserving (hi, lo) uint32 lane splits in 32-bit mode."""
    return _plain_column(node, schema, lambda dt: dt.kind in _EPOCH_KINDS)


def _epoch_lane_side(node, schema):
    """(ident, dtype, side_node_or_None) when `node` is an epoch-typed
    expression whose value can ride host-evaluated (hi, lo) lane pairs:
    a plain Column (ident = colname, shares the column-lane cache;
    side_node None) or ANY computed epoch expression — timestamp
    arithmetic, date truncation — which evaluates once on host in exact
    int64 and splits lanes from the result (ident = expression key)."""
    cname = _plain_epoch_column(node, schema)
    if cname is not None:
        return cname, schema[cname].dtype, None
    try:
        dt = node.to_field(schema).dtype
    except Exception:
        return None
    if dt.kind not in _EPOCH_KINDS:
        return None
    return f"\x00epochexpr\x00{node._key()}", dt, node


def _epoch_cmp_shape(node, schema):
    """(lspec, rspec, op) when `node` is a comparison whose sides are epoch
    lane sides and/or literals (at least one lane side) — compiled in
    32-bit mode as a two-lane unsigned comparison over split epoch bits;
    in x64 mode the generic int64 path handles epochs already. Each spec is
    ("lane", ident, dtype, side_node_or_None) or ("lit", lit_node).
    Lane-vs-lane requires identical dtypes (same epoch kind/unit/tz): the
    raw int64 physicals of different units are not comparable."""
    from ..expressions import BinaryOp, Literal

    if not (isinstance(node, BinaryOp) and node.op in _CMP_OPS):
        return None

    def spec(n):
        if isinstance(n, Literal):
            return ("lit", n)
        side = _epoch_lane_side(n, schema)
        if side is None:
            return None
        return ("lane", *side)

    ls, rs = spec(node.left), spec(node.right)
    if ls is None or rs is None:
        return None
    if ls[0] == "lit" and rs[0] == "lit":
        return None
    if ls[0] == "lane" and rs[0] == "lane" and ls[2] != rs[2]:
        return None
    # a literal compares against the lane side's dtype; reject non-epoch
    # literal-vs-lane pairings where conversion has no target
    return ls, rs, node.op


def _epoch_lane_keys(ident: str) -> Tuple[str, str]:
    return (f"__epochlane__\x00{ident}\x00hi",
            f"__epochlane__\x00{ident}\x00lo")


def _epoch_lit_keys(ident: str, node_key) -> Tuple[str, str]:
    base = f"__epochlit__\x00{ident}\x00{node_key}"
    return base + "\x00hi", base + "\x00lo"


def _two_lane_cmp(op: str, hi, lo, rhi, rlo):
    """Elementwise comparison of (hi, lo) uint32 lane pairs under the
    order-preserving epoch bit encoding (unsigned lexicographic)."""
    eq_hi = hi == rhi
    if op == "==":
        return eq_hi & (lo == rlo)
    if op == "!=":
        return ~(eq_hi & (lo == rlo))
    if op == "<":
        return (hi < rhi) | (eq_hi & (lo < rlo))
    if op == "<=":
        return (hi < rhi) | (eq_hi & (lo <= rlo))
    if op == ">":
        return (hi > rhi) | (eq_hi & (lo > rlo))
    return (hi > rhi) | (eq_hi & (lo >= rlo))  # ">="


def _epoch_bits_np(vals_i64: np.ndarray) -> np.ndarray:
    """Order-preserving uint64 view of int64 epochs (two's-complement ->
    unsigned total order via sign-bit flip)."""
    return vals_i64.astype(np.int64).view(np.uint64) ^ np.uint64(1 << 63)


def _eval_lane_series(table, node):
    """Host-evaluate a lane-staged sort key expression -> Series (length
    broadcast), or None when evaluation fails / yields python storage —
    the caller then declines to the host sort."""
    from ..expressions import Column

    try:
        if isinstance(node, Column):
            s = table.get_column(node.cname)
        else:
            from ..table import _broadcast_series

            s = _broadcast_series(node.evaluate(table), len(table))
    except Exception:
        return None
    if s.is_python():
        return None
    return s


def _peel_alias(node):
    from ..expressions import Alias

    while isinstance(node, Alias):
        node = node.child
    return node


def _stage_epoch_expr_lanes(table, node, bucket: int,
                            stage_cache: Optional[dict]):
    """Lane staging for ANY epoch-typed sort key expression (r4 verdict
    item 6): plain (possibly aliased) columns reuse the shared column-lane
    cache entry; computed epoch expressions (timestamp arithmetic) evaluate
    once on host — exact int64 — and split lanes from the result. UDF-
    containing keys never cache (Expression._memoizable rationale)."""
    from ..expressions import Column

    node = _peel_alias(node)
    if isinstance(node, Column):
        return _stage_epoch_lanes(table, node.cname, bucket, stage_cache)
    cacheable = stage_cache is not None and node._memoizable()
    key = ("__epochlanes__", node._key(), bucket)
    cached = stage_cache.get(key) if cacheable else None
    if cached is not None:
        return cached
    s = _eval_lane_series(table, node)
    if s is None:
        return None
    out = _epoch_lanes_of_series(s, bucket)
    if cacheable:
        stage_cache[key] = out
    return out


def _epoch_lanes_of_series(s, bucket: int):
    n = len(s)
    arr = s.to_arrow()
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    vals = _physical_np(arr).astype(np.int64)
    bits = _epoch_bits_np(vals)
    if bucket > n:
        bits = np.concatenate([bits, np.zeros(bucket - n, dtype=np.uint64)])
    hi = (bits >> np.uint64(32)).astype(np.uint32)
    lo = (bits & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return (jnp.asarray(hi), jnp.asarray(lo),
            jnp.asarray(_staged_validity(arr, n, bucket)))


def _stage_epoch_lanes(table, cname: str, bucket: int,
                       stage_cache: Optional[dict]):
    """(hi u32, lo u32, valid) exact lanes of an epoch column for 32-bit
    mode comparisons and sorts; cached with the partition."""
    key = ("__epochlanes__", cname, bucket)
    cached = stage_cache.get(key) if stage_cache is not None else None
    if cached is not None:
        return cached
    out = _epoch_lanes_of_series(table.get_column(cname), bucket)
    if stage_cache is not None:
        stage_cache[key] = out
    return out


def collect_epoch_cmps(nodes, schema):
    """Every epoch-comparison shape in the trees -> [(lspec, rspec, op)]."""
    from ..expressions import BinaryOp

    out = []

    def walk(n):
        if isinstance(n, BinaryOp):
            shape = _epoch_cmp_shape(n, schema)
            if shape is not None:
                out.append(shape)
                return  # the whole subtree rides lanes; nothing below stages
        for c in n.children():
            walk(c)

    for nd in nodes:
        walk(nd)
    return out


def epoch_cmp_env(cmps, schema, table, bucket: int,
                  stage_cache: Optional[dict], env: dict) -> Optional[dict]:
    """Merge epoch-comparison support into `env` (32-bit mode): each lane
    side's (hi, lo) pair — plain columns through the shared column-lane
    cache, computed sides host-evaluated once in exact int64 — and each
    literal's split bits keyed against its lane side. `cmps` is the list
    from ONE collect_epoch_cmps walk. Returns the (possibly unchanged)
    env, or None when a literal cannot convert or a computed side fails
    host evaluation."""
    if not cmps:
        return env
    merged = dict(env)
    for lspec, rspec, _op in cmps:
        lane_specs = [s for s in (lspec, rspec) if s[0] == "lane"]
        for _tag, ident, _dt, side_node in lane_specs:
            hi_k, lo_k = _epoch_lane_keys(ident)
            if hi_k in merged:
                continue
            if side_node is None:
                lanes = _stage_epoch_lanes(table, ident, bucket, stage_cache)
            else:
                lanes = _stage_epoch_expr_lanes(table, side_node, bucket,
                                                stage_cache)
            if lanes is None:
                return None
            hi, lo, valid = lanes
            merged[hi_k] = (hi, valid)
            merged[lo_k] = (lo, valid)
        lit = lspec[1] if lspec[0] == "lit" else (
            rspec[1] if rspec[0] == "lit" else None)
        if lit is None:
            continue
        _tag, ident, lane_dt, _sn = lane_specs[0]
        lhik, llok = _epoch_lit_keys(ident, lit._key())
        if lhik in merged or lit.value is None:
            continue
        try:
            epoch = _literal_to_physical(lit.value, lane_dt)
        except (ValueError, TypeError, KeyError):
            return None
        bits = int(_epoch_bits_np(np.array([epoch]))[0])
        merged[lhik] = jnp.uint32(bits >> 32)
        merged[llok] = jnp.uint32(bits & 0xFFFFFFFF)
    return merged


def epoch_cmps_for(nodes, schema):
    """ONE walk: the epoch-comparison shapes of `nodes` (empty under x64,
    where the generic int64 path applies)."""
    if x64_enabled():
        return []
    return collect_epoch_cmps(nodes, schema)


def device_required_columns(nodes, schema) -> set:
    """Columns that must stage NORMALLY on device: the plain required-column
    union, minus subtrees that ride host-evaluated epoch lane pairs (their
    inputs never reach the device; staging an epoch column normally would
    fail since 64-bit epochs cannot narrow to int32). A column referenced
    both inside a lane compare and elsewhere still stages."""
    from ..expressions import BinaryOp, Column

    out: set = set()
    in32 = not x64_enabled()

    def walk(n):
        if in32 and isinstance(n, BinaryOp) \
                and _epoch_cmp_shape(n, schema) is not None:
            return
        if isinstance(n, Column):
            out.add(n.cname)
        for c in n.children():
            walk(c)

    for nd in nodes:
        walk(nd)
    return out


def _string_cmp_shape(node, schema):
    """(colname, literal_value, flipped) when `node` is a comparison between
    a string Column and a string Literal (either side); else None. These
    compile to dictionary-code comparisons with the literal's code bounds
    injected per-partition at staging time."""
    from ..expressions import BinaryOp, Literal

    if not (isinstance(node, BinaryOp) and node.op in _CMP_OPS):
        return None

    def lit_str(n):
        return (isinstance(n, Literal)
                and (n.value is None or isinstance(n.value, str))
                and (n.dtype.is_string() or n.dtype.is_null()))

    lcol = _plain_string_column(node.left, schema)
    rcol = _plain_string_column(node.right, schema)
    if lcol is not None and lit_str(node.right):
        return lcol, node.right.value, False
    if rcol is not None and lit_str(node.left):
        return rcol, node.left.value, True
    return None


# LUT-evaluable predicate functions: the per-partition dictionary feeds the
# REGISTERED host implementation, so parity is by construction — including
# regex-backed like/ilike/match, which the device could never run itself
_STR_PRED_FNS = ("utf8.contains", "utf8.startswith", "utf8.endswith",
                 "utf8.like", "utf8.ilike", "utf8.match")


def _string_lut_shape(node, schema):
    """(colname, kind, payload, node_key) for predicates evaluable on the
    per-partition DICTIONARY instead of the rows: utf8.contains/startswith/
    endswith with a literal pattern, and is_in over string literals. The
    host computes the predicate over the O(unique) dictionary values with
    the SAME pyarrow kernels the host path uses (exact parity), producing a
    bool lookup table the device gathers by code — O(rows) work stays on
    the accelerator, O(unique) bookkeeping on the host (the division of
    labor SURVEY §7 prescribes)."""
    from ..expressions import Function, IsIn, Literal

    if isinstance(node, Function) and node.fname in _STR_PRED_FNS:
        if len(node.args) != 2 or node.kwargs:
            return None
        colname = _plain_string_column(node.args[0], schema)
        pat = node.args[1]
        if (colname is None or not isinstance(pat, Literal)
                or not isinstance(pat.value, str)):
            return None
        return colname, node.fname, pat.value, node._key()
    if isinstance(node, IsIn):
        colname = _plain_string_column(node.child, schema)
        items = node.items
        if (colname is None or not isinstance(items, Literal)
                or not isinstance(items.value, (list, tuple))):
            return None
        vals = [v for v in items.value if v is not None]
        if not all(isinstance(v, str) for v in vals):
            return None
        return colname, "is_in", tuple(vals), node._key()
    return None


def _strlut_env_key(node_key) -> str:
    return f"__strlut__\x00{node_key}"


# per-row (row-local) string functions: a predicate built from these over ONE
# string column depends only on that row's value, so it can evaluate over the
# partition dictionary instead of the rows (utf8.tokenize_* excluded: list-
# valued results have no boolean-LUT use and pull in tokenizer state)
_ROWLOCAL_STR_FNS = frozenset(
    f"utf8.{n}" for n in (
        "capitalize", "concat", "contains", "count_matches", "endswith",
        "extract", "find", "ilike", "left", "length", "length_bytes",
        "like", "lower", "lpad", "lstrip", "match", "normalize", "repeat",
        "replace", "reverse", "right", "rpad", "rstrip", "startswith",
        "substr", "upper",
    ))


def _string_dict_pred_shape(node, schema):
    """(colname, node, node_key) when `node` is a BOOLEAN-valued, row-local
    expression whose only column input is ONE plain string column — e.g.
    `upper(s) == "X"`, `strip(s).startswith(p)`, `length(s) > 3`,
    `(s + "-suffix").is_in([...])`. Each row's result depends only on that
    row's string value, so the host evaluates the WHOLE predicate over the
    O(unique) dictionary (+ one null slot for exact null semantics) with
    the registered host kernels, and the device gathers by code —
    generalizing the fixed contains/startswith/endswith LUT shapes to
    arbitrary predicate trees over string transforms. Reference semantics:
    fully general utf8 kernels, src/daft-core/src/array/ops/utf8.rs."""
    try:
        if not node.to_field(schema).dtype.is_boolean():
            return None
    except (ValueError, KeyError):
        return None
    colname = _single_string_col_rowlocal(node, schema)
    if colname is None:
        return None
    return colname, node, node._key()


def _single_string_col_rowlocal(node, schema) -> Optional[str]:
    """The one plain string column `node` row-locally depends on, or None.
    Row-local: every applied operation is per-row (whitelisted utf8 fns,
    compares, choices, casts), so a row's result depends only on that
    row's string value — the property that lets the whole subtree evaluate
    over the O(unique) dictionary instead of the rows. Shared by the
    boolean dictionary-predicate shape and the transformed group-key
    lane."""
    from ..expressions import (
        Alias, Between, BinaryOp, Cast, Column, FillNull, IfElse, IsIn,
        IsNull, Literal, Not, Function,
    )

    cols: set = set()

    def rowlocal(n):
        if isinstance(n, (Literal, Column)):
            if isinstance(n, Column):
                cols.add(n.cname)
            return True
        if isinstance(n, (Alias, Not, IsNull, Cast, Between, FillNull,
                          IfElse, BinaryOp)):
            return all(rowlocal(c) for c in n.children())
        if isinstance(n, IsIn):
            return isinstance(n.items, Literal) and rowlocal(n.child)
        if isinstance(n, Function):
            # kwargs are static python config (regex=, index=), never columns
            if n.fname not in _ROWLOCAL_STR_FNS:
                return False
            return all(rowlocal(c) for c in n.args)
        return False

    if not rowlocal(node):
        return None
    if len(cols) != 1:
        return None
    return _plain_string_column_named(next(iter(cols)), schema)


def _plain_string_column_named(colname, schema):
    try:
        return colname if schema[colname].dtype.is_string() else None
    except KeyError:
        return None


def _strdictpred_env_keys(node_key) -> Tuple[str, str, str]:
    base = f"__strdictpred__\x00{node_key}"
    return base + "\x00vals", base + "\x00valid", base + "\x00nullslot"


def _string_dict_value_shape(node, schema):
    """(colname, node, node_key) when `node` is a row-local COMPUTED
    expression of ONE plain string column used as a VALUE (group/distinct
    key, sort key, projection output): `upper(s)`, `s.substr(0, 2)`,
    fill_null chains. Equal source strings produce equal results, so the
    value set computes over the dictionary (+ null slot) and each row's
    dense sorted-order id is a gather. Plain columns are excluded — the
    existing dictionary-code path already handles them without the host
    evaluation."""
    if _plain_string_column(node, schema) is not None:
        return None
    colname = _single_string_col_rowlocal(node, schema)
    if colname is None:
        return None
    return colname, node, node._key()


def _string_value_applies(node, schema):
    """The transformed-string VALUE shape at a compile-claim point: the
    node must be string-VALUED, not a plain column (native codes path) and
    not a choice over plain columns/literals (joint-dictionary path) —
    precedence must match _compile_node's dispatch order."""
    try:
        if not node.to_field(schema).dtype.is_string():
            return None
    except (ValueError, KeyError):
        return None
    if _string_choice_shape(node, schema) is not None:
        return None
    return _string_dict_value_shape(node, schema)


def _int_transform_applies(node, schema):
    """(colname, node, node_key) when `node` is an INTEGER-valued row-local
    expression of ONE string column — `length(s)`, `find(s, p)`,
    `count_matches` — whose values (not recoded ids) gather by source code.
    A bare Function is required at the root: integer ARITHMETIC above the
    transform composes on device through the generic compiler once the
    transform itself is claimed."""
    from ..expressions import Function

    if not isinstance(node, Function):
        return None
    try:
        if not node.to_field(schema).dtype.is_integer():
            return None
    except (ValueError, KeyError):
        return None
    colname = _single_string_col_rowlocal(node, schema)
    if colname is None:
        return None
    return colname, node, node._key()


def _inttrans_env_keys(node_key) -> Tuple[str, str]:
    base = f"__inttransval__\x00{node_key}"
    return base + "\x00vals", base + "\x00valid"


def dict_int_transform_lane(table, shape, bucket: int,
                            stage_cache: Optional[dict]):
    """(vals, valid) integer lanes for an int-valued string transform:
    host evaluates over the dictionary + null slot (shared
    _eval_over_dictionary), the device gathers VALUES by source code. In
    32-bit mode the dictionary values are range-checked exactly on host —
    int64 results that cannot narrow to int32 decline (the wrap-safety
    rule applied at O(unique) cost instead of a device reduction).
    Returns None -> caller declines."""
    colname, node, node_key = shape
    cache_key = ("__inttranslane__", node_key, bucket, x64_enabled())
    cached = stage_cache.get(cache_key) if stage_cache is not None else None
    if cached is not None:
        return cached
    staged = stage_table_columns(table, [colname], bucket, stage_cache)
    if staged is None:
        return None
    _env, dcs = staged
    dc = dcs.get(colname)
    if dc is None or dc.dictionary is None:
        return None
    uniq = dc.dictionary
    arr = _eval_over_dictionary(colname, node, uniq)
    if arr is None:
        return None
    vals_np = np.asarray(pc.fill_null(arr, 0)).astype(np.int64)
    tvalid = np.asarray(pc.is_valid(arr), dtype=bool)
    if not x64_enabled():
        live = vals_np[tvalid]
        if live.size and (live.min() < _INT32_LO or live.max() > _INT32_HI):
            return None
        vals_np = vals_np.astype(np.int32)
    u = len(uniq)
    idx = jnp.where(dc.valid, dc.values, u).astype(jnp.int32)
    vals = jnp.asarray(vals_np)[idx]
    valid = jnp.asarray(tvalid)[idx]
    out = (vals, valid)
    if stage_cache is not None:
        stage_cache[cache_key] = out
    return out


def _strtransval_env_keys(node_key) -> Tuple[str, str]:
    base = f"__strtransval__\x00{node_key}"
    return base + "\x00vals", base + "\x00valid"


def _stroutdict_aux_key(node_key):
    return ("__stroutdict__", node_key)


def _transform_cmp_shape(node, schema):
    """(lside, rside, op) for a comparison whose sides are string-valued
    and column-backed over TWO DIFFERENT columns with at least one side a
    row-local TRANSFORM — `upper(s1) == s2`, `lstrip(a) < rstrip(b)`.
    Plain-vs-plain belongs to the col-vs-col joint-group machinery and
    single-column trees (incl. vs-literal) to the dictionary predicate, so
    this shape claims exactly the residual. Each side is
    ("col", colname, None) or ("trans", colname, side_node); the sides
    recode through a PAIRWISE sorted joint dictionary (transform side: its
    transformed dictionary) and compare as ints — sorted joint codes are
    order-isomorphic, so inequalities hold too."""
    from ..expressions import BinaryOp

    if not (isinstance(node, BinaryOp) and node.op in _CMP_OPS):
        return None

    def side(n):
        c = _plain_string_column(n, schema)
        if c is not None:
            return ("col", c, None)
        vs = _string_value_applies(n, schema)
        if vs is not None:
            return ("trans", vs[0], n)
        return None

    ls, rs = side(node.left), side(node.right)
    if ls is None or rs is None:
        return None
    if ls[0] == "col" and rs[0] == "col":
        return None  # the existing col-vs-col joint group owns this
    if ls[1] == rs[1]:
        return None  # one column: the dictionary predicate owns this
    return ls, rs, node.op


def _transcmp_env_keys(node_key) -> Tuple[str, str]:
    base = f"__transcmp__\x00{node_key}"
    return base + "\x00lremap", base + "\x00rremap"


def transform_cmp_env(nodes, schema, table, bucket: int,
                      stage_cache: Optional[dict], dcs, env: dict,
                      aux: dict) -> Optional[dict]:
    """Merge pairwise joint-dictionary remaps for every cross-column
    transform compare. Runs AFTER string_transform_env: a transform side's
    lane and transformed dictionary are already staged (env/aux); a plain
    side's codes and dictionary are in dcs. Returns env (possibly
    unchanged) or None -> decline to host."""
    from ..expressions import BinaryOp

    merged = env

    def side_dict(s):
        kind, colname, n = s
        if kind == "col":
            dc = dcs.get(colname)
            return None if dc is None or dc.dictionary is None \
                else dc.dictionary
        return aux.get(_stroutdict_aux_key(n._key()))

    def walk(n):
        nonlocal merged
        if isinstance(n, BinaryOp):
            shape = _transform_cmp_shape(n, schema)
            if shape is not None:
                ls, rs, _op = shape
                lk, rk = _transcmp_env_keys(n._key())
                if lk in merged:
                    return True
                cache_key = ("__transcmp__", n._key(), bucket)
                cached = (stage_cache.get(cache_key)
                          if stage_cache is not None else None)
                if cached is None:
                    ld, rd = side_dict(ls), side_dict(rs)
                    if ld is None or rd is None:
                        return False
                    joint = pc.unique(pa.concat_arrays(
                        [ld.cast(pa.large_string()),
                         rd.cast(pa.large_string())]))
                    joint = joint.take(pc.sort_indices(joint))
                    cached = (joint_remap(ld, joint), joint_remap(rd, joint))
                    if stage_cache is not None:
                        stage_cache[cache_key] = cached
                if merged is env:
                    merged = dict(env)
                merged[lk], merged[rk] = cached
                return True
        return all(walk(c) for c in n.children())

    for nd in nodes:
        if not walk(nd):
            return None
    return merged


def string_transform_env(nodes, schema, table, bucket: int,
                         stage_cache: Optional[dict], env: dict,
                         aux: dict) -> Optional[dict]:
    """Stage transformed-string VALUE lanes (sorted-order ids + validity)
    into env and their transformed dictionaries into aux for decode at
    unstage. Walks each tree; predicate-LUT subtrees are skipped (their
    env entries come from string_lut_env), and a claimed value subtree is
    not descended (its children evaluate on host over the dictionary).
    Returns env (possibly unchanged), or None when a lane cannot stage —
    the caller declines to the host path."""
    merged = env

    def walk(n):
        nonlocal merged
        if (_string_lut_shape(n, schema) is not None
                or _string_dict_pred_applies(n, schema) is not None):
            return True  # the LUT env owns this subtree
        vs = _string_value_applies(n, schema)
        if vs is not None:
            lane = dict_transform_lane(table, vs, bucket, stage_cache)
            if lane is None:
                return False
            vals, valid, tuniq = lane
            if merged is env:
                merged = dict(env)
            vk, mk = _strtransval_env_keys(vs[2])
            merged[vk] = vals
            merged[mk] = valid
            aux[_stroutdict_aux_key(vs[2])] = tuniq
            return True
        ivs = _int_transform_applies(n, schema)
        if ivs is not None:
            lane = dict_int_transform_lane(table, ivs, bucket, stage_cache)
            if lane is None:
                return False
            if merged is env:
                merged = dict(env)
            vk, mk = _inttrans_env_keys(ivs[2])
            merged[vk], merged[mk] = lane
            return True
        return all(walk(c) for c in n.children())

    for nd in nodes:
        if not walk(nd):
            return None
    return merged


def dict_transform_lane(table, shape, bucket: int,
                        stage_cache: Optional[dict]):
    """(vals, valid, transformed_dictionary) for a transformed-string
    expression: host evaluates the transform over the dictionary values +
    one null slot (exact null semantics — a fill_null can turn the null
    row into a real group), recodes the results through their SORTED
    distinct values (order-preserving: equal results — 'a' and 'A' under
    lower() — share an id, and id order == value order, so the same lane
    serves group identity AND sorts), and the device gathers ids by source
    code. O(unique log unique) host work, O(rows) on device. The
    transformed dictionary decodes ids back to values for projection
    outputs. Returns None -> caller declines."""
    colname, node, node_key = shape
    cache_key = ("__dicttranslane__", node_key, bucket)
    cached = stage_cache.get(cache_key) if stage_cache is not None else None
    if cached is not None:
        return cached
    staged = stage_table_columns(table, [colname], bucket, stage_cache)
    if staged is None:
        return None
    _env, dcs = staged
    dc = dcs.get(colname)
    if dc is None or dc.dictionary is None:
        return None
    uniq = dc.dictionary
    arr = _eval_over_dictionary(colname, node, uniq)
    if arr is None:
        return None
    try:
        distinct = pc.unique(arr.drop_null())
        tuniq = distinct.take(pc.sort_indices(distinct))
        ids_arr = pc.index_in(arr, value_set=tuniq)  # null -> null id
    except Exception:
        return None
    ids = np.asarray(pc.fill_null(ids_arr, 0), dtype=np.int32)
    tvalid = np.asarray(pc.is_valid(ids_arr), dtype=bool)
    u = len(uniq)
    idx = jnp.where(dc.valid, dc.values, u).astype(jnp.int32)
    vals = jnp.asarray(ids)[idx]
    valid = jnp.asarray(tvalid)[idx]
    out = (vals, valid, tuniq)
    if stage_cache is not None:
        stage_cache[cache_key] = out
    return out


# ---------------------------------------------------------------------------
# Joint-dictionary string groups: col-vs-col compares + string if_else/
# fill_null. Per-column dictionary codes are incomparable across columns, so
# every interacting group of string columns (+ literals) merges into ONE
# sorted joint dictionary at staging time; each column gets a small remap
# array injected into env and the closures compare/select JOINT codes on
# device. Same technique as the cross-table join-key recoding
# (device_join._joint_remaps); reference semantics: fully general utf8
# kernels, src/daft-core/src/array/ops/{utf8.rs,if_else.rs}.
# ---------------------------------------------------------------------------


_CMP_OPS_NULLSAFE = _CMP_OPS + ("<=>",)


def _string_cmp_side(node, schema):
    """One side of a general string compare: ('col', name) for a plain
    string Column, ('choice', _StringChoice) for a string fill_null/if_else,
    ('lit', value) / ('null', None) for string/null literals; else None."""
    from ..expressions import Literal

    c = _plain_string_column(node, schema)
    if c is not None:
        return ("col", c)
    ch = _string_choice_shape(node, schema)
    if ch is not None:
        return ("choice", ch)
    if isinstance(node, Literal):
        if node.value is None:
            return ("null", None)
        if isinstance(node.value, str) and (node.dtype.is_string()
                                            or node.dtype.is_null()):
            return ("lit", node.value)
    return None


def _side_group(side):
    """(cols, lits) a compare side contributes to the joint group."""
    kind, v = side
    if kind == "col":
        return (v,), ()
    if kind == "choice":
        return v.cols, v.lits
    if kind == "lit":
        return (), (v,)
    return (), ()


def _string_colcol_shape(node, schema):
    """(lside, rside) when `node` is a string compare whose sides are plain
    columns, string choice shapes (fill_null/if_else), or literals — with at
    least one non-literal side (pure literal-vs-column compares take the
    cheaper per-column bisect path, _string_cmp_shape, tried first)."""
    from ..expressions import BinaryOp

    if not (isinstance(node, BinaryOp) and node.op in _CMP_OPS_NULLSAFE):
        return None
    try:
        ldt = node.left.to_field(schema).dtype
        rdt = node.right.to_field(schema).dtype
    except (ValueError, KeyError):
        return None
    if not ((ldt.is_string() or ldt.is_null())
            and (rdt.is_string() or rdt.is_null())):
        return None
    lside = _string_cmp_side(node.left, schema)
    rside = _string_cmp_side(node.right, schema)
    if lside is None or rside is None:
        return None
    if lside[0] in ("lit", "null") and rside[0] in ("lit", "null"):
        return None  # constant-folding territory, not worth a device shape
    return lside, rside


class _StringChoice:
    """Shape of a string-producing FillNull/IfElse over plain string columns
    and string literals: `operands` is [('col', name) | ('lit', value) |
    ('null', None)] in positional order (child, fill) / (if_true, if_false);
    `pred` is the IfElse predicate node (None for FillNull)."""

    __slots__ = ("kind", "pred", "operands", "cols", "lits")

    def __init__(self, kind, pred, operands):
        self.kind = kind
        self.pred = pred
        self.operands = operands
        self.cols = tuple(sorted({v for k, v in operands if k == "col"}))
        self.lits = tuple(sorted({v for k, v in operands if k == "lit"}))


def _string_choice_shape(node, schema):
    """_StringChoice for a string-typed FillNull/IfElse whose value operands
    are plain string columns / string literals / null literals; else None."""
    from ..expressions import FillNull, IfElse, Literal

    node = _peel_alias(node)
    if isinstance(node, FillNull):
        kind, pred, vals = "fill_null", None, (node.child, node.fill)
    elif isinstance(node, IfElse):
        kind, pred, vals = "if_else", node.pred, (node.if_true, node.if_false)
    else:
        return None
    try:
        if not node.to_field(schema).dtype.is_string():
            return None
    except (ValueError, KeyError):
        return None
    operands = []
    for v in vals:
        c = _plain_string_column(v, schema)
        if c is not None:
            operands.append(("col", c))
        elif isinstance(v, Literal) and v.value is None:
            operands.append(("null", None))
        elif (isinstance(v, Literal) and isinstance(v.value, str)
              and (v.dtype.is_string() or v.dtype.is_null())):
            operands.append(("lit", v.value))
        else:
            return None
    return _StringChoice(kind, pred, operands)


def string_output_dictionary(node, schema, dcs, aux):
    """THE dictionary a string-producing device output decodes through:
    the column's own dictionary for a bare passthrough, the joint-group
    dictionary for a fill_null/if_else result, None when neither resolves
    (caller declines/errs). Shared by the projection resolver and the
    grouped-agg resolver so the decode rule lives once."""
    cname = _plain_string_column(node, schema)
    src = dcs.get(cname) if cname else None
    if src is not None and src.dictionary is not None:
        return src.dictionary
    ch = _string_choice_shape(node, schema)
    if ch is not None:
        return aux.get(_joint_gkey(ch.cols, ch.lits))
    vs = _string_dict_value_shape(node, schema)
    if vs is not None:
        return aux.get(_stroutdict_aux_key(vs[2]))
    return None


def _cmp_union_group(lside, rside):
    """The ONE definition of a general compare's joint group (union of both
    sides) — group registration and closure compilation must agree on env
    keys byte-for-byte, so both call this."""
    lc, ll = _side_group(lside)
    rc, rl = _side_group(rside)
    return (tuple(sorted(set(lc) | set(rc))),
            tuple(sorted(set(ll) | set(rl))))


def _joint_group_of(node, schema):
    """(cols, lits) joint-dictionary group for a node, or None. A general
    string compare's group unions BOTH sides (a choice side's codes must be
    comparable with the other side's), EXCEPT when the cheap per-column
    literal-bisect shape handles the node — that path uses the column's own
    dictionary, no joint group needed."""
    if _string_cmp_shape(node, schema) is None:
        cc = _string_colcol_shape(node, schema)
        if cc is not None:
            return _cmp_union_group(*cc)
    ch = _string_choice_shape(node, schema)
    if ch is not None:
        return ch.cols, ch.lits
    return None


def joint_remap(dictionary, joint):
    """Device remap array taking one dictionary's codes into a sorted JOINT
    dictionary's code space, padded to a size bucket so the consuming gather
    compiles per bucket — shared by the in-table string groups here and the
    cross-table join-key recoding (device_join._joint_remaps)."""
    if len(dictionary) == 0:
        # all-null side: codes are all 0/masked; remap needs 1 lane
        arr = np.zeros(1, dtype=np.int32)
    else:
        arr = np.asarray(pc.index_in(dictionary.cast(pa.large_string()),
                                     value_set=joint), dtype=np.int32)
    b = size_bucket(len(arr))
    if b > len(arr):
        arr = np.concatenate([arr, np.zeros(b - len(arr), np.int32)])
    return jnp.asarray(arr)


def _joint_gkey(cols, lits) -> str:
    return "\x1f".join(cols) + "\x1e" + "\x1f".join(lits)


def _joint_map_key(gkey: str, col: str) -> str:
    return f"__joint__\x00{gkey}\x00map\x00{col}"


def _joint_lit_key(gkey: str, lit: str) -> str:
    return f"__joint__\x00{gkey}\x00lit\x00{lit}"


def _joint_operand_fn(kind, val, gkey):
    """env -> (joint codes, valid) closure for a col/lit/null operand of a
    joint-dictionary group."""
    if kind == "col":
        mk = _joint_map_key(gkey, val)

        def get(env, _c=val, _mk=mk):
            codes, m = env[_c]
            return env[_mk][codes], m
    elif kind == "lit":
        lk = _joint_lit_key(gkey, val)

        def get(env, _lk=lk):
            n = _env_nrows(env)
            return (jnp.full(n, env[_lk], dtype=jnp.int32),
                    jnp.ones(n, dtype=bool))
    else:  # null literal

        def get(env):
            n = _env_nrows(env)
            return (jnp.zeros(n, dtype=jnp.int32),
                    jnp.zeros(n, dtype=bool))
    return get


def _choice_code_fn(ch, gkey, schema):
    """env -> (joint codes, valid) closure for a string fill_null/if_else,
    emitting codes in the group keyed by `gkey` (the node's OWN group when
    it is a projection output; the enclosing compare's bigger group when
    nested as a compare side)."""
    a = _joint_operand_fn(*ch.operands[0], gkey)
    b = _joint_operand_fn(*ch.operands[1], gkey)
    if ch.kind == "fill_null":
        def run(env, _a=a, _b=b):
            av, am = _a(env)
            bv, bm = _b(env)
            return jnp.where(am, av, bv), am | bm

        return run
    p, _pdt = _compile_node(ch.pred, schema)

    def run(env, _p=p, _a=a, _b=b):
        pv, pm = _p(env)
        av, am = _a(env)
        bv, bm = _b(env)
        out = jnp.where(pv, av, bv)
        return out, pm & jnp.where(pv, am, bm)

    return run


def _side_code_fn(side, gkey, schema):
    """env -> (joint codes, valid) for one side of a general string compare."""
    kind, v = side
    if kind == "choice":
        return _choice_code_fn(v, gkey, schema)
    return _joint_operand_fn(kind, v, gkey)


def _shape_choice_preds(node, schema):
    """The choice-side PREDICATES of a matched joint shape — the only
    subtrees under it that can contain further string shapes (its string
    sides are owned by the shape itself)."""
    ch = _string_choice_shape(node, schema)
    if ch is not None:
        return [ch.pred] if ch.pred is not None else []
    cc = _string_colcol_shape(node, schema)
    preds = []
    if cc is not None:
        for kind, v in cc:
            if kind == "choice" and v.pred is not None:
                preds.append(v.pred)
    return preds


def collect_joint_groups(nodes, schema):
    """Every joint-dictionary group in the trees. A matched shape's string
    sides are not re-walked (a choice nested under a compare emits codes in
    the COMPARE's group; registering its standalone subset group too would
    build a joint dictionary nothing reads) — only choice predicates recurse."""
    out = []

    def walk(n):
        g = _joint_group_of(n, schema)
        if g is not None:
            out.append(g)
            for p in _shape_choice_preds(n, schema):
                walk(p)
            return
        for c in n.children():
            walk(c)

    for nd in nodes:
        walk(nd)
    return out


def string_joint_env(nodes, schema, dcs, env, aux: dict):
    """Merge per-group remap arrays + literal codes into `env`; record each
    group's joint dictionary (pa.Array) into `aux[gkey]` so string-producing
    nodes can decode at unstage. Returns env, or None when a needed
    dictionary is unavailable (caller falls back to host)."""
    groups = collect_joint_groups(nodes, schema)
    if not groups:
        return env
    merged = dict(env)
    for cols, lits in set(groups):
        gkey = _joint_gkey(cols, lits)
        if gkey in aux:
            continue
        parts = []
        for c in cols:
            dc = dcs.get(c)
            if dc is None or dc.dictionary is None:
                return None
            parts.append(dc.dictionary.cast(pa.large_string()))
        if lits:
            parts.append(pa.array(list(lits), pa.large_string()))
        joint = pc.unique(pa.concat_arrays(parts))
        joint = joint.take(pc.sort_indices(joint))
        for c in cols:
            merged[_joint_map_key(gkey, c)] = joint_remap(dcs[c].dictionary,
                                                          joint)
        for lit in lits:
            code = pc.index(joint, pa.scalar(lit, pa.large_string())).as_py()
            merged[_joint_lit_key(gkey, lit)] = jnp.int32(code)
        aux[gkey] = joint
    return merged


def _numeric_isin_items(node, schema):
    """Static per-compile device item values for a numeric/date IsIn, or
    None when ineligible. NaN items decline (arrow's is_in matches NaN,
    jnp equality does not)."""
    import math

    from ..expressions import IsIn, Literal

    if not isinstance(node, IsIn):
        return None
    items = node.items
    if not isinstance(items, Literal) or not isinstance(items.value,
                                                        (list, tuple)):
        return None
    try:
        child_dt = node.child.to_field(schema).dtype
    except (ValueError, KeyError):
        return None
    if not (child_dt.is_numeric() or child_dt.kind == TypeKind.DATE
            or child_dt.kind == TypeKind.BOOL):
        return None
    int_child = not child_dt.is_floating()
    out = []
    for v in items.value:
        if v is None:
            continue  # null items never match (host: pc.is_in + fill_null)
        if isinstance(v, float):
            if math.isnan(v):
                return None  # arrow's is_in matches NaN; jnp equality can't
            if int_child:
                # host unifies int-vs-float to float64 compares, whose
                # rounding the 32-bit device can't reproduce: decline
                return None
        try:
            out.append(_literal_to_physical(v, child_dt))
        except (ValueError, TypeError):
            return None
    if not x64_enabled():
        for v in out:
            if isinstance(v, int) and not (-2**31 <= v <= 2**31 - 1):
                return None
    return tuple(out)


def _string_dict_pred_applies(node, schema):
    """The general dictionary predicate shape, ONLY where no cheaper
    specific shape already handles the node — the precedence must match
    _compile_node's dispatch order exactly, or the env builder and the
    compiled closure would disagree about which path owns a node. Boolean
    connectives and plain pass-throughs are also excluded: each side below
    them gets its own best shape (a bisect compare beats an O(unique)
    dictionary evaluation on high-cardinality columns)."""
    from ..expressions import Alias, BinaryOp, Column, IsNull, Literal, Not

    if isinstance(node, (Alias, Column, Literal, Not)):
        return None
    if isinstance(node, IsNull) and \
            _plain_string_column(node.child, schema) is not None:
        # is_null over a plain column is a native validity-mask op on
        # device; the dictionary evaluation would only add host work
        return None
    if isinstance(node, BinaryOp):
        if node.op in ("&", "|", "^"):
            return None
        if _string_cmp_shape(node, schema) is not None:
            return None
        if _string_colcol_shape(node, schema) is not None:
            return None
        if _epoch_cmp_shape(node, schema) is not None:
            return None
    if _string_lut_shape(node, schema) is not None:
        return None
    return _string_dict_pred_shape(node, schema)


def collect_string_luts(nodes, schema):
    """Every LUT-predicate shape in the trees: the fixed single-function
    shapes, plus general dictionary predicates (tagged "hostpred"); a
    matched general predicate's subtree is skipped — its children evaluate
    on host over the dictionary, never separately on device."""
    out = []

    def walk(n):
        shape = _string_lut_shape(n, schema)
        if shape is not None:
            out.append(shape)
        else:
            gshape = _string_dict_pred_applies(n, schema)
            if gshape is not None:
                out.append((gshape[0], "hostpred", gshape[1], gshape[2]))
                return
        for c in n.children():
            walk(c)

    for nd in nodes:
        walk(nd)
    return out


def _eval_over_dictionary(colname: str, node, uniq):
    """Host-evaluate `node` over the dictionary values PLUS one null slot
    (index len(uniq)) — THE one definition of dictionary-level evaluation,
    shared by the boolean predicate LUT and the transformed group-key lane
    so their null semantics can never diverge. Returns the arrow result
    array of length len(uniq)+1, or None (caller declines to host)."""
    from ..table import Table

    try:
        with_null = pa.concat_arrays(
            [uniq, pa.array([None], type=uniq.type)])
        tbl = Table.from_arrow(pa.table({colname: with_null}))
        got = node.evaluate(tbl)
        arr = got.to_arrow()
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        if len(arr) == 1 and len(with_null) > 1:  # scalar broadcast
            arr = pa.concat_arrays([arr] * len(with_null))
        return arr
    except Exception:
        return None


def _merge_dict_pred(merged: dict, colname: str, node, node_key, dcs) -> bool:
    """Evaluate a general dictionary predicate over the column's dictionary
    values PLUS one null slot (exact null semantics: whatever the host path
    produces for a null input — is_null, fill_null chains — the gather
    produces identically), through the host evaluator itself so parity is
    by construction. False = decline to the host path."""
    vals_k, valid_k, null_k = _strdictpred_env_keys(node_key)
    if vals_k in merged:
        return True
    dc = dcs.get(colname)
    if dc is None or dc.dictionary is None:
        return False
    uniq = dc.dictionary
    arr = _eval_over_dictionary(colname, node, uniq)
    if arr is None:
        return False
    vals_np = np.asarray(pc.fill_null(arr, False), dtype=bool)
    valid_np = np.asarray(pc.is_valid(arr), dtype=bool)
    u1 = len(uniq) + 1
    b = size_bucket(u1)
    if b > u1:
        pad = np.zeros(b - u1, dtype=bool)
        vals_np = np.concatenate([vals_np, pad])
        valid_np = np.concatenate([valid_np, pad])
    merged[vals_k] = jnp.asarray(vals_np)
    merged[valid_k] = jnp.asarray(valid_np)
    merged[null_k] = jnp.int32(len(uniq))
    return True


def string_lut_env(nodes, schema, dcs, env) -> Optional[dict]:
    """Merge per-partition dictionary lookup tables into `env` for every
    LUT-predicate. Returns the (possibly unchanged) env, or None when a
    needed dictionary is unavailable."""
    shapes = collect_string_luts(nodes, schema)
    if not shapes:
        return env
    merged = dict(env)
    for colname, kind, payload, node_key in shapes:
        if kind == "hostpred":
            if not _merge_dict_pred(merged, colname, payload, node_key, dcs):
                return None
            continue
        key = _strlut_env_key(node_key)
        if key in merged:
            continue
        dc = dcs.get(colname)
        if dc is None or dc.dictionary is None:
            return None
        uniq = dc.dictionary
        if kind == "is_in":
            lut = pc.is_in(uniq, value_set=pa.array(list(payload),
                                                    type=uniq.type))
        else:
            # run the REGISTERED host implementation over the dictionary:
            # whatever semantics the host path has (incl. like's regex
            # translation), the LUT has identically
            from ..functions import get_function
            from ..series import Series

            got = get_function(kind).evaluate(
                Series.from_arrow(uniq, "u"),
                Series.from_pylist([payload], "p", DataType.string()))
            lut = got.to_arrow()
        lut_np = np.asarray(pc.fill_null(lut, False), dtype=bool)
        b = size_bucket(max(len(uniq), 1))
        if b > len(lut_np):
            lut_np = np.concatenate([lut_np, np.zeros(b - len(lut_np), bool)])
        merged[key] = jnp.asarray(lut_np)
    return merged


def expr_is_device_compilable(node, schema, _normalized: bool = False) -> bool:
    """Can this expression tree run fully on device against `schema`?"""
    from ..expressions import (
        Alias, Between, BinaryOp, Cast, Column, FillNull, Function, IfElse, IsIn,
        IsNull, Literal, Not, normalize_literals,
    )

    if not _normalized:
        try:
            node = normalize_literals(node, schema)
        except (ValueError, KeyError):
            return False
        return expr_is_device_compilable(node, schema, _normalized=True)

    def rec(n):
        return expr_is_device_compilable(n, schema, _normalized=True)

    try:
        out_dt = node.to_field(schema).dtype
    except (ValueError, KeyError):
        return False
    if _string_dict_pred_applies(node, schema) is not None:
        # the whole boolean subtree evaluates over the dictionary on host;
        # nothing below it needs to compile on device
        return True
    if _int_transform_applies(node, schema) is not None:
        # int-valued string transform: values come from a host dictionary
        # evaluation, gathered by code
        return True
    if not (is_device_dtype(out_dt) or out_dt.is_null()):
        # strings ride dictionary codes: bare column passthrough, a
        # fill_null/if_else over string columns/literals whose output codes
        # live in a joint dictionary, or a row-local transform of ONE
        # string column whose sorted-order ids come from a host transform
        # of the dictionary (all decoded at unstage); any OTHER
        # string-producing compute stays host
        if out_dt.is_string():
            if _plain_string_column(node, schema) is not None:
                return True
            ch = _string_choice_shape(node, schema)
            if ch is not None:
                return ch.pred is None or rec(ch.pred)
            if _string_dict_value_shape(node, schema) is not None:
                return True
            return False
        return False
    if isinstance(node, Column):
        return stageable_dtype(schema[node.cname].dtype)
    if isinstance(node, Literal):
        return _literal_fits_device(node)
    if isinstance(node, (Alias, Not, IsNull)):
        return all(rec(c) for c in node.children())
    def any_string_child(n) -> bool:
        """True when any DIRECT child is string-typed (or untyped): its
        device representation would be dictionary codes, which only the
        string-comparison shape knows how to interpret."""
        for c in n.children():
            try:
                if c.to_field(schema).dtype.is_string():
                    return True
            except (ValueError, KeyError):
                return True
        return False

    if isinstance(node, Cast):
        # one level is enough here: casting dictionary CODES themselves is
        # nonsense, but a cast OVER e.g. a bool from a legit string compare
        # is fine — deeper strings are vetted where they are consumed
        if any_string_child(node):
            return False
        return is_device_dtype(node.dtype) and rec(node.child)
    if isinstance(node, BinaryOp):
        if node.op == "+" and out_dt.is_string():
            return False
        if _string_cmp_shape(node, schema) is not None:
            return True
        cc = _string_colcol_shape(node, schema)
        if cc is not None:
            # joint-dictionary recode, compared on device; a choice side's
            # predicate must itself compile
            return all(s[0] != "choice" or s[1].pred is None or rec(s[1].pred)
                       for s in cc)
        # epoch comparisons compile as two-lane splits only in 32-bit mode;
        # under x64 the generic int64 path below handles them
        if not x64_enabled() and _epoch_cmp_shape(node, schema) is not None:
            return True
        # cross-column transform compares recode through a pairwise joint
        # dictionary (transform_cmp_env)
        if _transform_cmp_shape(node, schema) is not None:
            return True
        # any OTHER op touching a string child (col vs col: codes come
        # from different dictionaries) must stay host
        if any_string_child(node):
            return False
        return all(rec(c) for c in node.children())
    if isinstance(node, (FillNull, IfElse, Between)):
        if any_string_child(node):
            return False
        return all(rec(c) for c in node.children())
    if isinstance(node, Function):
        if _string_lut_shape(node, schema) is not None:
            return True  # dictionary-LUT predicate (contains/starts/ends)
        if node.fname in _DEVICE_FNS:
            return all(rec(c) for c in node.children())
        return False
    if isinstance(node, IsIn):
        if _string_lut_shape(node, schema) is not None:
            return True  # string membership via the dictionary LUT
        return (_numeric_isin_items(node, schema) is not None
                and rec(node.child))
    return False


_DEVICE_FNS = {
    "numeric.abs": lambda v: jnp.abs(v),
    "numeric.negate": lambda v: -v,
    "numeric.ceil": lambda v: jnp.ceil(v),
    "numeric.floor": lambda v: jnp.floor(v),
    "numeric.sign": lambda v: jnp.sign(v),
    "numeric.sqrt": lambda v: jnp.sqrt(v.astype(_wf())),
    "numeric.exp": lambda v: jnp.exp(v.astype(_wf())),
    "numeric.log": lambda v: jnp.log(v.astype(_wf())),
    "numeric.log2": lambda v: jnp.log2(v.astype(_wf())),
    "numeric.log10": lambda v: jnp.log10(v.astype(_wf())),
    "numeric.log1p": lambda v: jnp.log1p(v.astype(_wf())),
    "numeric.sin": lambda v: jnp.sin(v.astype(_wf())),
    "numeric.cos": lambda v: jnp.cos(v.astype(_wf())),
    "numeric.tan": lambda v: jnp.tan(v.astype(_wf())),
    "float.is_nan": lambda v: jnp.isnan(v),
    "float.is_inf": lambda v: jnp.isinf(v),
    "float.not_nan": lambda v: ~jnp.isnan(v),
}


def _strlit_keys(colname: str, lit: str) -> Tuple[str, str, str]:
    """Deterministic env keys for a (column, literal) pair's injected code
    bounds: eq code (-1 when absent), bisect-left pos, bisect-right pos."""
    base = f"__strlit__\x00{colname}\x00{lit}"
    return base + "\x00eq", base + "\x00lt", base + "\x00le"


def _env_nrows(env) -> int:
    """Bucket length from the first COLUMN entry (env also carries scalar
    literal-code leaves, which have no row dimension)."""
    for v in env.values():
        if isinstance(v, tuple):
            return v[0].shape[0]
    raise AssertionError("projection env has no column entries")


def collect_string_cmp_literals(nodes, schema):
    """Every (colname, literal) string comparison in the trees (normalized)."""
    from ..expressions import BinaryOp

    out = []

    def walk(n):
        if isinstance(n, BinaryOp):
            shape = _string_cmp_shape(n, schema)
            if shape is not None and shape[1] is not None:
                out.append((shape[0], shape[1]))
        for c in n.children():
            walk(c)

    for nd in nodes:
        walk(nd)
    return out


def string_literal_env(nodes, schema, dcs, env) -> Optional[dict]:
    """Merge the per-partition code bounds for every string-literal
    comparison into `env` ({env_key: int32 scalar} entries). The compiled
    closure is shared across partitions (the literal's CODE varies, the
    program does not). Returns the (possibly unchanged) env, or None when a
    needed dictionary is unavailable (caller falls back to host)."""
    import bisect

    add: Dict[str, jax.Array] = {}
    for colname, lit in collect_string_cmp_literals(nodes, schema):
        keq, klt, kle = _strlit_keys(colname, lit)
        if keq in add:
            continue
        dc = dcs.get(colname)
        if dc is None or dc.dictionary is None:
            return None
        uniq = dc.dict_list()
        i = bisect.bisect_left(uniq, lit)
        j = bisect.bisect_right(uniq, lit)
        eq = i if i < len(uniq) and uniq[i] == lit else -1
        add[keq] = jnp.int32(eq)
        add[klt] = jnp.int32(i)
        add[kle] = jnp.int32(j)
    if not add:
        return env
    merged = dict(env)
    merged.update(add)
    return merged


def _compile_node(node, schema) -> "Tuple[callable, DataType]":
    """Recursively build a python closure over {name: (values, valid)} env.

    The closure is pure jax -> safe to jit; types resolved statically via schema.
    """
    from ..expressions import (
        Alias, Between, BinaryOp, Cast, Column, FillNull, Function, IfElse, IsIn,
        IsNull, Literal, Not,
    )

    out_dt = node.to_field(schema).dtype

    gshape = _string_dict_pred_applies(node, schema)
    if gshape is not None:
        # general dictionary predicate: the WHOLE boolean subtree was
        # host-evaluated over the column's dictionary (+ null slot); the
        # device gathers (value, validity) by code
        colname, _pred, node_key = gshape
        vals_k, valid_k, null_k = _strdictpred_env_keys(node_key)

        def run(env, _c=colname, _vk=vals_k, _mk=valid_k, _nk=null_k):
            codes, m = env[_c]
            idx = jnp.where(m, codes, env[_nk])
            return env[_vk][idx], env[_mk][idx]

        return run, out_dt

    vshape = _string_value_applies(node, schema)
    if vshape is not None:
        # transformed-string value: the lane (sorted-order ids + validity)
        # was staged by string_transform_env; decode at unstage goes
        # through the transformed dictionary (string_output_dictionary)
        vk, mk = _strtransval_env_keys(vshape[2])

        def run(env, _vk=vk, _mk=mk):
            return env[_vk], env[_mk]

        return run, out_dt

    ishape = _int_transform_applies(node, schema)
    if ishape is not None:
        # int-valued string transform (length/find/count_matches): the
        # lane carries VALUES gathered by code, no decode needed
        vk, mk = _inttrans_env_keys(ishape[2])

        def run(env, _vk=vk, _mk=mk):
            return env[_vk], env[_mk]

        return run, out_dt

    if isinstance(node, Column):
        name = node.cname

        def run(env):
            return env[name]

        return run, out_dt

    if isinstance(node, Literal):
        if node.value is None:
            def run(env, _dt=out_dt):
                n = _env_nrows(env)
                return jnp.zeros(n, dtype=jnp.int32), jnp.zeros(n, dtype=bool)
        else:
            v = _literal_to_physical(node.value, node.dtype)
            jd = _jdt(node.dtype)

            def run(env, _v=v, _jd=jd):
                n = _env_nrows(env)
                return jnp.full(n, _v, dtype=_jd), jnp.ones(n, dtype=bool)

        return run, out_dt

    if isinstance(node, Alias):
        inner, _ = _compile_node(node.child, schema)
        return inner, out_dt

    if isinstance(node, Cast):
        inner, _ = _compile_node(node.child, schema)
        jd = _jdt(node.dtype)

        def run(env, _inner=inner, _jd=jd):
            v, m = _inner(env)
            return v.astype(_jd), m

        return run, out_dt

    if isinstance(node, Not):
        inner, _ = _compile_node(node.child, schema)

        def run(env, _inner=inner):
            v, m = _inner(env)
            return ~v, m

        return run, out_dt

    if isinstance(node, IsNull):
        inner, _ = _compile_node(node.child, schema)
        neg = node.negate

        def run(env, _inner=inner, _neg=neg):
            v, m = _inner(env)
            out = m if _neg else ~m
            return out, jnp.ones_like(m)

        return run, out_dt

    if isinstance(node, (FillNull, IfElse)) and out_dt.is_string():
        ch = _string_choice_shape(node, schema)
        if ch is None:
            raise ValueError("string choice not device-compilable here")
        return _choice_code_fn(ch, _joint_gkey(ch.cols, ch.lits),
                               schema), out_dt

    if isinstance(node, FillNull):
        a, adt = _compile_node(node.child, schema)
        b, bdt = _compile_node(node.fill, schema)
        jd = _jdt(out_dt)

        def run(env, _a=a, _b=b, _jd=jd):
            av, am = _a(env)
            bv, bm = _b(env)
            out = jnp.where(am, av.astype(_jd), bv.astype(_jd))
            return out, am | bm

        return run, out_dt

    if isinstance(node, IfElse):
        p, _ = _compile_node(node.pred, schema)
        t, _ = _compile_node(node.if_true, schema)
        f, _ = _compile_node(node.if_false, schema)
        jd = _jdt(out_dt)

        def run(env, _p=p, _t=t, _f=f, _jd=jd):
            pv, pm = _p(env)
            tv, tm = _t(env)
            fv, fm = _f(env)
            out = jnp.where(pv, tv.astype(_jd), fv.astype(_jd))
            valid = pm & jnp.where(pv, tm, fm)
            return out, valid

        return run, out_dt

    if isinstance(node, Between):
        x, _ = _compile_node(node.child, schema)
        lo, _ = _compile_node(node.lower, schema)
        hi, _ = _compile_node(node.upper, schema)

        def run(env, _x=x, _lo=lo, _hi=hi):
            xv, xm = _x(env)
            lv, lm = _lo(env)
            hv, hm = _hi(env)
            ge, ge_m = xv >= lv, xm & lm
            le, le_m = xv <= hv, xm & hm
            out = ge & le
            # Kleene AND: valid when both valid, or either side is a valid False
            valid = (ge_m & le_m) | (ge_m & ~ge) | (le_m & ~le)
            return out, valid

        return run, out_dt

    if isinstance(node, BinaryOp):
        shape = _string_cmp_shape(node, schema)
        if shape is not None:
            colname, lit, flipped = shape
            cop = _CMP_FLIP[node.op] if flipped else node.op
            if lit is None:
                # comparison with a null literal: all-null result (SQL)
                def run(env, _c=colname):
                    _v, m = env[_c]
                    z = jnp.zeros_like(m)
                    return z, z

                return run, out_dt
            keq, klt, kle = _strlit_keys(colname, lit)

            def run(env, _c=colname, _op=cop, _keq=keq, _klt=klt, _kle=kle):
                codes, m = env[_c]
                if _op == "==":
                    out = codes == env[_keq]
                elif _op == "!=":
                    out = codes != env[_keq]
                elif _op == "<":
                    out = codes < env[_klt]
                elif _op == ">=":
                    out = codes >= env[_klt]
                elif _op == "<=":
                    out = codes < env[_kle]
                else:  # ">"
                    out = codes >= env[_kle]
                return out, m

            return run, out_dt
        ccshape = _string_colcol_shape(node, schema)
        if ccshape is not None:
            lside, rside = ccshape
            gkey = _joint_gkey(*_cmp_union_group(lside, rside))
            lf2 = _side_code_fn(lside, gkey, schema)
            rf2 = _side_code_fn(rside, gkey, schema)
            op = node.op

            def run(env, _l=lf2, _r=rf2, _op=op):
                lv, lm = _l(env)
                rv, rm = _r(env)
                if _op == "<=>":
                    eq = (lv == rv) & lm & rm
                    return eq | (~lm & ~rm), jnp.ones_like(lm)
                if _op == "==":
                    out = lv == rv
                elif _op == "!=":
                    out = lv != rv
                elif _op == "<":
                    out = lv < rv
                elif _op == "<=":
                    out = lv <= rv
                elif _op == ">":
                    out = lv > rv
                else:
                    out = lv >= rv
                return out, lm & rm

            return run, out_dt
        eshape = None if x64_enabled() else _epoch_cmp_shape(node, schema)
        if eshape is not None:
            lspec, rspec, cop = eshape
            if lspec[0] == "lit":
                # normalize to lane-op-lit / lane-op-lane with the lane side
                # on the left, flipping the comparison when the literal led
                lspec, rspec, cop = rspec, lspec, _CMP_FLIP[cop]
            _tag, lident, _ldt, _lsn = lspec
            hi_k, lo_k = _epoch_lane_keys(lident)
            if rspec[0] == "lit":
                lit = rspec[1]
                if lit.value is None:
                    def run(env, _hk=hi_k):
                        _v, m = env[_hk]
                        z = jnp.zeros_like(m)
                        return z, z

                    return run, out_dt
                lhik, llok = _epoch_lit_keys(lident, lit._key())

                def run(env, _op=cop, _hk=hi_k, _lk=lo_k, _lh=lhik,
                        _ll=llok):
                    hi, m = env[_hk]
                    lo, _m2 = env[_lk]
                    return _two_lane_cmp(_op, hi, lo, env[_lh], env[_ll]), m

                return run, out_dt
            rhi_k, rlo_k = _epoch_lane_keys(rspec[1])

            def run(env, _op=cop, _hk=hi_k, _lk=lo_k, _rhk=rhi_k,
                    _rlk=rlo_k):
                hi, lm = env[_hk]
                lo, _m2 = env[_lk]
                rhi, rm = env[_rhk]
                rlo, _m4 = env[_rlk]
                return _two_lane_cmp(_op, hi, lo, rhi, rlo), lm & rm

            return run, out_dt
        tshape = _transform_cmp_shape(node, schema)
        if tshape is not None:
            ls, rs, cop = tshape
            lk, rk = _transcmp_env_keys(node._key())

            def _lane_reader(s):
                kind, colname, n = s
                if kind == "col":
                    def read(env, _c=colname):
                        return env[_c]
                else:
                    vk, mk = _strtransval_env_keys(n._key())

                    def read(env, _vk=vk, _mk=mk):
                        return env[_vk], env[_mk]
                return read

            lread, rread = _lane_reader(ls), _lane_reader(rs)
            cmp_fn = _CMP_FNS[cop]

            def run(env, _lr=lread, _rr=rread, _lk=lk, _rk=rk, _f=cmp_fn):
                lv, lm = _lr(env)
                rv, rm = _rr(env)
                lj = env[_lk][lv]
                rj = env[_rk][rv]
                return _f(lj, rj), lm & rm

            return run, out_dt
        lf, ldt = _compile_node(node.left, schema)
        rf, rdt = _compile_node(node.right, schema)
        op = node.op
        if op in ("&", "|"):
            def run(env, _l=lf, _r=rf, _op=op):
                lv, lm = _l(env)
                rv, rm = _r(env)
                if _op == "&":
                    out = lv & rv
                    # Kleene: valid if both valid, or either side is a valid False
                    valid = (lm & rm) | (lm & ~lv) | (rm & ~rv)
                else:
                    out = lv | rv
                    valid = (lm & rm) | (lm & lv) | (rm & rv)
                return out, valid

            return run, out_dt
        if op == "^":
            def run(env, _l=lf, _r=rf):
                lv, lm = _l(env)
                rv, rm = _r(env)
                return lv ^ rv, lm & rm

            return run, out_dt

        if op in _CMP_FNS:
            fn = _CMP_FNS[op]

            def run(env, _l=lf, _r=rf, _fn=fn):
                lv, lm = _l(env)
                rv, rm = _r(env)
                return _fn(lv, rv), lm & rm

            return run, out_dt
        if op == "<=>":
            def run(env, _l=lf, _r=rf):
                lv, lm = _l(env)
                rv, rm = _r(env)
                eq = (lv == rv) & lm & rm
                both_null = ~lm & ~rm
                return eq | both_null, jnp.ones_like(lm)

            return run, out_dt

        jd = _jdt(out_dt)

        def arith(lv, rv, _op=op, _jd=jd):
            if _op == "+":
                return (lv.astype(_jd) + rv.astype(_jd))
            if _op == "-":
                return (lv.astype(_jd) - rv.astype(_jd))
            if _op == "*":
                return (lv.astype(_jd) * rv.astype(_jd))
            if _op == "/":
                return lv.astype(_wf()) / rv.astype(_wf())
            if _op == "//":
                if jnp.issubdtype(jnp.result_type(lv, rv), jnp.floating):
                    return jnp.floor(lv / rv).astype(_jd)  # 1.0//0.0 = inf like host
                return jnp.floor_divide(lv, rv).astype(_jd)
            if _op == "%":
                return jnp.mod(lv, rv).astype(_jd)
            if _op == "**":
                return jnp.power(lv.astype(_wf()), rv.astype(_wf()))
            raise AssertionError(_op)

        def run(env, _l=lf, _r=rf, _arith=arith, _op=op):
            lv, lm = _l(env)
            rv, rm = _r(env)
            if _op == "/":
                # float division: inf/nan like the host (arrow) kernel
                return _arith(lv, rv), lm & rm
            if _op in ("//", "%") and not jnp.issubdtype(jnp.result_type(lv, rv), jnp.floating):
                # INT division by zero: null (the host checked kernel raises; on
                # device we cannot raise inside jit, so mask instead). Float
                # operands keep inf/nan semantics to match the host.
                safe = jnp.where(rv == 0, jnp.ones_like(rv), rv)
                out = _arith(lv, safe)
                return out, lm & rm & (rv != 0)
            return _arith(lv, rv), lm & rm

        return run, out_dt

    if isinstance(node, (Function, IsIn)):
        lshape = _string_lut_shape(node, schema)
        if lshape is not None:
            colname, _kind, _payload, node_key = lshape
            lut_k = _strlut_env_key(node_key)

            def run(env, _c=colname, _lk=lut_k):
                codes, m = env[_c]
                return env[_lk][codes], m

            return run, out_dt

    if isinstance(node, IsIn):
        items = _numeric_isin_items(node, schema)
        if items is None:
            raise ValueError("is_in not device-compilable here")
        inner, _ = _compile_node(node.child, schema)

        def run(env, _inner=inner, _items=items):
            v, m = _inner(env)
            if not _items:
                return jnp.zeros_like(m), m
            out = jnp.zeros_like(m)
            for it in _items:  # small static lists: unrolled compares fuse
                out = out | (v == it)
            return out, m

        return run, out_dt

    if isinstance(node, Function):
        if node.fname not in _DEVICE_FNS:
            raise ValueError(f"function {node.fname} not device-compilable")
        inner, _ = _compile_node(node.args[0], schema)
        fn = _DEVICE_FNS[node.fname]

        def run(env, _inner=inner, _fn=fn):
            v, m = _inner(env)
            return _fn(v), m

        return run, out_dt

    raise ValueError(f"{type(node).__name__} not device-compilable")


_PROJ_CACHE: Dict = {}


def compile_projection(nodes, schema, input_names: Tuple[str, ...]):
    """Compile a list of NORMALIZED expression nodes to ONE jitted fn:
    env dict -> list[(values, valid)].

    Cached on (node keys, schema, input order, x64 mode); XLA additionally
    caches per bucket.
    """
    key = (tuple(n._key() for n in nodes), tuple((f.name, f.dtype) for f in schema),
           input_names, x64_enabled())
    if key in _PROJ_CACHE:
        return _PROJ_CACHE[key]
    compiled = [_compile_node(n, schema) for n in nodes]
    fns = [c[0] for c in compiled]
    out_dts = [c[1] for c in compiled]

    @jax.jit
    def run(env):
        return [f(env) for f in fns]

    _PROJ_CACHE[key] = (run, out_dts)
    return run, out_dts


def stage_table_columns(table, names, bucket: int, stage_cache: Optional[dict] = None):
    """Stage the named columns of a host Table: returns (env, dcs) where env
    is {name: (values, valid)} for the jitted programs and dcs the backing
    DeviceColumns (string dictionaries live there). HBM-resident columns are
    reused from `stage_cache` (the per-MicroPartition residency cache —
    staging, not compute, is the bottleneck through the host link, so
    repeated queries over the same partition must not re-transfer).
    Returns None if any column is ineligible."""
    env = {}
    dcs = {}
    for name in names:
        ckey = (name, bucket, x64_enabled())
        dc = stage_cache.get(ckey) if stage_cache is not None else None
        if dc is None:
            s = table.get_column(name)
            if not stageable_dtype(s.dtype):
                return None
            dc = stage_series(s, bucket)
            if stage_cache is not None:
                stage_cache[ckey] = dc
        env[name] = (dc.values, dc.valid)
        dcs[name] = dc
    return env, dcs


def _rewrite_between(node, schema):
    """Between over string/epoch children rewrites to the conjunction of two
    comparisons — exactly the host's implementation (Series.between is
    (x >= lo) & (x <= hi)) — so the dictionary-code and epoch-lane compare
    machinery applies. Numeric Between keeps its fused direct compile."""
    from ..expressions import Between, BinaryOp

    kids = node.children()
    if kids:
        node = node.with_children([_rewrite_between(c, schema) for c in kids])
    if isinstance(node, Between):
        try:
            cdt = node.child.to_field(schema).dtype
        except (ValueError, KeyError):
            return node
        if cdt.is_string() or cdt.kind in _EPOCH_KINDS:
            return BinaryOp("&",
                            BinaryOp(">=", node.child, node.lower),
                            BinaryOp("<=", node.child, node.upper))
    return node


def normalize_and_check(exprs, schema) -> Optional[list]:
    """Normalize each expression's literals against `schema`, apply device
    rewrites, and verify device compilability. Returns the normalized
    nodes, or None if any is ineligible."""
    from ..expressions import normalize_literals

    try:
        nodes = [_rewrite_between(normalize_literals(e._node, schema), schema)
                 for e in exprs]
    except (ValueError, KeyError):
        return None
    for nd in nodes:
        if not expr_is_device_compilable(nd, schema, _normalized=True):
            return None
    return nodes


_INT32_LO, _INT32_HI = -(2 ** 31), 2 ** 31 - 1


def int64_wrap_safe(nodes, schema, env, stage_cache: Optional[dict],
                    bucket: int) -> bool:
    """32-bit mode guard: int64-typed arithmetic computes in int32 lanes and
    can wrap silently (staging only range-checks the LEAF columns). Prove by
    interval arithmetic over the STAGED data's actual min/max that no
    int64-typed arithmetic node can leave the int32 range; anything unproven
    declines to the host path (exact 64-bit semantics there). The per-column
    ranges cost one fused reduction + sync each, cached with the partition.

    Found live: `select((col_i64 * col_i64))` with values ~1e5 returned the
    int32-wrapped product on the device path while the host returned 1e10.
    """
    if x64_enabled():
        return True
    from ..datatypes import DataType
    from ..expressions import Alias, BinaryOp, Column, Function, Literal

    risky_dts = (DataType.int64(), DataType.uint64())

    _lanes_memo: dict = {}

    def rides_lanes(n):
        # an epoch-compare subtree is host-evaluated in exact int64 and
        # reaches the device only as (hi, lo) lane pairs, and a dictionary-
        # predicate subtree is host-evaluated over the dictionary: int32
        # wrap safety is irrelevant below either. Memoized by node identity:
        # has_risky and safe both probe every node, and each probe walks
        # the subtree.
        r = _lanes_memo.get(id(n))
        if r is None:
            r = ((isinstance(n, BinaryOp)
                  and _epoch_cmp_shape(n, schema) is not None)
                 or _string_dict_pred_applies(n, schema) is not None
                 or _string_value_applies(n, schema) is not None
                 or _int_transform_applies(n, schema) is not None)
            _lanes_memo[id(n)] = r
        return r

    def has_risky(n):
        if rides_lanes(n):
            return False
        try:
            if (isinstance(n, (BinaryOp, Function))
                    and n.to_field(schema).dtype in risky_dts):
                return True
        except (ValueError, KeyError):
            return True
        return any(has_risky(c) for c in n.children())

    if not any(has_risky(n) for n in nodes):
        return True

    def col_range(name):
        key = ("__int_range__", name, bucket, x64_enabled())
        r = stage_cache.get(key) if stage_cache is not None else None
        if r is None:
            if name not in env:
                return None
            v, m = env[name]
            if not jnp.issubdtype(v.dtype, jnp.integer):
                return None
            lo_d = jnp.min(jnp.where(m, v, jnp.iinfo(v.dtype).max))
            hi_d = jnp.max(jnp.where(m, v, jnp.iinfo(v.dtype).min))
            lo, hi = (int(x) for x in jax.device_get((lo_d, hi_d)))  # 1 sync
            if hi < lo:  # all-null column
                lo = hi = 0
            r = (lo, hi)
            if stage_cache is not None:
                stage_cache[key] = r
        return r

    def bounds(n):
        """Exact integer interval of a node, or None = unknown."""
        if isinstance(n, Alias):
            return bounds(n.child)
        if isinstance(n, Column):
            return col_range(n.cname)
        if isinstance(n, Literal):
            v = n.value
            return (v, v) if isinstance(v, int) and not isinstance(v, bool) \
                else None
        if isinstance(n, BinaryOp) and n.op in ("+", "-", "*"):
            a = bounds(n.left)
            b = bounds(n.right)
            if a is None or b is None:
                return None
            if n.op == "+":
                return (a[0] + b[0], a[1] + b[1])
            if n.op == "-":
                return (a[0] - b[1], a[1] - b[0])
            prods = [x * y for x in a for y in b]
            return (min(prods), max(prods))
        if isinstance(n, BinaryOp) and n.op == "%":
            b = bounds(n.right)
            if b is None:
                return None
            m = max(abs(b[0]), abs(b[1]))
            if m == 0:
                return None
            return (-(m - 1), m - 1)
        if isinstance(n, BinaryOp) and n.op == "//":
            a = bounds(n.left)
            b = bounds(n.right)
            if a is None or b is None or b[0] <= 0 <= b[1]:
                return None  # divisor range crosses zero
            cands = [a[0] // b[0], a[0] // b[1], a[1] // b[0], a[1] // b[1]]
            return (min(cands), max(cands))
        return None

    def safe(n):
        if rides_lanes(n):
            return True
        if isinstance(n, (BinaryOp, Function)):
            try:
                dt_ = n.to_field(schema).dtype
            except (ValueError, KeyError):
                return False
            if dt_ in risky_dts:
                bd = bounds(n)
                if bd is None or bd[0] < _INT32_LO or bd[1] > _INT32_HI:
                    return False
        return all(safe(c) for c in n.children())

    return all(safe(n) for n in nodes)


def _stage_and_run(table, exprs, stage_cache: Optional[dict]):
    """Shared device prologue: normalize + eligibility-check the expressions,
    stage the input columns, compile and launch ONE jitted program. Returns
    (outs, out_dts, nodes, dcs) with `outs` still on device (async), or None
    when ineligible. Used by the projection and sort paths."""

    schema = table.schema
    n = len(table)
    if n == 0:
        return None
    nodes = normalize_and_check(exprs, schema)
    if nodes is None:
        return None
    # epoch-compare subtrees are consumed through host-evaluated lane
    # pairs, never staged normally (their dtypes cannot narrow to int32)
    epoch_cmps = epoch_cmps_for(nodes, schema)
    needed = device_required_columns(nodes, schema)
    if not needed and not epoch_cmps:
        return None
    b = size_bucket(n)
    staged = stage_table_columns(table, needed, b, stage_cache)
    if staged is None:
        return None
    env, dcs = staged
    if not int64_wrap_safe(nodes, schema, env, stage_cache, b):
        return None
    env = string_literal_env(nodes, schema, dcs, env)
    if env is None:
        return None
    env = epoch_cmp_env(epoch_cmps, schema, table, b, stage_cache, env)
    if env is None:
        return None
    env = string_lut_env(nodes, schema, dcs, env)
    if env is None:
        return None
    aux: dict = {}
    env = string_joint_env(nodes, schema, dcs, env, aux)
    if env is None:
        return None
    env = string_transform_env(nodes, schema, table, b, stage_cache, env, aux)
    if env is None:
        return None
    env = transform_cmp_env(nodes, schema, table, b, stage_cache, dcs, env,
                            aux)
    if env is None:
        return None
    run, out_dts = compile_projection(nodes, schema, tuple(sorted(needed)))
    return run(env), out_dts, nodes, dcs, aux


def eval_projection_device_async(table, exprs, stage_cache: Optional[dict] = None):
    """Dispatch a device projection WITHOUT blocking: staging and the jitted
    compute launch happen now (jax dispatch is asynchronous); the returned
    zero-arg resolver materializes the host Table (device_get) when called.
    This is what lets the executor double-buffer — stage morsel i+1 while the
    device still computes morsel i (reference role: the pipelined channel
    hand-off of daft-local-execution intermediate_op.rs:71+).
    Returns None if ineligible."""
    from ..schema import Field, Schema
    from ..table import Table

    n = len(table)
    staged = _stage_and_run(table, exprs, stage_cache)
    if staged is None:
        return None
    outs, out_dts, nodes, dcs, aux = staged  # async: device computes from here
    schema = table.schema

    def resolve():
        cols = []
        fields = []
        for e, nd, (v, m), dt in zip(exprs, nodes, outs, out_dts):
            dictionary = None
            if dt.is_string():
                # string outputs are bare column passthroughs OR joint-coded
                # fill_null/if_else results (enforced by the compilability
                # check): decode with the matching dictionary
                dictionary = string_output_dictionary(nd, schema, dcs, aux)
                if dictionary is None:
                    raise RuntimeError(
                        f"string projection {e.name()!r} lost its dictionary")
            dc = DeviceColumn(v, m, n, dt, dictionary=dictionary)
            s = unstage(dc).rename(e.name())
            cols.append(s)
            fields.append(Field(e.name(), s.dtype))
        return Table(Schema(fields), cols)

    return resolve


def eval_projection_device(table, exprs, stage_cache: Optional[dict] = None) -> Optional[object]:
    """Evaluate a projection on device; returns a host Table or None if ineligible."""
    resolve = eval_projection_device_async(table, exprs, stage_cache)
    return None if resolve is None else resolve()


# ---------------------------------------------------------------------------
# Segment aggregation (grouped agg on device)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("num_segments", "kind"))
def _segment_agg(values, valid, codes, num_segments: int, kind: str):
    count_dt = jnp.int64 if x64_enabled() else jnp.int32
    v64 = values
    if kind == "sum":
        contrib = jnp.where(valid, v64, jnp.zeros_like(v64))
        return jax.ops.segment_sum(contrib, codes, num_segments)
    if kind == "count":
        return jax.ops.segment_sum(valid.astype(count_dt), codes, num_segments)
    if kind == "min":
        big = _type_max(v64.dtype)
        contrib = jnp.where(valid, v64, jnp.full_like(v64, big))
        return jax.ops.segment_min(contrib, codes, num_segments)
    if kind == "max":
        small = _type_min(v64.dtype)
        contrib = jnp.where(valid, v64, jnp.full_like(v64, small))
        return jax.ops.segment_max(contrib, codes, num_segments)
    raise ValueError(kind)


def _type_max(dt):
    if jnp.issubdtype(dt, jnp.floating):
        return jnp.inf
    return jnp.iinfo(dt).max


def _type_min(dt):
    if jnp.issubdtype(dt, jnp.floating):
        return -jnp.inf
    return jnp.iinfo(dt).min


def segment_aggregate(values: jax.Array, valid: jax.Array, codes: jax.Array,
                      num_segments: int, kind: str) -> Tuple[jax.Array, jax.Array]:
    """Masked segment aggregation; returns (per-group values, per-group valid)."""
    out = _segment_agg(values, valid, codes, num_segments, kind)
    if kind == "count":
        return out, jnp.ones(num_segments, dtype=bool)
    counts = _segment_agg(valid, valid, codes, num_segments, "count")
    return out, counts > 0


# Up to this many segments, the one-hot compare-reduce formulation beats the
# scatter-based segment_sum by ~1000x on TPU (measured on v5e: the compare,
# mask and reduction fuse into one HBM-bandwidth pass; XLA's scatter path does
# not). Beyond it, fall back to scatter.
_ONEHOT_MAX_SEGMENTS = 4096
_REDUCE_CHUNK = 8192


def segment_reduce(values: jax.Array, valid: jax.Array, codes: jax.Array,
                   num_segments: int, kind: str) -> Tuple[jax.Array, jax.Array]:
    """TPU-tuned masked segment reduction -> (per-group values, per-group valid).

    Low-cardinality strategy: chunked one-hot compare-reduce with a
    Kahan-compensated cross-chunk combine for float sums (accumulation error
    stays at the float32 representation floor, ~5e-8 relative, instead of
    growing with rows — required for TPC-H money-sum parity in 32-bit mode).
    High-cardinality strategy: scatter segment ops (chunked+compensated for
    float sums)."""
    if kind == "count":
        cnt = _segment_count(valid, codes, num_segments)
        return cnt, jnp.ones(num_segments, dtype=bool)
    if num_segments <= _ONEHOT_MAX_SEGMENTS and values.ndim == 1:
        out = _onehot_reduce(values, valid, codes, num_segments, kind)
    elif kind == "sum" and jnp.issubdtype(values.dtype, jnp.floating) and values.ndim == 1:
        out = _scatter_sum_kahan(jnp.where(valid, values, 0), codes, num_segments)
    else:
        out = _segment_agg(values, valid, codes, num_segments, kind)
    counts = _segment_count(valid, codes, num_segments)
    return out, counts > 0


def _count_dtype():
    return jnp.int64 if x64_enabled() else jnp.int32


def _segment_count(valid, codes, num_segments):
    if num_segments <= _ONEHOT_MAX_SEGMENTS:
        b = valid.shape[0]
        chunk = min(_REDUCE_CHUNK, b)
        nch = b // chunk
        sel = (codes.reshape(nch, chunk, 1)
               == jnp.arange(num_segments, dtype=codes.dtype)) \
            & valid.reshape(nch, chunk, 1)
        return jnp.sum(jnp.sum(sel, axis=1, dtype=_count_dtype()), axis=0)
    return jax.ops.segment_sum(valid.astype(_count_dtype()), codes, num_segments)


def _kahan_combine(partials):
    """Compensated sum over the leading (chunk) axis."""
    def step(carry, p):
        s, comp = carry
        y = p - comp
        t = s + y
        return (t, (t - s) - y), None

    zero = jnp.zeros(partials.shape[1:], partials.dtype)
    (s, _), _ = jax.lax.scan(step, (zero, zero), partials)
    return s


def _onehot_reduce(values, valid, codes, num_segments, kind):
    b = values.shape[0]
    chunk = min(_REDUCE_CHUNK, b)
    nch = b // chunk
    vc = values.reshape(nch, chunk, 1)
    sel = (codes.reshape(nch, chunk, 1)
           == jnp.arange(num_segments, dtype=codes.dtype)) \
        & valid.reshape(nch, chunk, 1)
    if kind == "sum":
        partials = jnp.sum(jnp.where(sel, vc, jnp.zeros_like(vc)), axis=1)
        if jnp.issubdtype(values.dtype, jnp.floating):
            return _kahan_combine(partials)
        return jnp.sum(partials, axis=0)
    if kind == "min":
        ident = _type_max(values.dtype)
        part = jnp.min(jnp.where(sel, vc, jnp.full_like(vc, ident)), axis=1)
        return jnp.min(part, axis=0)
    if kind == "max":
        ident = _type_min(values.dtype)
        part = jnp.max(jnp.where(sel, vc, jnp.full_like(vc, ident)), axis=1)
        return jnp.max(part, axis=0)
    raise ValueError(kind)


def _scatter_sum_kahan(values, codes, num_segments):
    b = values.shape[0]
    chunk = min(_REDUCE_CHUNK, b)
    nch = b // chunk
    partials = jax.vmap(
        lambda vv, cd: jax.ops.segment_sum(vv, cd, num_segments))(
        values.reshape(nch, chunk), codes.reshape(nch, chunk))
    return _kahan_combine(partials)


# ---------------------------------------------------------------------------
# Device sort (jax.lax.sort on bit-transformed keys)
# ---------------------------------------------------------------------------

def _sortable_bits(values: jax.Array, valid: jax.Array, descending: bool,
                   nulls_first: bool) -> List[jax.Array]:
    """Map (values, valid) to one or two uint32 key lanes whose lexicographic
    unsigned order equals the requested total order (nulls at extremes; NaN
    above every number, matching arrow).

    Works in both x64 and 32-bit-only (real TPU) modes: 64-bit inputs (only
    present under x64) are split into hi/lo uint32 lanes.
    """
    v = values
    width64 = v.dtype.itemsize == 8
    if jnp.issubdtype(v.dtype, jnp.bool_):
        bits = v.astype(jnp.uint32)
    elif jnp.issubdtype(v.dtype, jnp.unsignedinteger):
        bits = v if width64 else v.astype(jnp.uint32)
    elif jnp.issubdtype(v.dtype, jnp.signedinteger):
        if width64:
            bits = jax.lax.bitcast_convert_type(v.astype(jnp.int64), jnp.uint64) ^ jnp.uint64(1 << 63)
        else:
            bits = jax.lax.bitcast_convert_type(v.astype(jnp.int32), jnp.uint32) ^ jnp.uint32(1 << 31)
    else:
        # canonicalize every NaN to the POSITIVE quiet NaN: its bit pattern
        # sits strictly above +inf, so NaN sorts after all numbers ascending
        # (and first descending) — exactly arrow's NaN-greatest order. The
        # old inf-substitution made NaN TIE with real +inf.
        if width64:
            f = jnp.where(jnp.isnan(v), jnp.asarray(jnp.nan, v.dtype), v)
            f = jnp.where(f == 0.0, jnp.zeros_like(f), f)  # -0.0 ties +0.0
            b = jax.lax.bitcast_convert_type(f, jnp.int64)
            bits = jnp.where(b < 0, jax.lax.bitcast_convert_type(~b, jnp.uint64),
                             jax.lax.bitcast_convert_type(b, jnp.uint64) ^ jnp.uint64(1 << 63))
        else:
            v32 = v.astype(jnp.float32)
            f = jnp.where(jnp.isnan(v32), jnp.asarray(jnp.nan, jnp.float32), v32)
            f = jnp.where(f == 0.0, jnp.zeros_like(f), f)  # -0.0 ties +0.0
            b = jax.lax.bitcast_convert_type(f, jnp.int32)
            bits = jnp.where(b < 0, jax.lax.bitcast_convert_type(~b, jnp.uint32),
                             jax.lax.bitcast_convert_type(b, jnp.uint32) ^ jnp.uint32(1 << 31))
    if descending:
        bits = ~bits
    if bits.dtype == jnp.uint64:
        hi = (bits >> jnp.uint64(32)).astype(jnp.uint32)
        lo = (bits & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        lanes = [hi, lo]
    else:
        lanes = [bits]
    # null handling: prepend a selector lane (0=null-first, 1=value, 2=null-last)
    null_sel = jnp.where(valid, jnp.uint32(1), jnp.uint32(0 if nulls_first else 2))
    return [null_sel] + [jnp.where(valid, l, jnp.uint32(0)) for l in lanes]


def _stage_f64_sort_lanes(table, node, bucket: int,
                          stage_cache: Optional[dict]):
    """EXACT float64 sort key in 32-bit mode: the order-preserving bit
    transform (sign-magnitude -> total order, canonical NaN above +inf)
    applied to the full 64-bit pattern ON HOST, then split into (hi, lo)
    uint32 lanes the device sort consumes as two consecutive keys. No
    precision is lost — this removes the Q1-style money-sort fallback.

    `node` may be ANY f64-typed expression, not just a plain Column (r4
    verdict item 6): the host evaluates the derived key ONCE in exact
    float64 (e.g. Q1's price*(1-discount)), the lanes split from that, and
    the sort itself stays on device. Cached with the partition under the
    expression key."""
    node = _peel_alias(node)
    # UDF-containing keys never cache: a UDF may be non-deterministic and
    # its _key uses id(fn), which CPython can reuse after GC — a stale hit
    # would silently mis-sort (same rule as Expression._memoizable)
    cacheable = stage_cache is not None and node._memoizable()
    key = ("__f64lanes__", node._key(), bucket)
    cached = stage_cache.get(key) if cacheable else None
    if cached is not None:
        return cached
    s = _eval_lane_series(table, node)
    if s is None:
        return None
    n = len(s)
    arr = s.to_arrow()
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    vals = np.asarray(pc.fill_null(arr, 0.0), dtype=np.float64)
    # canonical positive quiet NaN: bit pattern above +inf -> NaN-greatest,
    # matching _sortable_bits and arrow; -0.0 canonicalizes to +0.0 (arrow
    # ties signed zeros under the stable sort — distinct bit patterns would
    # order them and break the tiebreak parity)
    vals = np.where(np.isnan(vals), np.float64("nan"), vals)
    vals = np.where(vals == 0.0, np.float64(0.0), vals)
    bits = vals.view(np.uint64)
    flipped = np.where((bits >> np.uint64(63)) == 1, ~bits,
                       bits ^ np.uint64(1 << 63))
    if bucket > n:
        flipped = np.concatenate([flipped,
                                  np.zeros(bucket - n, dtype=np.uint64)])
    hi = (flipped >> np.uint64(32)).astype(np.uint32)
    lo = (flipped & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    out = (jnp.asarray(hi), jnp.asarray(lo),
           jnp.asarray(_staged_validity(arr, n, bucket)))
    if cacheable:
        stage_cache[key] = out
    return out


def device_table_argsort(table, sort_keys, descending=None, nulls_first=None,
                         stage_cache: Optional[dict] = None):
    """Argsort indices for a Table computed ON DEVICE (keys staged/compiled
    like projections, then one `jax.lax.sort` over the bit-transformed
    lanes). Matches Table.argsort's ordering exactly, including the
    nulls-follow-direction default. Returns np.ndarray[int] or None when any
    key is device-ineligible."""
    from ..datatypes import DataType
    from ..table import _norm_flag

    n = len(table)
    if n == 0:
        return None
    keys = list(sort_keys)
    k = len(keys)
    desc = _norm_flag(descending, k, False)
    nf = _norm_flag(nulls_first, k, None)
    f64_lane_keys: Dict[int, Tuple[str, Any]] = {}
    if not x64_enabled():
        # float64 keys must not sort in float32 (spurious ties reorder rows
        # vs the host), and epoch keys cannot narrow to int32 at all. ANY
        # f64/epoch-typed key — plain column OR computed expression (Q1's
        # price*(1-discount) money sorts) — evaluates once on host in exact
        # 64-bit and sorts on device via host-split (hi, lo) lanes.
        from ..expressions import normalize_literals

        try:
            pre = [normalize_literals(e._node, table.schema) for e in keys]
        except (ValueError, KeyError):
            return None
        for i, nd in enumerate(pre):
            try:
                dt_ = nd.to_field(table.schema).dtype
            except (ValueError, KeyError):
                return None
            if dt_ == DataType.float64():
                f64_lane_keys[i] = ("f64", nd)
            elif dt_.kind in _EPOCH_KINDS:
                f64_lane_keys[i] = ("epoch", nd)
            # other keys are vetted by _stage_and_run below — checking
            # compilability here too would walk every tree twice per sort
    entries: List = [None] * k
    b = size_bucket(n)
    # lane keys stage FIRST (cheap host work that can decline) so a decline
    # never wastes the device staging/compile of the other keys
    for i, (kind, nd) in f64_lane_keys.items():
        entry = (_stage_f64_sort_lanes(table, nd, b, stage_cache)
                 if kind == "f64"
                 else _stage_epoch_expr_lanes(table, nd, b, stage_cache))
        if entry is None:
            return None
        entries[i] = entry
    non_lane = [(i, e) for i, e in enumerate(keys) if i not in f64_lane_keys]
    if non_lane:
        staged = _stage_and_run(table, [e for _, e in non_lane], stage_cache)
        if staged is None:
            return None
        outs = staged[0]
        for (i, _), vm in zip(non_lane, outs):
            entries[i] = vm
    nf_resolved = [(f if f is not None else d) for f, d in zip(nf, desc)]
    idx = device_argsort(entries, desc, nf_resolved, n)
    return np.asarray(jax.device_get(idx))[:n]


def device_argsort(key_cols: Sequence[Tuple],
                   descending: Sequence[bool], nulls_first: Sequence[bool],
                   length: int) -> jax.Array:
    """Stable multi-key argsort on device; padding rows sort to the very end.
    Each key is (values, valid) — bit-transformed by _sortable_bits — or an
    exact pre-split (hi_u32, lo_u32, valid) lane triple (64-bit keys staged
    in 32-bit mode)."""
    b = key_cols[0][0].shape[0]
    operands: List[jax.Array] = []
    inbounds = jnp.arange(b) < length
    pad_sel = jnp.where(inbounds, jnp.uint32(0), jnp.uint32(1))
    operands.append(pad_sel)  # padding rows after all real rows
    for entry, d, nf in zip(key_cols, descending, nulls_first):
        if len(entry) == 3:
            hi, lo, m = entry
            # bitwise-not of the 64-bit pattern distributes across the split
            lanes_ = [~hi, ~lo] if d else [hi, lo]
            null_sel = jnp.where(m, jnp.uint32(1),
                                 jnp.uint32(0 if nf else 2))
            ops = [null_sel] + [jnp.where(m, l, jnp.uint32(0))
                                for l in lanes_]
        else:
            v, m = entry
            ops = _sortable_bits(v, m, d, nf)
        for lane in ops:
            operands.append(jnp.where(inbounds, lane, jnp.uint32(0)))
    idx = jnp.arange(b, dtype=jnp.int32)
    out = jax.lax.sort(tuple(operands) + (idx,), num_keys=len(operands), is_stable=True)
    return out[-1]


# ---------------------------------------------------------------------------
# Device hash (for shuffle bucketing; 2x32-bit lanes, TPU-friendly)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("num_buckets",))
def hash_buckets(columns: Tuple[jax.Array, ...], valids: Tuple[jax.Array, ...],
                 num_buckets: int) -> jax.Array:
    """Combine column hashes -> bucket id per row (murmur-style 32-bit mixing)."""
    h = jnp.zeros(columns[0].shape[0], dtype=jnp.uint32)
    for v, m in zip(columns, valids):
        hv = _hash32(v)
        hv = jnp.where(m, hv, jnp.uint32(0x9E3779B9))
        h = _mix32(h ^ hv)
    return (h % jnp.uint32(num_buckets)).astype(jnp.int32)


def _hash32(v: jax.Array) -> jax.Array:
    if jnp.issubdtype(v.dtype, jnp.floating):
        f = v.astype(jnp.float32)
        f = jnp.where(f == 0.0, jnp.zeros_like(f), f)  # -0.0 == 0.0
        x = jax.lax.bitcast_convert_type(f, jnp.uint32)
    elif v.dtype == jnp.bool_:
        x = v.astype(jnp.uint32)
    elif v.dtype.itemsize == 8:
        x64 = v.astype(jnp.int64)
        lo = (x64 & 0xFFFFFFFF).astype(jnp.uint32)
        hi = ((x64 >> 32) & 0xFFFFFFFF).astype(jnp.uint32)
        x = _mix32(lo) ^ hi
    else:
        x = v.astype(jnp.int32).astype(jnp.uint32)
    return _mix32(x)


def _mix32(x: jax.Array) -> jax.Array:
    x = x.astype(jnp.uint32)
    x = (x ^ (x >> jnp.uint32(16))) * jnp.uint32(0x85EBCA6B)
    x = (x ^ (x >> jnp.uint32(13))) * jnp.uint32(0xC2B2AE35)
    return x ^ (x >> jnp.uint32(16))
