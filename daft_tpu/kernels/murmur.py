"""MurmurHash3 x86 32-bit, vectorized with numpy, for Iceberg bucket transforms.

Matches the Iceberg spec's bucket hashing (reference uses it in
src/daft-dsl/src/functions/partitioning/); ints hash as little-endian 8 bytes,
strings/binary as UTF-8 bytes, seed 0.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)


def _rotl32(x: np.ndarray, r: int) -> np.ndarray:
    r = np.uint32(r)
    with np.errstate(over="ignore"):
        return (x << r) | (x >> (np.uint32(32) - r))


def _mm3_scalar_bytes(data: bytes) -> int:
    """Reference scalar murmur3_32 over bytes, seed 0."""
    h = np.uint32(0)
    n = len(data)
    nblocks = n // 4
    with np.errstate(over="ignore"):
        for i in range(nblocks):
            k = np.uint32(int.from_bytes(data[i * 4:i * 4 + 4], "little"))
            k = np.uint32(k * _C1)
            k = _rotl32(k, 15)
            k = np.uint32(k * _C2)
            h ^= k
            h = _rotl32(h, 13)
            h = np.uint32(h * np.uint32(5) + np.uint32(0xE6546B64))
        k = np.uint32(0)
        tail = data[nblocks * 4:]
        if len(tail) >= 3:
            k ^= np.uint32(tail[2]) << np.uint32(16)
        if len(tail) >= 2:
            k ^= np.uint32(tail[1]) << np.uint32(8)
        if len(tail) >= 1:
            k ^= np.uint32(tail[0])
            k = np.uint32(k * _C1)
            k = _rotl32(k, 15)
            k = np.uint32(k * _C2)
            h ^= k
        h ^= np.uint32(n)
        h ^= h >> np.uint32(16)
        h = np.uint32(h * np.uint32(0x85EBCA6B))
        h ^= h >> np.uint32(13)
        h = np.uint32(h * np.uint32(0xC2B2AE35))
        h ^= h >> np.uint32(16)
    return int(np.int32(h))


def _mm3_long_vec(vals: np.ndarray) -> np.ndarray:
    """Vectorized murmur3_32 of int64 values encoded as 8 little-endian bytes."""
    v = vals.astype(np.int64).view(np.uint64)
    k1 = (v & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    k2 = (v >> np.uint64(32)).astype(np.uint32)
    with np.errstate(over="ignore"):
        h = np.zeros(len(vals), dtype=np.uint32)
        for k in (k1, k2):
            k = (k * _C1).astype(np.uint32)
            k = _rotl32(k, 15)
            k = (k * _C2).astype(np.uint32)
            h ^= k
            h = _rotl32(h, 13)
            h = (h * np.uint32(5) + np.uint32(0xE6546B64)).astype(np.uint32)
        h ^= np.uint32(8)
        h ^= h >> np.uint32(16)
        h = (h * np.uint32(0x85EBCA6B)).astype(np.uint32)
        h ^= h >> np.uint32(13)
        h = (h * np.uint32(0xC2B2AE35)).astype(np.uint32)
        h ^= h >> np.uint32(16)
    return h.view(np.int32)


def murmur3_32_arrow(arr: pa.Array) -> pa.Array:
    t = arr.type
    mask = pc.is_valid(arr) if arr.null_count else None
    if pa.types.is_integer(t):
        filled = pc.fill_null(arr, 0) if arr.null_count else arr
        out = _mm3_long_vec(np.asarray(filled.cast(pa.int64())))
        res = pa.array(out, type=pa.int32())
    elif pa.types.is_date32(t):
        return murmur3_32_arrow(arr.cast(pa.int32()))
    elif pa.types.is_timestamp(t) or pa.types.is_time(t):
        return murmur3_32_arrow(arr.cast(pa.int64()))
    elif pa.types.is_string(t) or pa.types.is_large_string(t) or pa.types.is_binary(t) or pa.types.is_large_binary(t):
        from .. import native

        if native.available():
            from .host_hash import _offsets_and_bytes

            offs, data, _filled = _offsets_and_bytes(
                arr if pa.types.is_binary(arr.type) or pa.types.is_large_binary(arr.type)
                else arr.cast(pa.large_binary()))
            valid = np.asarray(mask, dtype=bool) if mask is not None else None
            out = native.murmur3_bytes(data, offs, valid, 0)
            res = pa.array(out, type=pa.int32())
            if mask is not None:
                res = pc.if_else(mask, res, pa.nulls(len(res), pa.int32()))
            return res
        vals = arr.to_pylist()
        out = [
            None if v is None else _mm3_scalar_bytes(v.encode() if isinstance(v, str) else bytes(v))
            for v in vals
        ]
        return pa.array(out, type=pa.int32())
    else:
        raise ValueError(f"murmur3_32 unsupported for {t}")
    if mask is not None:
        res = pc.if_else(mask, res, pa.nulls(len(res), pa.int32()))
    return res
