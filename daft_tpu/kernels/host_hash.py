"""Vectorized 64-bit hashing of Arrow arrays on the host.

Role-equivalent to the reference's hashing kernels (src/daft-core/src/kernels/hashing.rs);
implementation is a fresh numpy-vectorized design: fixed-width columns hash via a
splitmix64-style finalizer over the raw value buffer; var-len (string/binary) columns use
a vectorized 64-bit polynomial rolling hash over the flattened byte buffer with
`np.add.reduceat` segment reduction, then the same finalizer.

Hashes are used for: hash partitioning (shuffles), `Expression.hash()`, minhash and the
probe-table fallback. Join/groupby equality never relies on hash equality alone.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)
_MIX1 = np.uint64(0xBF58476D1CE4E5B9)
_MIX2 = np.uint64(0x94D049BB133111EB)
_NULL_HASH = np.uint64(0x7FB5D329728EA185)
_POLY_P = np.uint64(0x100000001B3)  # FNV prime reused as polynomial base


def _splitmix64(x: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        x = (x + _GOLDEN).astype(np.uint64)
        x = (x ^ (x >> np.uint64(30))) * _MIX1
        x = (x ^ (x >> np.uint64(27))) * _MIX2
        x = x ^ (x >> np.uint64(31))
    return x


def hash_array(arr: pa.Array, seed: np.ndarray | int | None = None) -> np.ndarray:
    """Hash an arrow array to uint64 per row. `seed` may be a scalar or per-row array
    (used to combine hashes across columns: h = hash(col, seed=h_prev))."""
    n = len(arr)
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    if seed is None:
        seeds = np.zeros(n, dtype=np.uint64)
    elif np.isscalar(seed):
        seeds = np.full(n, np.uint64(seed), dtype=np.uint64)
    else:
        seeds = seed.astype(np.uint64, copy=False)

    t = arr.type
    if pa.types.is_null(t):
        base = np.full(n, _NULL_HASH, dtype=np.uint64)
        return _splitmix64(base ^ seeds)
    if pa.types.is_dictionary(t):
        arr = arr.cast(t.value_type)
        t = arr.type

    if pa.types.is_boolean(t):
        vals = arr.cast(pa.uint8())
        return _hash_fixed(vals, seeds)
    if pa.types.is_decimal(t):
        return _hash_decimal128(arr, seeds)
    if (
        pa.types.is_integer(t) or pa.types.is_floating(t)
        or pa.types.is_date(t) or pa.types.is_timestamp(t)
        or pa.types.is_time(t) or pa.types.is_duration(t)
    ):
        return _hash_fixed(arr, seeds)
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        arr = arr.cast(pa.large_binary())
        t = arr.type
    if pa.types.is_binary(t) or pa.types.is_large_binary(t):
        return _hash_varlen(arr, seeds)
    if pa.types.is_fixed_size_binary(t):
        arr = arr.cast(pa.large_binary())
        return _hash_varlen(arr, seeds)
    if pa.types.is_list(t) or pa.types.is_large_list(t) or pa.types.is_fixed_size_list(t):
        # NB: use .values (keeps slots behind null rows), never .flatten() (drops them,
        # which would desync offsets for every row after a null).
        if pa.types.is_fixed_size_list(t):
            size = t.list_size
            offs = (np.arange(n + 1, dtype=np.int64) + arr.offset) * size
            child = arr.values
        else:
            offs = np.asarray(arr.offsets).astype(np.int64)
            child = arr.values
        lo, hi = int(offs[0]), int(offs[-1])
        inner = hash_array(child.slice(lo, hi - lo)) if hi > lo else np.empty(0, np.uint64)
        return _hash_segments_from_offsets(arr, offs - lo, inner, seeds, n)
    if pa.types.is_struct(t):
        h = seeds
        for i in range(t.num_fields):
            h = hash_array(arr.field(i), seed=h)
        return _apply_null_mask(arr, h, seeds)
    raise ValueError(f"cannot hash arrow type {t}")


def _valid_mask(arr: pa.Array) -> np.ndarray | None:
    if arr.null_count == 0:
        return None
    return np.asarray(pc.is_valid(arr), dtype=bool)


def _apply_null_mask(arr: pa.Array, h: np.ndarray, seeds: np.ndarray) -> np.ndarray:
    m = _valid_mask(arr)
    if m is not None:
        h = np.where(m, h, _splitmix64(_NULL_HASH ^ seeds))
    return h


def _hash_fixed(arr: pa.Array, seeds: np.ndarray) -> np.ndarray:
    t = arr.type
    if pa.types.is_floating(t):
        vals = np.nan_to_num(_values_np(arr).astype(np.float64), nan=0.0)
        # normalize -0.0 == 0.0
        vals = vals + 0.0
        bits = vals.view(np.uint64)
    else:
        bits = _values_np(arr).astype(np.int64, copy=False).view(np.uint64)
    from .. import native

    if native.available():
        return native.hash_fixed64(bits, _valid_mask(arr), seeds)
    h = _splitmix64(bits ^ seeds)
    return _apply_null_mask(arr, h, seeds)


def _values_np(arr: pa.Array) -> np.ndarray:
    """Physical values of a primitive arrow array as numpy (nulls filled
    arbitrarily). Temporal storage casts to its integer physical type
    BEFORE the null fill: pyarrow has no int->date32 scalar cast, so
    filling a nullable date column first crashed every hash
    shuffle/join/filter keyed on it (caught by the exchange byte-identity
    matrix)."""
    if pa.types.is_date32(arr.type):
        arr = arr.cast(pa.int32())
    elif pa.types.is_date64(arr.type):
        arr = arr.cast(pa.int64())
    elif pa.types.is_timestamp(arr.type) or pa.types.is_duration(arr.type):
        arr = arr.cast(pa.int64())
    elif pa.types.is_time(arr.type):
        arr = arr.cast(pa.int64() if arr.type.bit_width == 64 else pa.int32())
    if arr.null_count:
        arr = pc.fill_null(arr, _zero_scalar(arr.type))
    return np.asarray(arr)


def _zero_scalar(t: pa.DataType):
    if pa.types.is_timestamp(t) or pa.types.is_duration(t) or pa.types.is_time(t) or pa.types.is_date(t):
        return pa.scalar(0, pa.int64()).cast(t)
    return pa.scalar(0, t) if not pa.types.is_boolean(t) else pa.scalar(False, t)


def _offsets_and_bytes(arr: pa.Array):
    t = arr.type
    assert pa.types.is_large_binary(t) or pa.types.is_binary(t)
    if arr.null_count:
        arr = pc.fill_null(arr, b"")
    buffers = arr.buffers()
    off_dtype = np.int64 if pa.types.is_large_binary(t) else np.int32
    offs = np.frombuffer(buffers[1], dtype=off_dtype, count=len(arr) + 1 + arr.offset)[arr.offset:]
    data = np.frombuffer(buffers[2], dtype=np.uint8) if buffers[2] is not None else np.empty(0, np.uint8)
    return offs.astype(np.int64, copy=False), data, arr


def _hash_varlen(orig: pa.Array, seeds: np.ndarray) -> np.ndarray:
    n = len(orig)
    offs, data, filled = _offsets_and_bytes(orig if not isinstance(orig, pa.ChunkedArray) else orig.combine_chunks())
    from .. import native

    if native.available():
        return native.hash_bytes(data, offs, _valid_mask(orig), seeds)
    lengths = offs[1:] - offs[:-1]
    start, end = offs[0], offs[-1]
    seg = data[start:end].astype(np.uint64)
    if len(seg):
        # position of each byte within its row
        row_of_byte = np.repeat(np.arange(n, dtype=np.int64), lengths)
        pos = np.arange(len(seg), dtype=np.int64) - (offs[:-1] - start)[row_of_byte]
        with np.errstate(over="ignore"):
            weights = np.power(_POLY_P, pos.astype(np.uint64))
            terms = (seg + np.uint64(1)) * weights
        sums = _segment_sums(terms, offs[:-1] - start, lengths, n)
    else:
        sums = np.zeros(n, dtype=np.uint64)
    with np.errstate(over="ignore"):
        h = _splitmix64(sums ^ (np.uint64(0xC2B2AE3D27D4EB4F) * lengths.astype(np.uint64)) ^ seeds)
    return _apply_null_mask(orig, h, seeds)


def _segment_sums(terms: np.ndarray, starts: np.ndarray, lengths: np.ndarray, n: int) -> np.ndarray:
    """Per-row sums of `terms` segmented by (starts, lengths); empty rows sum to 0.

    `np.add.reduceat` mishandles empty segments (it returns terms[idx] and, when
    clamped, corrupts the previous row), so reduce only over non-empty rows — their
    start offsets are strictly increasing and cover the byte buffer contiguously.
    """
    sums = np.zeros(n, dtype=np.uint64)
    nz = lengths > 0
    if nz.any():
        with np.errstate(over="ignore"):
            sums[nz] = np.add.reduceat(terms, starts[nz])
    return sums


def _hash_decimal128(arr: pa.Array, seeds: np.ndarray) -> np.ndarray:
    """Hash decimals exactly from their little-endian two's-complement representation
    (the reference hashes decimals by value, not via a lossy float cast). Narrow
    decimals are widened to decimal128 so equal values hash equally across widths;
    decimal256 folds its four uint64 lanes."""
    t = arr.type
    if t.byte_width < 16:
        arr = arr.cast(pa.decimal128(t.precision, t.scale))
        t = arr.type
    filled = pc.fill_null(arr, pa.scalar(0, t).cast(t)) if arr.null_count else arr
    n = len(filled)
    lanes_per = t.byte_width // 8
    lanes = np.frombuffer(filled.buffers()[1], dtype=np.uint64, count=lanes_per * (n + filled.offset))
    lanes = lanes[lanes_per * filled.offset:]
    with np.errstate(over="ignore"):
        h = seeds
        for i in range(lanes_per - 1, -1, -1):
            h = _splitmix64(lanes[i::lanes_per] ^ h)
    return _apply_null_mask(arr, h, seeds)


def _hash_segments_from_offsets(
    arr: pa.Array, offs: np.ndarray, inner_hashes: np.ndarray, seeds: np.ndarray, n: int
) -> np.ndarray:
    from .. import native

    if native.available():
        return native.hash_segments(inner_hashes, offs, _valid_mask(arr), seeds)
    lengths = offs[1:] - offs[:-1]
    if len(inner_hashes):
        pos = np.arange(len(inner_hashes), dtype=np.int64) - np.repeat(offs[:-1], lengths)
        with np.errstate(over="ignore"):
            terms = inner_hashes * np.power(_POLY_P, pos.astype(np.uint64))
        sums = _segment_sums(terms, offs[:-1], lengths, n)
    else:
        sums = np.zeros(n, dtype=np.uint64)
    with np.errstate(over="ignore"):
        h = _splitmix64(sums ^ lengths.astype(np.uint64) ^ seeds)
    return _apply_null_mask(arr, h, seeds)


def hash_table_columns(columns: list, seed: int = 0) -> np.ndarray:
    """Combined row hash across multiple arrow arrays."""
    if not columns:
        raise ValueError("need at least one column to hash")
    h = np.full(len(columns[0]), np.uint64(seed), dtype=np.uint64)
    for c in columns:
        h = hash_array(c, seed=h)
    return h
