"""Pallas TPU kernels for the aggregation hot path.

The TPC-H-Q1-shaped pipeline (filter mask -> K weighted segment sums over
small group cardinality) is one fused MXU program here: each grid step loads a
row block into VMEM, forms the masked one-hot group matrix, and accumulates
`one_hot.T @ values` into a (groups, K) VMEM accumulator — so ALL K aggregate
columns ride a single data pass through the 128x128 systolic array, instead of
K separate scatter-based `segment_sum` lowerings touching HBM K times.

Counts come from an exact host bincount (float32 one-hot accumulation would
silently stall at 2^24 rows per group); the kernel carries the K weighted
sums, which is where the FLOPs are.

Grid iteration on TPU is sequential per core, which makes the accumulate-into-
out_ref pattern sound (out block index is constant across steps; step 0 zeroes
it). Tests run `interpret=True` on CPU; on TPU the same call compiles to a
Mosaic kernel.

Reference role-equivalent: the grouped-aggregation kernels of
src/daft-core/src/array/ops/groups.rs + agg.rs, redesigned as a dense MXU
contraction rather than hash-bucket scatter (SURVEY.md §7 "Hard parts":
groupby on device without pointer-chasing).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_BLOCK_ROWS = 1024

# trace-time engagement counter: bumped when a deep-fused kernel is BUILT
# into a compiled agg program (bench asserts the path actually engaged)
DEEP_FUSED_TRACES = [0]


def _kernel(codes_ref, mask_ref, vals_ref, out_ref, comp_ref, *, num_groups: int):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _zero():
        out_ref[:] = jnp.zeros_like(out_ref)
        comp_ref[:] = jnp.zeros_like(comp_ref)

    codes = codes_ref[:]  # (B, 1) int32
    mask = mask_ref[:]    # (B, 1) float32 (0/1)
    group_ids = jax.lax.broadcasted_iota(jnp.int32, (1, num_groups), 1)
    one_hot = (codes == group_ids).astype(jnp.float32) * mask  # (B, G)
    # (G, B) @ (B, K) -> (G, K) on the MXU
    block = jnp.dot(one_hot.T, vals_ref[:], preferred_element_type=jnp.float32)
    # Kahan-compensated accumulation ACROSS grid steps: naive float32 adds
    # drift past 1e-6 relative on TPC-H-scale money sums (the segment_sum
    # route this kernel replaces compensates too, device.py _sum_kahan)
    y = block - comp_ref[:]
    t = out_ref[:] + y
    comp_ref[:] = (t - out_ref[:]) - y
    out_ref[:] = t


@functools.partial(jax.jit, static_argnames=("num_groups", "interpret"))
def _masked_segment_sums_padded(codes, mask, vals, num_groups: int, interpret: bool):
    n, k = vals.shape
    grid = n // _BLOCK_ROWS
    sums, _comp = pl.pallas_call(
        functools.partial(_kernel, num_groups=num_groups),
        out_shape=(jax.ShapeDtypeStruct((num_groups, k), jnp.float32),
                   jax.ShapeDtypeStruct((num_groups, k), jnp.float32)),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, k), lambda i: (i, 0)),
        ],
        out_specs=(pl.BlockSpec((num_groups, k), lambda i: (0, 0)),
                   pl.BlockSpec((num_groups, k), lambda i: (0, 0))),
        interpret=interpret,
    )(codes, mask, vals)
    return sums


def build_fused_expr_sums(pred_fn, child_fns, names, num_groups: int,
                          k: int, interpret: bool):
    """Deep-fused Q1-shaped kernel (r4 verdict weak #5): the filter
    PREDICATE and the K derived float-sum columns are evaluated INSIDE the
    pallas body from the raw staged columns, per VMEM block — the XLA
    composition materializes a pre-masked (n, K) float32 matrix in HBM as
    the pallas operand (one write + one read of n*K*4 bytes that this
    kernel never pays). `pred_fn`/`child_fns` are the expression compiler's
    closures (pure jnp over {name: (values, valid)}), so the kernel body is
    generated from the SAME compiled expressions as the host/XLA paths —
    parity by construction.

    Returns fn(codes [n,1] i32, inb [n,1] bool, *cols interleaved
    (values [n,1], valid [n,1]) per name) -> sums (num_groups, K) f32.
    n must be a multiple of _BLOCK_ROWS."""

    def kernel(codes_ref, inb_ref, *refs):
        col_refs = refs[:-2]
        out_ref, comp_ref = refs[-2], refs[-1]
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _zero():
            out_ref[:] = jnp.zeros_like(out_ref)
            comp_ref[:] = jnp.zeros_like(comp_ref)

        env = {}
        for j, name in enumerate(names):
            env[name] = (col_refs[2 * j][:][:, 0],
                         col_refs[2 * j + 1][:][:, 0])
        inb = inb_ref[:][:, 0]
        if pred_fn is not None:
            pv, pm = pred_fn(env)
            sel = pv & pm & inb  # invalid predicate rows filter out (WHERE)
        else:
            sel = inb
        cols = []
        for fn in child_fns:
            v, m = fn(env)
            cols.append(jnp.where(m & sel, v.astype(jnp.float32),
                                  jnp.float32(0)))
        vk = jnp.stack(cols, axis=1)  # (B, K) in VMEM
        codes = codes_ref[:]          # (B, 1)
        group_ids = jax.lax.broadcasted_iota(jnp.int32, (1, num_groups), 1)
        one_hot = ((codes == group_ids).astype(jnp.float32)
                   * sel.astype(jnp.float32)[:, None])
        block = jnp.dot(one_hot.T, vk, preferred_element_type=jnp.float32)
        y = block - comp_ref[:]
        t = out_ref[:] + y
        comp_ref[:] = (t - out_ref[:]) - y
        out_ref[:] = t

    def call(codes, inb, *cols):
        grid = codes.shape[0] // _BLOCK_ROWS
        blk2 = pl.BlockSpec((_BLOCK_ROWS, 1), lambda i: (i, 0))
        sums, _comp = pl.pallas_call(
            kernel,
            out_shape=(jax.ShapeDtypeStruct((num_groups, k), jnp.float32),
                       jax.ShapeDtypeStruct((num_groups, k), jnp.float32)),
            grid=(grid,),
            in_specs=[blk2, blk2] + [blk2] * len(cols),
            out_specs=(pl.BlockSpec((num_groups, k), lambda i: (0, 0)),
                       pl.BlockSpec((num_groups, k), lambda i: (0, 0))),
            interpret=interpret,
        )(codes, inb, *cols)
        # bump AFTER the pallas trace succeeded: a body/BlockSpec failure
        # falls back to the batched kernel and must not read as engagement
        DEEP_FUSED_TRACES[0] += 1
        return sums

    return call


def masked_segment_sums(codes: np.ndarray, mask: Optional[np.ndarray],
                        values: np.ndarray, num_groups: int,
                        interpret: Optional[bool] = None) -> Tuple[np.ndarray, np.ndarray]:
    """Fused sums + counts for K value columns grouped by `codes`.

    codes: (n,) int group ids in [0, num_groups); mask: (n,) bool or None;
    values: (n, K) float64/float32 (NaNs allowed where masked out).
    Returns (sums (num_groups, K) float64, counts (num_groups,) int64).

    float32 accumulation on the MXU — callers needing exact float64 sums
    (the host parity path) should use the arrow/bincount route; this kernel
    is the device-throughput path.
    """
    n = len(codes)
    k = values.shape[1]
    if n == 0:
        # grid=(0,) would skip the kernel entirely, leaving out_ref unwritten
        return np.zeros((num_groups, k)), np.zeros(num_groups, np.int64)
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    m = np.ones(n, np.float32) if mask is None else mask.astype(np.float32)
    # counts must be exact (float32 accumulation stalls at 2^24), so they come
    # from a host bincount; the kernel carries only the K weighted sums
    if mask is None:
        counts = np.bincount(codes, minlength=num_groups).astype(np.int64)
    else:
        counts = np.bincount(codes[mask], minlength=num_groups).astype(np.int64)
    # masked-out rows contribute nothing; also zero their values so NaN*0
    # poisoning cannot leak through the matmul
    vk = np.where(m[:, None] > 0, values, 0.0).astype(np.float32)
    pad = (-n) % _BLOCK_ROWS
    if pad:
        codes = np.concatenate([codes, np.zeros(pad, codes.dtype)])
        m = np.concatenate([m, np.zeros(pad, np.float32)])
        vk = np.concatenate([vk, np.zeros((pad, k), np.float32)])
    out = _masked_segment_sums_padded(
        jnp.asarray(codes.astype(np.int32)[:, None]),
        jnp.asarray(m[:, None]),
        jnp.asarray(vk),
        num_groups, interpret)
    return np.asarray(jax.device_get(out)).astype(np.float64), counts
