"""Mergeable approximate sketches: HyperLogLog, MinHash, and a quantile sketch.

Role-equivalent to the reference's src/hyperloglog/src/lib.rs, src/daft-minhash/ and
src/daft-sketch/ — required so approx_count_distinct / approx_percentiles decompose
into stage-1 (per-partition sketch) + shuffle + stage-2 (sketch merge) like every
other distributed aggregation. Implementations are vectorized numpy; the fixed-size
register arrays are device-friendly (a future pallas path can merge them with
elementwise max on TPU).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import pyarrow as pa

from .host_hash import hash_array

# ---------------------------------------------------------------------------
# HyperLogLog (dense, p=14 like the reference's NUM_REGISTERS=16384)
# ---------------------------------------------------------------------------

HLL_P = 14
HLL_M = 1 << HLL_P  # 16384 registers

#: standard error of a dense HLL with HLL_M registers (1.04/sqrt(m))
HLL_STANDARD_ERROR = 1.04 / float(HLL_M) ** 0.5


def register_ranks(hashes: np.ndarray):
    """(register index int64, rank uint8) per 64-bit hash — the scatter
    operands of a dense HLL build. Shared by the HllSketch class, the
    grouped host build (sketch/hll.py) and the device register-scatter
    (sketch/device.py), so every path places identical ranks."""
    h = hashes.astype(np.uint64, copy=False)
    idx = (h >> np.uint64(64 - HLL_P)).astype(np.int64)
    with np.errstate(over="ignore"):
        rest = (h << np.uint64(HLL_P)) | np.uint64((1 << HLL_P) - 1)
    # rank = leading zeros of remaining bits + 1; vectorized clz via binary reduction
    v = rest.copy()
    cnt = np.zeros(len(h), dtype=np.uint8)
    for sbits in (32, 16, 8, 4, 2, 1):
        s = np.uint64(sbits)
        mask = (v >> np.uint64(64 - sbits)) == 0
        cnt = np.where(mask, cnt + np.uint8(sbits), cnt)
        v = np.where(mask, v << s, v)
    rank = (cnt + 1).astype(np.uint8)
    return idx, rank


_ALPHA_INF = 1.0 / (2.0 * np.log(2.0))


def _sigma(x: np.ndarray) -> np.ndarray:
    """Ertl's sigma: sum_{k>=1} x^(2^k) * 2^(k-1) + x, vectorized with a
    fixpoint loop (x in [0,1]; x==1 diverges and is handled by the caller)."""
    x = np.asarray(x, dtype=np.float64).copy()
    y = np.ones_like(x)
    z = x.copy()
    for _ in range(128):
        x = x * x
        z_new = z + x * y
        y = y + y
        if np.array_equal(z_new, z):
            break
        z = z_new
    return z


def _tau(x: np.ndarray) -> np.ndarray:
    """Ertl's tau: (1/3) * (1 - x - sum_{k>=1} (1 - x^(2^-k))^2 * 2^-k),
    vectorized (x in [0,1]; 0 at both endpoints). Per the published
    algorithm, y halves BEFORE each term accumulates."""
    x = np.asarray(x, dtype=np.float64)
    ends = (x == 0.0) | (x == 1.0)
    x = np.where(ends, 0.5, x)  # placeholder to keep sqrt well-behaved
    y = np.ones_like(x)
    z = 1.0 - x
    for _ in range(64):
        x = np.sqrt(x)
        y = y / 2.0
        z_new = z - (1.0 - x) ** 2 * y
        if np.array_equal(z_new, z):
            break
        z = z_new
    return np.where(ends, 0.0, z / 3.0)


def estimate_from_histogram(hist: np.ndarray, m: int) -> np.ndarray:
    """Cardinality estimates from register-VALUE histograms [g, q+2]
    (hist[:, k] = number of registers holding rank k; hist[:, 0] = zero
    registers). The sketch subsystem's sparse encoding estimates straight
    from entry counts through here, never densifying 16 KiB per group.

    Ertl's improved raw estimator ("New cardinality estimation algorithms
    for HyperLogLog sketches", 2017): no bias plateaus or empirical range
    thresholds, so the subsystem's property-tested bound (relative error
    <= 2 x 1.04/sqrt(m)) holds across the whole cardinality range —
    including the n ~ 2.5m..5m zone where the original bias-corrected
    harmonic mean is known to exceed it."""
    hist = np.asarray(hist, dtype=np.float64)
    q = hist.shape[1] - 2
    mf = float(m)
    z = mf * _tau(1.0 - hist[:, q + 1] / mf)
    for k in range(q, 0, -1):
        z = 0.5 * (z + hist[:, k])
    zeros_frac = hist[:, 0] / mf
    empty = zeros_frac == 1.0  # sigma diverges at 1: an empty sketch is 0
    z = z + mf * _sigma(np.where(empty, 0.0, zeros_frac))
    with np.errstate(divide="ignore"):
        est = _ALPHA_INF * mf * mf / z
    est = np.where(empty, 0.0, est)
    # z=0 (every register saturated at q+1) means "past the estimable
    # range": report a finite ceiling instead of casting inf to uint64
    est = np.where(np.isfinite(est), est, float(1 << 63))
    return np.round(est).astype(np.uint64)


def estimate_from_registers(regs: np.ndarray) -> np.ndarray:
    """Cardinality estimates from dense register rows [..., HLL_M] (uint8)
    — vectorized over leading dims so a grouped estimate is one pass."""
    regs = np.asarray(regs, dtype=np.uint8)
    m = regs.shape[-1]
    flat = regs.reshape(-1, m)
    g = flat.shape[0]
    if g == 0:
        return np.zeros(regs.shape[:-1], dtype=np.uint64)
    q = 64 - HLL_P  # max rank is q + 1
    if int(flat.max(initial=0)) > q + 1:
        # right-length but out-of-range payload: a corrupt sketch must fail
        # as a typed engine error, not an IndexError inside np.add.at
        from ..errors import DaftValueError

        raise DaftValueError(
            f"corrupt HLL sketch: register value exceeds max rank {q + 1}")
    hist = np.zeros((g, q + 2), dtype=np.float64)
    np.add.at(hist, (np.repeat(np.arange(g), m),
                     flat.reshape(-1).astype(np.int64)), 1.0)
    return estimate_from_histogram(hist, m).reshape(regs.shape[:-1])


class HllSketch:
    """Dense HyperLogLog over 64-bit hashes. Mergeable via elementwise max."""

    __slots__ = ("registers",)

    def __init__(self, registers: Optional[np.ndarray] = None):
        self.registers = (
            np.zeros(HLL_M, dtype=np.uint8) if registers is None else registers
        )

    def add_hashes(self, hashes: np.ndarray) -> "HllSketch":
        if len(hashes) == 0:
            return self
        idx, rank = register_ranks(hashes)
        np.maximum.at(self.registers, idx, rank)
        return self

    def add_array(self, arr: pa.Array) -> "HllSketch":
        if arr.null_count:
            import pyarrow.compute as pc

            arr = arr.drop_null()
        if len(arr) == 0:
            return self
        return self.add_hashes(hash_array(arr))

    def merge(self, other: "HllSketch") -> "HllSketch":
        np.maximum(self.registers, other.registers, out=self.registers)
        return self

    def estimate(self) -> int:
        return int(estimate_from_registers(self.registers[None])[0])

    def to_bytes(self) -> bytes:
        return self.registers.tobytes()

    @staticmethod
    def from_bytes(b: bytes) -> "HllSketch":
        return HllSketch(np.frombuffer(b, dtype=np.uint8).copy())


# ---------------------------------------------------------------------------
# MinHash (permutation family a*x+b mod prime, like daft-minhash)
# ---------------------------------------------------------------------------

_MERSENNE = np.uint64((1 << 61) - 1)


def _perm_params(num_hashes: int, seed: int):
    rng = np.random.RandomState(seed)
    a = rng.randint(1, 1 << 31, size=num_hashes).astype(np.uint64) * np.uint64(2) + np.uint64(1)
    b = rng.randint(0, 1 << 31, size=num_hashes).astype(np.uint64)
    return a, b


def minhash_strings(arr: pa.Array, num_hashes: int = 64, ngram_size: int = 1, seed: int = 1) -> pa.Array:
    """Per-row MinHash signatures of whitespace-tokenized text (word ngrams)."""
    a, b = _perm_params(num_hashes, seed)
    out_sigs: List[Optional[List[int]]] = []
    for v in arr.to_pylist():
        if v is None:
            out_sigs.append(None)
            continue
        words = v.split(" ")
        if len(words) >= ngram_size:
            grams = [" ".join(words[i:i + ngram_size]) for i in range(len(words) - ngram_size + 1)]
        else:
            grams = [v]
        gh = hash_array(pa.array(grams, type=pa.large_string())).astype(np.uint64)
        with np.errstate(over="ignore"):
            sig = (gh[:, None] * a[None, :] + b[None, :]) % _MERSENNE
        out_sigs.append((sig.min(axis=0) & np.uint64(0xFFFFFFFF)).astype(np.uint32).tolist())
    return pa.array(out_sigs, type=pa.list_(pa.uint32(), num_hashes))


# ---------------------------------------------------------------------------
# Quantile sketch: mergeable weighted-sample summary (GK-lite)
# ---------------------------------------------------------------------------

#: default sample bound; rank error of a compressed summary is ~1/cap
QUANTILE_CAP = 4096


def quantile_compress(values: np.ndarray, weights: np.ndarray,
                      cap: int = QUANTILE_CAP):
    """Compress a weighted sample to at most `cap` points DETERMINISTICALLY:
    sort by value and keep the points at `cap` evenly spaced weighted ranks
    (each carrying total/cap mass). Determinism matters for the two-phase
    aggregation contract — re-running the same plan over the same partitions
    must reproduce the same estimates bit-for-bit."""
    if len(values) <= cap:
        return values, weights
    order = np.argsort(values, kind="stable")
    v = values[order]
    w = weights[order]
    total = w.sum()
    cum = np.cumsum(w) - w / 2.0
    targets = (np.arange(cap) + 0.5) / cap * total
    idx = np.clip(np.searchsorted(cum, targets), 0, len(v) - 1)
    return v[idx], np.full(cap, total / cap)


def weighted_quantiles(values: np.ndarray, weights: np.ndarray,
                       qs: Sequence[float]):
    """Interpolated quantiles of a weighted sample (midpoint rank rule);
    [None]*len(qs) when the sample is empty."""
    if len(values) == 0:
        return [None for _ in qs]
    order = np.argsort(values, kind="stable")
    v = values[order]
    w = weights[order]
    cum = np.cumsum(w)
    cum = (cum - w / 2.0) / w.sum()
    return [float(np.interp(q, cum, v)) for q in qs]


def quantile_state_to_bytes(values: np.ndarray, weights: np.ndarray,
                            cap: int = QUANTILE_CAP) -> bytes:
    """Fixed little-endian layout: uint32 cap, uint32 k, k float64 values,
    k float64 weights — the Binary-column payload the exchange ships."""
    k = len(values)
    head = np.array([cap, k], dtype="<u4").tobytes()
    return (head + np.ascontiguousarray(values, dtype="<f8").tobytes()
            + np.ascontiguousarray(weights, dtype="<f8").tobytes())


def quantile_state_from_bytes(b: bytes):
    """(values, weights, cap) from quantile_state_to_bytes output."""
    cap, k = np.frombuffer(b, dtype="<u4", count=2)
    vals = np.frombuffer(b, dtype="<f8", count=int(k), offset=8).copy()
    wts = np.frombuffer(b, dtype="<f8", count=int(k),
                        offset=8 + 8 * int(k)).copy()
    return vals, wts, int(cap)


class QuantileSketch:
    """Mergeable quantile sketch: keeps a bounded weighted sample.

    Simpler than DDSketch but mergeable and accurate to ~1/cap quantile
    (rank) error, which matches the approx_percentiles contract. Compression
    is deterministic (evenly spaced weighted ranks), so estimates do not
    depend on merge order beyond the documented rank error.
    """

    __slots__ = ("values", "weights", "cap")

    def __init__(self, cap: int = QUANTILE_CAP, values=None, weights=None):
        self.cap = cap
        self.values = np.empty(0, dtype=np.float64) if values is None else values
        self.weights = np.empty(0, dtype=np.float64) if weights is None else weights

    def add(self, vals: np.ndarray) -> "QuantileSketch":
        vals = np.asarray(vals, dtype=np.float64)
        vals = vals[~np.isnan(vals)]
        if len(vals) == 0:
            return self
        self.values = np.concatenate([self.values, vals])
        self.weights = np.concatenate([self.weights, np.ones(len(vals))])
        self._compress()
        return self

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        self.values = np.concatenate([self.values, other.values])
        self.weights = np.concatenate([self.weights, other.weights])
        self._compress()
        return self

    def _compress(self) -> None:
        self.values, self.weights = quantile_compress(
            self.values, self.weights, self.cap)

    def quantiles(self, qs: Sequence[float]):
        return weighted_quantiles(self.values, self.weights, qs)

    def to_bytes(self) -> bytes:
        return quantile_state_to_bytes(self.values, self.weights, self.cap)

    @staticmethod
    def from_bytes(b: bytes) -> "QuantileSketch":
        vals, wts, cap = quantile_state_from_bytes(b)
        return QuantileSketch(cap, vals, wts)

    def to_state(self):
        return (self.values.tolist(), self.weights.tolist(), self.cap)

    @staticmethod
    def from_state(state) -> "QuantileSketch":
        vals, wts, cap = state
        return QuantileSketch(cap, np.asarray(vals, dtype=np.float64), np.asarray(wts, dtype=np.float64))
