"""Mergeable approximate sketches: HyperLogLog, MinHash, and a quantile sketch.

Role-equivalent to the reference's src/hyperloglog/src/lib.rs, src/daft-minhash/ and
src/daft-sketch/ — required so approx_count_distinct / approx_percentiles decompose
into stage-1 (per-partition sketch) + shuffle + stage-2 (sketch merge) like every
other distributed aggregation. Implementations are vectorized numpy; the fixed-size
register arrays are device-friendly (a future pallas path can merge them with
elementwise max on TPU).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import pyarrow as pa

from .host_hash import hash_array

# ---------------------------------------------------------------------------
# HyperLogLog (dense, p=14 like the reference's NUM_REGISTERS=16384)
# ---------------------------------------------------------------------------

HLL_P = 14
HLL_M = 1 << HLL_P  # 16384 registers


class HllSketch:
    """Dense HyperLogLog over 64-bit hashes. Mergeable via elementwise max."""

    __slots__ = ("registers",)

    def __init__(self, registers: Optional[np.ndarray] = None):
        self.registers = (
            np.zeros(HLL_M, dtype=np.uint8) if registers is None else registers
        )

    def add_hashes(self, hashes: np.ndarray) -> "HllSketch":
        if len(hashes) == 0:
            return self
        h = hashes.astype(np.uint64, copy=False)
        idx = (h >> np.uint64(64 - HLL_P)).astype(np.int64)
        with np.errstate(over="ignore"):
            rest = (h << np.uint64(HLL_P)) | np.uint64((1 << HLL_P) - 1)
        # rank = leading zeros of remaining bits + 1; vectorized clz via binary reduction
        v = rest.copy()
        cnt = np.zeros(len(h), dtype=np.uint8)
        for sbits in (32, 16, 8, 4, 2, 1):
            s = np.uint64(sbits)
            mask = (v >> np.uint64(64 - sbits)) == 0
            cnt = np.where(mask, cnt + np.uint8(sbits), cnt)
            v = np.where(mask, v << s, v)
        rank = (cnt + 1).astype(np.uint8)
        np.maximum.at(self.registers, idx, rank)
        return self

    def add_array(self, arr: pa.Array) -> "HllSketch":
        if arr.null_count:
            import pyarrow.compute as pc

            arr = arr.drop_null()
        if len(arr) == 0:
            return self
        return self.add_hashes(hash_array(arr))

    def merge(self, other: "HllSketch") -> "HllSketch":
        np.maximum(self.registers, other.registers, out=self.registers)
        return self

    def estimate(self) -> int:
        m = float(HLL_M)
        regs = self.registers.astype(np.float64)
        alpha = 0.7213 / (1.0 + 1.079 / m)
        raw = alpha * m * m / np.sum(np.exp2(-regs))
        zeros = int(np.count_nonzero(self.registers == 0))
        if raw <= 2.5 * m and zeros:
            raw = m * np.log(m / zeros)  # linear counting for small cardinalities
        return int(round(raw))

    def to_bytes(self) -> bytes:
        return self.registers.tobytes()

    @staticmethod
    def from_bytes(b: bytes) -> "HllSketch":
        return HllSketch(np.frombuffer(b, dtype=np.uint8).copy())


# ---------------------------------------------------------------------------
# MinHash (permutation family a*x+b mod prime, like daft-minhash)
# ---------------------------------------------------------------------------

_MERSENNE = np.uint64((1 << 61) - 1)


def _perm_params(num_hashes: int, seed: int):
    rng = np.random.RandomState(seed)
    a = rng.randint(1, 1 << 31, size=num_hashes).astype(np.uint64) * np.uint64(2) + np.uint64(1)
    b = rng.randint(0, 1 << 31, size=num_hashes).astype(np.uint64)
    return a, b


def minhash_strings(arr: pa.Array, num_hashes: int = 64, ngram_size: int = 1, seed: int = 1) -> pa.Array:
    """Per-row MinHash signatures of whitespace-tokenized text (word ngrams)."""
    a, b = _perm_params(num_hashes, seed)
    out_sigs: List[Optional[List[int]]] = []
    for v in arr.to_pylist():
        if v is None:
            out_sigs.append(None)
            continue
        words = v.split(" ")
        if len(words) >= ngram_size:
            grams = [" ".join(words[i:i + ngram_size]) for i in range(len(words) - ngram_size + 1)]
        else:
            grams = [v]
        gh = hash_array(pa.array(grams, type=pa.large_string())).astype(np.uint64)
        with np.errstate(over="ignore"):
            sig = (gh[:, None] * a[None, :] + b[None, :]) % _MERSENNE
        out_sigs.append((sig.min(axis=0) & np.uint64(0xFFFFFFFF)).astype(np.uint32).tolist())
    return pa.array(out_sigs, type=pa.list_(pa.uint32(), num_hashes))


# ---------------------------------------------------------------------------
# Quantile sketch: mergeable reservoir-of-sorted-samples (GK-lite)
# ---------------------------------------------------------------------------

class QuantileSketch:
    """Mergeable quantile sketch: keeps a bounded uniform sample with weights.

    Simpler than DDSketch but mergeable and accurate to ~1/cap quantile error,
    which matches the approx_percentiles contract.
    """

    __slots__ = ("values", "weights", "cap", "_rng")

    def __init__(self, cap: int = 4096, values=None, weights=None, seed: int = 0x5EED):
        self.cap = cap
        self.values = np.empty(0, dtype=np.float64) if values is None else values
        self.weights = np.empty(0, dtype=np.float64) if weights is None else weights
        self._rng = np.random.RandomState(seed & 0x7FFFFFFF)

    def add(self, vals: np.ndarray) -> "QuantileSketch":
        vals = np.asarray(vals, dtype=np.float64)
        vals = vals[~np.isnan(vals)]
        if len(vals) == 0:
            return self
        self.values = np.concatenate([self.values, vals])
        self.weights = np.concatenate([self.weights, np.ones(len(vals))])
        self._compress()
        return self

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        self.values = np.concatenate([self.values, other.values])
        self.weights = np.concatenate([self.weights, other.weights])
        self._compress()
        return self

    def _compress(self) -> None:
        if len(self.values) <= self.cap:
            return
        total = self.weights.sum()
        keep = self.cap
        idx = self._rng.choice(len(self.values), size=keep, replace=False,
                               p=self.weights / total)
        self.values = self.values[idx]
        self.weights = np.full(keep, total / keep)

    def quantiles(self, qs: Sequence[float]):
        if len(self.values) == 0:
            return [None for _ in qs]
        order = np.argsort(self.values)
        v = self.values[order]
        w = self.weights[order]
        cum = np.cumsum(w)
        cum = (cum - w / 2.0) / w.sum()
        return [float(np.interp(q, cum, v)) for q in qs]

    def to_state(self):
        return (self.values.tolist(), self.weights.tolist(), self.cap)

    @staticmethod
    def from_state(state) -> "QuantileSketch":
        vals, wts, cap = state
        return QuantileSketch(cap, np.asarray(vals, dtype=np.float64), np.asarray(wts, dtype=np.float64))
