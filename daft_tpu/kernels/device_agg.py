"""Fused device groupby-aggregation.

ONE jitted program per plan shape evaluates every aggregation input projection
and its masked segment reduction on device, with an optional fused filter
predicate that stays a mask (no host compaction) — the TPU analog of the
reference's fused streaming pipeline (src/daft-local-execution/src/pipeline.rs:141-211
and the grouped-agg sinks in src/daft-table/src/ops/agg.rs).

Division of labor (SURVEY §7): group keys compute their dense codes ON
DEVICE (_group_codes_kernel: sort + boundary scan + first-occurrence
remap) for 1-4 stageable keys — integer/date values, plain string columns
via their sorted dictionary codes, multi-key via mixed-radix packing
(null-free); anything else falls back to the host dictionary encode
(Table._group_codes). Either way the VPU does the O(rows) work:
projections fused into masked `segment_sum/min/max` reductions with
static segment counts (padded to a power of two so XLA compiles once per
bucket, not once per cardinality).

32-bit mode (real TPUs, x64 off): float64 inputs compute as float32; per-call
partials return to the host which combines across partitions in float64, so
multi-partition totals keep ~1e-7 relative accuracy. Integer sums narrow to
int32 and are overflow-guarded: the kernel also returns max|v| and the masked
row count, and the host re-runs that aggregate on the host path if
n * max|v| could exceed int32 (rare; correctness over speed).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..datatypes import DataType
from .device import (
    compile_projection,
    segment_reduce,
    size_bucket,
    stage_table_columns,
    x64_enabled,
)

# agg kinds with a device segment reduction. mean decomposes to sum+count.
_DEVICE_AGG_KINDS = {"sum", "count", "min", "max", "mean"}

_AGG_CACHE: Dict = {}


def _unwrap(expr):
    from ..expressions import AggExpr, Alias

    node = expr._node
    while isinstance(node, Alias):
        node = node.child
    return node if isinstance(node, AggExpr) else None


@functools.partial(jax.jit, static_argnames=())
def _group_codes_kernel(vals, valid, n):
    """Dense group codes for ONE integer key column, fully on device:
    sort -> boundary detect -> scan -> scatter, then remap codes to
    FIRST-OCCURRENCE order so the output group order matches the host
    dictionary-encode exactly (including the SQL rule that null keys form
    one group). Returns (codes [b] int32, num_groups, first_rows [b],
    uniq_vals [b], uniq_valid [b]) — the uniq arrays are meaningful for the
    first num_groups lanes, ordered by first occurrence."""
    b = vals.shape[0]
    idx = jnp.arange(b, dtype=jnp.int32)
    oob = idx >= n                      # padding lanes beyond the real rows
    isnull = (~valid) & (~oob)          # null KEYS group together (SQL)
    big = jnp.iinfo(vals.dtype).max
    k = jnp.where(valid, vals, big)
    perm = jnp.lexsort((k, isnull.astype(jnp.int32), oob.astype(jnp.int32)))
    sk = k[perm]
    snull = isnull[perm]
    soob = oob[perm]
    prev_diff = jnp.concatenate([
        jnp.ones((1,), bool),
        (sk[1:] != sk[:-1]) | (snull[1:] != snull[:-1])])
    boundary = (~soob) & prev_diff
    codes_sorted = jnp.cumsum(boundary.astype(jnp.int32)) - 1
    codes_sorted = jnp.maximum(codes_sorted, 0)  # padding lanes -> group 0
    codes = jnp.zeros(b, jnp.int32).at[perm].set(codes_sorted)
    num_groups = jnp.sum(boundary.astype(jnp.int32))
    # first-occurrence row per group; padding contributes the sentinel b
    first = jnp.full(b, b, jnp.int32).at[codes].min(jnp.where(oob, b, idx))
    order = jnp.argsort(first)          # empty/sentinel groups sort last
    inv = jnp.zeros(b, jnp.int32).at[order].set(jnp.arange(b, dtype=jnp.int32))
    codes = inv[codes]
    first_rows = first[order]
    safe_rows = jnp.minimum(first_rows, b - 1)
    return codes, num_groups, first_rows, vals[safe_rows], valid[safe_rows]


def _stage_group_key(table, key_expr, cache):
    """(vals, valid) int lanes for ONE group key: integer/date expressions
    via the join-key stager; plain STRING columns via their sorted
    dictionary codes (dense ints already — the device kernel neither knows
    nor cares that they decode to text); transformed-string keys
    (upper/substr/length/fill_null chains over one string column) via a
    host transform of the dictionary gathered by code
    (device.dict_transform_lane)."""
    from ..expressions import normalize_literals
    from .device import (_plain_string_column, _rewrite_between,
                         _string_dict_value_shape, dict_transform_lane,
                         size_bucket)
    from .device_join import _stage_key

    staged = _stage_key(table, key_expr, cache)
    if staged is not None:
        return staged
    # normalize ONCE, with the same rewrites normalize_and_check applies
    # (a Between inside a row-local tree must produce the SAME node key
    # string_transform_env caches under, or the lane stages twice)
    try:
        node = _rewrite_between(
            normalize_literals(key_expr._node, table.schema), table.schema)
    except (ValueError, KeyError):
        return None
    cname = _plain_string_column(node, table.schema)
    if cname is not None:
        staged_cols = stage_table_columns(table, [cname],
                                          size_bucket(len(table)), cache)
        if staged_cols is None:
            return None
        _env, dcs = staged_cols
        dc = dcs[cname]
        if dc.dictionary is None:
            return None
        return dc.values, dc.valid
    # transformed-string keys: no projection-compilability gate — the
    # transform evaluates on host over the dictionary. (INT-valued
    # transforms — length/find — never reach here: _stage_key stages them
    # as compiled int expressions through the same transform lane.)
    shape = _string_dict_value_shape(node, table.schema)
    if shape is None:
        return None
    lane = dict_transform_lane(table, shape, size_bucket(len(table)), cache)
    if lane is None:
        return None
    vals, valid, _tuniq = lane
    return vals, valid


def _try_device_group_codes(table, group_by, stage_cache, n: int):
    """(codes_dev, uniq Table, num_groups) via the device kernel for 1-4
    stageable keys — integer/date values, string dictionary codes, packed
    mixed-radix for multi-key (null-free only: packing collapses null
    components). Unique key ROWS are gathered on host by first-occurrence
    index, so the group order matches the host dictionary encode exactly.
    Returns None when ineligible (host _group_codes handles everything)."""
    from ..series import Series

    lanes = _staged_group_lanes(table, group_by, stage_cache, n)
    if lanes is None:
        return None
    vals, valid = lanes
    codes, num_groups, first_rows, _uv, _um = _group_codes_kernel(
        vals, valid, jnp.int32(n))
    num_groups = int(num_groups)  # one tiny sync; bounds the segment bucket
    first = np.asarray(jax.device_get(first_rows))[:num_groups]
    import pyarrow as pa

    # gather the num_groups first-occurrence ROWS first, then evaluate the
    # key expressions over just those — O(groups) host work, not O(rows)
    first_tbl = table.take(Series.from_arrow(
        pa.array(first.astype(np.uint64)), "idx"))
    uniq = first_tbl.eval_expression_list(list(group_by))
    return codes, uniq, num_groups


def _staged_group_lanes(table, keys, stage_cache, n: int):
    """ONE (vals, valid) int lane for 1-4 group/distinct keys: single keys
    stage directly (nulls fine — the kernel groups them); multi-key packs
    mixed-radix, which is only null-faithful when every component is
    null-free (a null component would collapse distinct tuples like
    (1, null)/(2, null) into one packed-null group), so nullable multi-key
    inputs decline. Shared by the groupby and distinct paths."""
    from .device_join import _pack_composite_keys

    staged = [_stage_group_key(table, k, stage_cache) for k in keys]
    if any(s is None for s in staged):
        return None
    if len(staged) == 1:
        return staged[0]
    # ONE fused reduction + sync for the nullability check, not one/key
    all_valid = bool(jax.device_get(
        jnp.all(jnp.stack([jnp.all(m[:n]) for _, m in staged]))))
    if not all_valid:
        return None
    packed = _pack_composite_keys([staged])
    if packed is None:
        return None
    (vals, valid), = packed
    return vals, valid


def device_distinct_indices(table, keys, stage_cache, n: int):
    """First-occurrence row indices of the distinct key tuples, computed on
    device via _group_codes_kernel (row order preserved — same contract as
    Table.distinct's host dictionary encode). Multi-column keys pack through
    the join layer's mixed-radix packing, which is only null-faithful when
    every component is null-free: a null component would collapse distinct
    tuples like (1, null)/(2, null) into one packed-null group, so nullable
    multi-key inputs decline to the host path. Returns np.ndarray or None."""
    lanes = _staged_group_lanes(table, keys, stage_cache, n)
    if lanes is None:
        return None
    vals, valid = lanes
    _, num_groups, first_rows, _, _ = _group_codes_kernel(
        vals, valid, jnp.int32(n))
    num_groups = int(num_groups)
    return np.asarray(jax.device_get(first_rows))[:num_groups]


def group_codes_cached(table, group_by, stage_cache: Optional[dict], n: int,
                       b: int, stats=None):
    """(codes_dev, uniq Table|None, num_groups) for ``group_by`` over
    ``table``, cached with the partition under the stage cache (the
    dictionary encode over string keys is the dominant per-query host cost
    on resident data). Device kernel for 1-4 stageable keys, host
    ``Table._group_codes`` otherwise; ungrouped degenerates to one group.
    Shared by the staged aggregation path and the resident segment runtime
    (fuse/segment.py) so both key the SAME cache entries — a staged run
    warms the resident run and vice versa."""
    from ..table import _group_codes

    codes_key = ("groupcodes", tuple(e._node._key() for e in group_by), b)
    cached = stage_cache.get(codes_key) if stage_cache is not None else None
    if cached is None:
        if 1 <= len(group_by) <= 4:
            # stageable keys (int/date values, string dictionary codes,
            # packed for multi-key): codes computed ON DEVICE (sort +
            # boundary scan), keeping the O(rows) bookkeeping off the host
            try:
                cached = _try_device_group_codes(table, group_by,
                                                 stage_cache, n)
            except Exception:
                cached = None
            if cached is not None and stats is not None:
                stats.bump("device_group_codes")
        if cached is None:
            if group_by:
                key_tbl = table.eval_expression_list(list(group_by))
                codes_np, uniq = _group_codes(key_tbl)
                num_groups = len(uniq)
            else:
                codes_np = np.zeros(n, dtype=np.int64)
                uniq = None
                num_groups = 1
            codes_dev = jnp.asarray(np.pad(codes_np.astype(np.int32), (0, b - n)))
            cached = (codes_dev, uniq, num_groups)
        if stage_cache is not None:
            stage_cache[codes_key] = cached
    return cached


def device_grouped_agg(table, to_agg, group_by, stage_cache: Optional[dict] = None,
                       predicate=None, stats=None):
    """Synchronous fused grouped aggregation on device: dispatch + resolve.
    Returns a host Table or None when ineligible (see the async variant)."""
    resolve = device_grouped_agg_async(table, to_agg, group_by, stage_cache,
                                       predicate, stats=stats)
    return None if resolve is None else resolve()


def _plan_agg_specs(to_agg, schema, predicate=None):
    """Shared eligibility prologue for the async kernel and the planner's
    static check — ONE implementation so the two can never drift. Returns
    (specs, child_nodes, pred_nodes) or None when any aggregation kind,
    count mode, child expression, or predicate is device-ineligible."""
    from .device import normalize_and_check

    specs = []  # (alias, kind, AggExpr node, count_mode)
    child_exprs = []
    for e in to_agg:
        node = _unwrap(e)
        if node is None or node.kind not in _DEVICE_AGG_KINDS:
            return None
        if node.kind == "count" and node.extra.get("mode", "valid") not in (
                "valid", "all", "null"):
            return None
        specs.append((e.name(), node.kind, node, node.extra.get("mode", "valid")))
        child_exprs.append(_ExprView(node.child))
    child_nodes = normalize_and_check(child_exprs, schema)
    if child_nodes is None:
        return None
    pred_nodes = None
    if predicate is not None:
        pred_nodes = normalize_and_check([predicate], schema)
        if pred_nodes is None:
            return None
    return specs, child_nodes, pred_nodes


def agg_plan_device_compilable(to_agg, schema, predicate=None) -> bool:
    """Static shape check (no data, no staging): used by the executor to
    choose the double-buffered driver before any partition exists."""
    try:
        return _plan_agg_specs(to_agg, schema, predicate) is not None
    except Exception:
        return False


def device_grouped_agg_async(table, to_agg, group_by,
                             stage_cache: Optional[dict] = None,
                             predicate=None, stats=None):
    """Fused grouped aggregation for one partition on device, split into a
    dispatch (staging + the jitted launch happen now) and a deferred resolver
    (ONE result fetch + host assembly when called) — the executor stages
    partition i+1 while the device reduces partition i. Honest caveat: on a
    COLD stage cache the dispatch itself still pays small device syncs (the
    group-count fetch bounding the segment bucket, and the wrap-guard's
    min/max when int64 arithmetic is present), which queue behind the
    previous partition's compute; warm partitions dispatch sync-free.

    `to_agg`: aggregation Expressions (kinds sum/count/min/max/mean);
    `group_by`: key Expressions — 1-4 stageable keys (int/date values,
    plain string columns via dictionary codes, multi-key packed null-free)
    code on device, anything else on host; `predicate`: optional filter
    fused as a mask.

    Returns a zero-arg resolver yielding a host Table (keys + aggregates,
    first-occurrence group order, matching the host path) — the resolver
    returns None if the int-sum overflow guard trips at materialization —
    or None immediately when ineligible.
    """
    from ..schema import Field, Schema
    from ..table import Table

    n = len(table)
    if n == 0:
        return None
    schema = table.schema

    # --- plan the aggregate list (shared with the planner's static check) --
    planned = _plan_agg_specs(to_agg, schema, predicate)
    if planned is None:
        return None
    specs, child_nodes, pred_nodes = planned

    # --- host bookkeeping: group codes (cached with the partition — the
    # dictionary encode over string keys is the dominant per-query host cost
    # on resident data) ----------------------------------------------------
    b = size_bucket(n)
    codes_dev, uniq, num_groups = group_codes_cached(table, group_by,
                                                     stage_cache, n, b, stats)
    gb = max(16, 1 << (num_groups - 1).bit_length())  # static segment bucket

    # --- stage inputs -----------------------------------------------------
    from .device import (device_required_columns, epoch_cmp_env,
                         epoch_cmps_for, int64_wrap_safe, string_joint_env,
                         string_literal_env, string_lut_env,
                         string_transform_env)

    check_nodes = list(child_nodes) + (list(pred_nodes) if pred_nodes else [])
    epoch_cmps = epoch_cmps_for(check_nodes, schema)
    needed = device_required_columns(check_nodes, schema)
    staged = stage_table_columns(table, sorted(needed), b, stage_cache)
    if staged is None:
        return None
    env, dcs = staged
    if not int64_wrap_safe(check_nodes, schema, env, stage_cache, b):
        return None  # int64 arithmetic could wrap in int32 lanes
    env = string_literal_env(check_nodes, schema, dcs, env)
    if env is None:
        return None  # a string comparison lost its dictionary
    env = epoch_cmp_env(epoch_cmps, schema, table, b, stage_cache, env)
    if env is None:
        return None  # an epoch literal failed to convert
    env = string_lut_env(check_nodes, schema, dcs, env)
    if env is None:
        return None  # a LUT predicate lost its dictionary
    joint_aux: dict = {}
    env = string_joint_env(check_nodes, schema, dcs, env, joint_aux)
    if env is None:
        return None  # a joint-group column lost its dictionary
    env = string_transform_env(check_nodes, schema, table, b, stage_cache,
                               env, joint_aux)
    if env is None:
        return None  # a transformed-string lane failed to stage
    from .device import transform_cmp_env

    env = transform_cmp_env(check_nodes, schema, table, b, stage_cache, dcs,
                            env, joint_aux)
    if env is None:
        return None  # a cross-column transform compare lost a dictionary

    # --- compile + run ONE fused program ---------------------------------
    from ..context import get_context

    kinds = tuple(s[1] for s in specs)
    modes = tuple(s[3] for s in specs)
    _cfg = get_context().execution_config
    use_pallas = bool(_cfg.use_pallas_segment_sums)
    use_deep = bool(_cfg.use_pallas_deep_fusion)
    run = _compile_agg(tuple(child_nodes), pred_nodes[0] if pred_nodes else None,
                       schema, tuple(sorted(needed)), kinds, modes, gb,
                       use_pallas, use_deep)
    # the row-count scalar lives on device with the partition: every host->
    # device transfer pays the full link latency (~60ms through a tunneled
    # chip), so a warm query must make zero uploads and ONE result fetch
    nkey = ("nrows", n)
    n_dev = stage_cache.get(nkey) if stage_cache is not None else None
    if n_dev is None:
        n_dev = jnp.int32(n)
        if stage_cache is not None:
            stage_cache[nkey] = n_dev
    outs_dev = run(env, codes_dev, n_dev)  # async: device computes from here

    def resolve():
        outs = jax.device_get(outs_dev)

        # --- assemble host result ----------------------------------------
        from ..series import Series

        out_cols: List[Series] = list(uniq._columns) if uniq is not None else []
        out_fields: List[Field] = list(uniq.schema) if uniq is not None else []
        agg_outs = outs[:len(specs)]
        for (alias, kind, agg_node, _mode), child_nd, out in zip(
                specs, child_nodes, agg_outs):
            expected_dt = agg_node.to_field(schema).dtype
            dictionary = None
            if expected_dt.is_string():
                # string min/max reduce over sorted-dictionary CODES (order-
                # isomorphic): the result must decode through the child
                # column's dictionary — or, for a fill_null/if_else child,
                # its joint-group dictionary — or it would silently return
                # code digits
                from .device import string_output_dictionary

                dictionary = string_output_dictionary(child_nd, schema, dcs,
                                                      joint_aux)
                if dictionary is None:
                    return None  # cannot decode: host path recomputes
            merged = _finish_agg(kind, out, num_groups, expected_dt, n,
                                 dictionary=dictionary)
            if merged is None:
                return None  # overflow guard tripped: host path recomputes
            out_cols.append(merged.rename(alias))
            out_fields.append(Field(alias, expected_dt))
        result = Table(Schema(out_fields), out_cols)
        if pred_nodes is not None:
            # prune filtered-away groups; order survivors like the host path
            # (first occurrence within the filtered rows)
            sel_cnt, first_idx = (np.asarray(a)[:num_groups] for a in outs[-1])
            if group_by:
                surv = np.nonzero(sel_cnt > 0)[0]
                order = surv[np.argsort(first_idx[surv], kind="stable")]
                if len(order) != num_groups or (order != np.arange(num_groups)).any():
                    import pyarrow as pa

                    result = result.take(Series.from_arrow(
                        pa.array(order.astype(np.uint64)), "idx"))
        return result

    return resolve


class _ExprView:
    """Minimal Expression-shaped wrapper so helper APIs taking Expressions
    can accept bare nodes."""

    __slots__ = ("_node",)

    def __init__(self, node):
        self._node = node

    def name(self):
        return self._node.name()


def _compile_agg(child_nodes, pred_node, schema, input_names, kinds, modes, gb,
                 use_pallas: bool = False, use_deep: bool = False,
                 donate: bool = False):
    # `donate` hands the env argument's buffers to XLA (donate_argnums):
    # the resident segment path passes a FRESH intermediate env (the map
    # program's outputs, never stage-cache entries), so its HBM is reused
    # for the reduction outputs instead of copied. The staged path keeps
    # donate=False — its env aliases the partition's residency cache, which
    # must survive the call. Part of the cache key: the two variants are
    # different XLA executables.
    key = (tuple(n._key() for n in child_nodes),
           pred_node._key() if pred_node is not None else None,
           tuple((f.name, f.dtype) for f in schema), input_names, kinds, modes,
           gb, x64_enabled(), use_pallas, use_deep, donate)
    if key in _AGG_CACHE:
        return _AGG_CACHE[key]

    child_run, _ = compile_projection(list(child_nodes), schema, input_names)
    pred_run = None
    if pred_node is not None:
        pred_run, _ = compile_projection([pred_node], schema, input_names)

    import functools

    from .device import _ONEHOT_MAX_SEGMENTS, _compile_node
    from .pallas_ops import (_BLOCK_ROWS, _masked_segment_sums_padded,
                             build_fused_expr_sums)

    # donation warns and no-ops on the CPU backend, so it only ever arms on
    # a real accelerator (the caller additionally gates on the backend)
    _jit = (functools.partial(jax.jit, donate_argnums=(0,))
            if donate and jax.default_backend() != "cpu" else jax.jit)

    @_jit
    def run(env, codes, n):
        inbounds = jnp.arange(codes.shape[0], dtype=jnp.int32) < n
        if pred_run is not None:
            (pv, pm), = pred_run(env)
            sel = pv & pm & inbounds  # invalid predicate rows filter out (SQL WHERE)
        else:
            sel = inbounds
        # In 32-bit mode every float sum accumulates in float32 anyway, so
        # the batched pallas kernel (ALL float-sum columns in ONE one_hot.T @
        # values MXU pass, pallas_ops.py) is bit-compatible with the
        # segment_sum route; x64 mode keeps exact float64 segment sums.
        # group-cardinality bound mirrors segment_reduce's one-hot cap: a
        # (1024, gb) one-hot block past ~4k groups blows the VMEM budget
        pallas_ok = (use_pallas and not x64_enabled()
                     and codes.shape[0] >= _BLOCK_ROWS
                     and codes.shape[0] % _BLOCK_ROWS == 0
                     and gb <= _ONEHOT_MAX_SEGMENTS)
        fused_sums = []  # (slot in outs, pre-masked float32 column, cnt)
        outs = []
        for (v, m), kind, mode in zip(child_run(env), kinds, modes):
            m = m & sel
            if kind == "count":
                if mode == "all":
                    contrib = sel
                elif mode == "null":
                    contrib = sel & ~m
                else:
                    contrib = m
                cnt, _ = segment_reduce(contrib, contrib, codes, gb, "count")
                outs.append(cnt)
                continue
            if kind in ("sum", "mean"):
                # accumulate in the widest same-class dtype (int8 inputs must
                # not sum in int8)
                if jnp.issubdtype(v.dtype, jnp.floating):
                    acc = v.astype(jnp.float64 if x64_enabled() else jnp.float32)
                elif v.dtype == jnp.bool_:
                    acc = v.astype(jnp.int64 if x64_enabled() else jnp.int32)
                elif jnp.issubdtype(v.dtype, jnp.unsignedinteger):
                    acc = v.astype(jnp.uint64 if x64_enabled() else jnp.uint32)
                else:
                    acc = v.astype(jnp.int64 if x64_enabled() else jnp.int32)
                cnt, _ = segment_reduce(m, m, codes, gb, "count")
                if pallas_ok and jnp.issubdtype(acc.dtype, jnp.floating):
                    fused_sums.append((len(outs),
                                       jnp.where(m, acc, 0.0).astype(jnp.float32),
                                       cnt))
                    outs.append(None)  # back-filled from the batched kernel
                    continue
                vals, valid = segment_reduce(acc, m, codes, gb, "sum")
                if jnp.issubdtype(acc.dtype, jnp.integer) and not x64_enabled():
                    # overflow guard operands: masked max|v| for the host check
                    absv = jnp.where(m, jnp.abs(v.astype(jnp.float32)), 0.0)
                    outs.append((vals, valid, cnt, jnp.max(absv)))
                else:
                    outs.append((vals, valid, cnt, jnp.float32(0)))
                continue
            # min / max
            vals, valid = segment_reduce(v, m, codes, gb, kind)
            outs.append((vals, valid))
        if fused_sums:
            # Deep fusion (second pallas kernel, r4 verdict weak #5): the
            # predicate and the derived float-sum columns evaluate INSIDE
            # the kernel from the raw staged columns — no pre-masked (n, K)
            # matrix ever materializes in HBM. Eligible when every env
            # entry is a plain 1-D column pair (no string/epoch scalar
            # extras whose closures the kernel cannot be handed).
            deep_ok = (use_deep
                       and all(isinstance(v, tuple) and v[0].ndim == 1
                               for v in env.values()))
            if deep_ok:
                try:
                    # each child appends exactly one outs entry, so the
                    # outs slot IS the child index
                    child_fns = [_compile_node(child_nodes[slot], schema)[0]
                                 for slot, _c, _cnt in fused_sums]
                    pred_fn = None
                    if pred_run is not None:
                        def pred_fn(e, _pr=pred_run):
                            (pv, pm), = _pr(e)
                            return pv, pm
                    deep = build_fused_expr_sums(
                        pred_fn, child_fns, tuple(sorted(env)), gb,
                        len(fused_sums),
                        jax.default_backend() == "cpu")
                    inb = inbounds[:, None]
                    flat_cols = []
                    for name in sorted(env):
                        v, m = env[name]
                        flat_cols.append(v[:, None])
                        flat_cols.append(m[:, None])
                    sums = deep(codes[:, None], inb, *flat_cols)
                    for j, (slot, _col, cnt) in enumerate(fused_sums):
                        outs[slot] = (sums[:, j], cnt > 0, cnt,
                                      jnp.float32(0))
                    fused_sums = []
                except Exception:
                    pass  # fall through to the batched kernel below
        if fused_sums:
            vk = jnp.stack([col for _, col, _ in fused_sums], axis=1)
            sums = _masked_segment_sums_padded(
                codes[:, None], sel.astype(jnp.float32)[:, None], vk, gb,
                jax.default_backend() == "cpu")
            for j, (slot, _col, cnt) in enumerate(fused_sums):
                outs[slot] = (sums[:, j], cnt > 0, cnt, jnp.float32(0))
        if pred_run is not None:
            # group-survival data: codes/uniq were built from the UNFILTERED
            # table, so the host must drop groups with no selected rows and
            # reorder survivors by first selected row (host semantics:
            # first-occurrence order of the filtered table)
            sel_cnt, _ = segment_reduce(sel, sel, codes, gb, "count")
            idx = jnp.arange(codes.shape[0], dtype=jnp.int32)
            first_idx, _ = segment_reduce(idx, sel, codes, gb, "min")
            outs.append((sel_cnt, first_idx))
        return outs

    _AGG_CACHE[key] = run
    return run


def _finish_agg(kind, out, num_groups, expected_dt: DataType, n,
                dictionary=None):
    """Device partials -> host Series of the expected dtype (or None when the
    int32 overflow guard fired and the host must recompute). `dictionary`
    decodes string min/max code results."""
    import pyarrow as pa

    from ..series import Series
    from .device import DeviceColumn, unstage

    if kind == "count":
        vals = np.asarray(out)[:num_groups]
        return Series.from_arrow(pa.array(vals.astype(np.uint64)), "o", expected_dt)
    if kind in ("sum", "mean"):
        vals, valid, cnt, max_abs = out
        vals = np.asarray(vals)
        valid = np.asarray(valid)
        if np.issubdtype(vals.dtype, np.integer) and not x64_enabled():
            # guards BOTH sum and mean — a wrapped int32 sum poisons either
            if float(n) * float(max_abs) >= 2**31 - 1:
                return None  # could have wrapped: recompute on host
        if kind == "mean":
            cnt = np.asarray(cnt)[:num_groups]
            with np.errstate(invalid="ignore", divide="ignore"):
                mv = vals[:num_groups].astype(np.float64) / cnt.astype(np.float64)
            arr = pa.array(mv, pa.float64())
            if not valid[:num_groups].all():
                arr = pa.compute.if_else(pa.array(valid[:num_groups]), arr,
                                         pa.nulls(num_groups, pa.float64()))
            return Series.from_arrow(arr, "o", expected_dt)
        dc = DeviceColumn(vals, valid, num_groups, expected_dt)
        return unstage(dc)
    # min / max
    vals, valid = out
    dc = DeviceColumn(np.asarray(vals), np.asarray(valid), num_groups,
                      expected_dt, dictionary=dictionary)
    return unstage(dc)
