"""Chrome-trace + progress instrumentation.

Role-equivalent to the reference's chrome-trace layer
(src/common/tracing/src/lib.rs:13-55, armed by DAFT_DEV_ENABLE_CHROME_TRACE
and re-armed per query by the native executor) and its tqdm progress bars
(daft/runners/progress_bar.py). Events are buffered in a bounded RING
(evictions counted, reported as droppedEvents) and written as one
chrome://tracing-compatible JSON array; since PR 6 the per-op duration
events are rendered FROM the structured profiler's span tree
(daft_tpu/profile/) at each query's end — one consolidated writer,
re-armed per query — so the trace carries the same cross-thread
attribution the QueryProfile does. On TPU the same file can be opened
alongside an xprof/xplane capture to line up host pipeline stages with
device kernels.

Enable with the env var DAFT_TPU_CHROME_TRACE=<path> (armed at import/query
time) or programmatically:

    with daft_tpu.tracing.chrome_trace("/tmp/q1.json"):
        df.collect()
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Deque, Optional

# Buffer cap: a RING — past it the OLDEST events are evicted and counted
# (dropped_events()), so a long-running armed process keeps the most recent
# window instead of growing without bound. The flush metadata records the
# drop count so a truncated trace is never mistaken for a complete one.
DEFAULT_BUFFER_CAP = 200_000

_lock = threading.Lock()
_events: Deque[dict] = deque(maxlen=DEFAULT_BUFFER_CAP)
_dropped = 0
_path: Optional[str] = None
_t0_us: float = 0.0
# thread name -> chrome tid, stable for the LIFETIME of one armed trace:
# the consolidated multi-query file must keep each real thread on one lane
_tids: dict = {}

_progress_cb: Optional[Callable[[str, int], None]] = None


def active() -> bool:
    return _path is not None


def _now_us() -> float:
    return time.perf_counter_ns() / 1000.0


def set_buffer_cap(cap: int) -> None:
    """Resize the ring (keeps the newest events that fit; tests use this to
    exercise eviction cheaply)."""
    global _events, _dropped
    with _lock:
        old = list(_events)
        _events = deque(old[-cap:] if cap else [], maxlen=max(1, cap))
        _dropped += max(0, len(old) - cap)


def dropped_events() -> int:
    with _lock:
        return _dropped


def tail(n: int = 2000) -> list:
    """The newest ``n`` buffered chrome events (oldest first) — what the
    flight recorder's diagnostics bundles snapshot when a trace is armed."""
    with _lock:
        evs = list(_events)
    return evs[-n:]


def enable(path: str) -> None:
    """Start buffering events; flush() writes them to `path`."""
    global _path, _t0_us, _dropped
    with _lock:
        _path = path
        _t0_us = _now_us()
        _events.clear()
        _dropped = 0
        _tids.clear()


def _append_locked(ev: dict) -> None:
    # runs under _lock (every caller holds it); the lock-discipline rule is
    # lexical and cannot see through the helper
    global _dropped
    if _events.maxlen is not None and len(_events) == _events.maxlen:
        # the ring evicts its oldest entry on this append
        _dropped += 1  # daftlint: disable=DTL002
    _events.append(ev)


def add_event(name: str, start_us: float, dur_us: float, tid: int = 0,
              args: Optional[dict] = None) -> None:
    if _path is None:
        return
    ev = {"name": name, "ph": "X", "pid": os.getpid(), "tid": tid,
          "ts": start_us - _t0_us, "dur": dur_us}
    if args:
        ev["args"] = args
    with _lock:
        _append_locked(ev)


def add_instant(name: str, args: Optional[dict] = None) -> None:
    """Zero-duration marker (chrome-trace 'instant' event) — used for
    discrete occurrences like injected faults and breaker trips, which have
    no wall time but matter when lining up a failure against the pipeline."""
    if _path is None:
        return
    ev = {"name": name, "ph": "i", "s": "g", "pid": os.getpid(), "tid": 0,
          "ts": _now_us() - _t0_us}
    if args:
        ev["args"] = args
    with _lock:
        _append_locked(ev)


def add_span_events(profiler) -> None:
    """Render a finished query's span tree + typed events into the chrome
    buffer (the consolidated writer: execution no longer emits per-pull
    chrome events itself — the span tree is the single source). Threads map
    to chrome tids by first appearance, stable across the armed trace's
    lifetime; span phases and attrs ride in `args` so the trace viewer
    shows the same breakdown the QueryProfile carries. Incremental: only
    spans/events not yet rendered are emitted, so an AQE query's per-stage
    flushes never duplicate earlier stages."""
    if _path is None:
        return
    spans, events = profiler.drain_for_chrome()
    pid = os.getpid()
    with _lock:
        t0 = _t0_us
        for sp in spans:
            tid = _tids.setdefault(sp.thread, len(_tids))
            args = {"span": sp.sid, "kind": sp.kind}
            if sp.parent is not None:
                args["parent"] = sp.parent
            if sp.part is not None:
                args["part"] = sp.part
            if sp.phases:
                args.update({f"phase.{k}": v for k, v in sp.phases.items()})
            if sp.attrs:
                args.update(sp.attrs)
            _append_locked({
                "name": sp.name, "ph": "X", "pid": pid, "tid": tid,
                "ts": sp.t0_ns / 1000.0 - t0, "dur": sp.dur_ns / 1000.0,
                "args": args})
        for ev in events:
            _append_locked({
                "name": ev["kind"], "ph": "i", "s": "g", "pid": pid,
                "tid": 0, "ts": ev["t_ns"] / 1000.0 - t0,
                "args": dict(ev.get("attrs") or {})})


def flush(keep: bool = False) -> Optional[str]:
    """Write buffered events atomically w.r.t. concurrent emits: the buffer
    is snapshotted (and, unless ``keep``, cleared) under the lock in one
    step, then written outside it — an emit racing the file write lands in
    the next flush, never lost or duplicated. ``keep=True`` is the
    per-query re-arming mode: the file on disk always reflects everything
    so far, and later queries keep appending."""
    global _dropped
    with _lock:
        path = _path
        if path is None:
            return None
        evs = list(_events)
        dropped = _dropped
        if not keep:
            # the written file records this window's drops; the next
            # window starts with a clean count (a later complete batch
            # must not be mislabeled as truncated)
            _events.clear()
            _dropped = 0
    doc = {"traceEvents": evs, "displayTimeUnit": "ms"}
    if dropped:
        doc["droppedEvents"] = dropped
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def flush_query() -> Optional[str]:
    """Query-end flush: rewrite the armed trace file with everything
    buffered so far, KEEPING the buffer — every query re-arms the same
    consolidated writer, and the file survives a process kill between
    queries (reference: the native executor's per-query chrome re-arming)."""
    return flush(keep=True)


def disable() -> None:
    global _path
    with _lock:
        _path = None
        _events.clear()
        _tids.clear()


@contextmanager
def chrome_trace(path: str):
    """Trace every query run inside the block into one chrome-trace file."""
    enable(path)
    try:
        yield
    finally:
        flush()
        disable()


# armed from the environment once, like the reference's DAFT_DEV_ENABLE_CHROME_TRACE;
# the atexit hook guarantees the file is written even though no context manager
# wraps the process, and bounds the buffer's lifetime to the process
_env_path = os.environ.get("DAFT_TPU_CHROME_TRACE")
if _env_path:
    import atexit

    enable(_env_path)
    atexit.register(flush)


# ---------------------------------------------------------------------------
# progress
# ---------------------------------------------------------------------------

def set_progress_callback(cb: Optional[Callable[[str, int], None]]) -> None:
    """cb(op_name, rows_emitted) fires per produced partition (None clears)."""
    global _progress_cb
    _progress_cb = cb


def report_progress(op_name: str, rows: int) -> None:
    cb = _progress_cb
    if cb is not None:
        cb(op_name, rows)


class ProgressBar:
    """Terminal progress UI (reference: daft/runners/progress_bar.py): one
    tqdm bar per operator when tqdm is importable, a plain carriage-return
    line otherwise. Enable with `progress_bars()` (or DAFT_TPU_PROGRESS=1,
    wired in context.py); disable with `progress_bars(False)`."""

    def __init__(self, use_tqdm: Optional[bool] = None):
        if use_tqdm is None:
            try:
                import tqdm  # noqa: F401

                use_tqdm = True
            except ImportError:
                use_tqdm = False
        self._use_tqdm = use_tqdm
        self._bars = {}
        self._counts = {}

    def __call__(self, op_name: str, rows: int) -> None:
        if self._use_tqdm:
            from tqdm import tqdm

            bar = self._bars.get(op_name)
            if bar is None:
                bar = self._bars[op_name] = tqdm(
                    desc=op_name, unit=" rows", position=len(self._bars),
                    leave=False)
            bar.update(rows)
        else:
            import sys

            self._counts[op_name] = self._counts.get(op_name, 0) + rows
            line = " | ".join(f"{k}: {v:,}" for k, v in self._counts.items())
            print("\r" + line[:160], end="", file=sys.stderr, flush=True)

    def close(self) -> None:
        for bar in self._bars.values():
            bar.close()
        self._bars.clear()
        if self._counts:
            import sys

            print("", file=sys.stderr)
        self._counts.clear()


def query_finished() -> None:
    """Close per-query progress state (bars restart fresh next query)."""
    cb = _progress_cb
    if isinstance(cb, ProgressBar):
        cb.close()


def progress_bars(enable: bool = True) -> None:
    """Toggle terminal progress reporting for subsequent queries."""
    global _progress_cb
    if isinstance(_progress_cb, ProgressBar):
        _progress_cb.close()
    set_progress_callback(ProgressBar() if enable else None)
