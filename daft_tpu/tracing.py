"""Chrome-trace + progress instrumentation.

Role-equivalent to the reference's chrome-trace layer
(src/common/tracing/src/lib.rs:13-55, armed by DAFT_DEV_ENABLE_CHROME_TRACE
and re-armed per query by the native executor) and its tqdm progress bars
(daft/runners/progress_bar.py). Events are buffered in memory and flushed as
one chrome://tracing-compatible JSON array; on TPU the same file can be opened
alongside an xprof/xplane capture to line up host pipeline stages with device
kernels.

Enable with the env var DAFT_TPU_CHROME_TRACE=<path> (armed at import/query
time) or programmatically:

    with daft_tpu.tracing.chrome_trace("/tmp/q1.json"):
        df.collect()
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Callable, List, Optional

_lock = threading.Lock()
_events: List[dict] = []
_path: Optional[str] = None
_t0_us: float = 0.0

_progress_cb: Optional[Callable[[str, int], None]] = None


def active() -> bool:
    return _path is not None


def _now_us() -> float:
    return time.perf_counter_ns() / 1000.0


def enable(path: str) -> None:
    """Start buffering events; flush() writes them to `path`."""
    global _path, _t0_us
    with _lock:
        _path = path
        _t0_us = _now_us()
        _events.clear()


def add_event(name: str, start_us: float, dur_us: float, tid: int = 0,
              args: Optional[dict] = None) -> None:
    if _path is None:
        return
    ev = {"name": name, "ph": "X", "pid": os.getpid(), "tid": tid,
          "ts": start_us - _t0_us, "dur": dur_us}
    if args:
        ev["args"] = args
    with _lock:
        _events.append(ev)


def add_instant(name: str, args: Optional[dict] = None) -> None:
    """Zero-duration marker (chrome-trace 'instant' event) — used for
    discrete occurrences like injected faults and breaker trips, which have
    no wall time but matter when lining up a failure against the pipeline."""
    if _path is None:
        return
    ev = {"name": name, "ph": "i", "s": "g", "pid": os.getpid(), "tid": 0,
          "ts": _now_us() - _t0_us}
    if args:
        ev["args"] = args
    with _lock:
        _events.append(ev)


def flush() -> Optional[str]:
    """Write buffered events; returns the path written (None if disabled)."""
    with _lock:
        path = _path
        if path is None:
            return None
        evs = list(_events)
        _events.clear()
    with open(path, "w") as f:
        json.dump({"traceEvents": evs, "displayTimeUnit": "ms"}, f)
    return path


def disable() -> None:
    global _path
    with _lock:
        _path = None
        _events.clear()


@contextmanager
def chrome_trace(path: str):
    """Trace every query run inside the block into one chrome-trace file."""
    enable(path)
    try:
        yield
    finally:
        flush()
        disable()


# armed from the environment once, like the reference's DAFT_DEV_ENABLE_CHROME_TRACE;
# the atexit hook guarantees the file is written even though no context manager
# wraps the process, and bounds the buffer's lifetime to the process
_env_path = os.environ.get("DAFT_TPU_CHROME_TRACE")
if _env_path:
    import atexit

    enable(_env_path)
    atexit.register(flush)


# ---------------------------------------------------------------------------
# progress
# ---------------------------------------------------------------------------

def set_progress_callback(cb: Optional[Callable[[str, int], None]]) -> None:
    """cb(op_name, rows_emitted) fires per produced partition (None clears)."""
    global _progress_cb
    _progress_cb = cb


def report_progress(op_name: str, rows: int) -> None:
    cb = _progress_cb
    if cb is not None:
        cb(op_name, rows)


class ProgressBar:
    """Terminal progress UI (reference: daft/runners/progress_bar.py): one
    tqdm bar per operator when tqdm is importable, a plain carriage-return
    line otherwise. Enable with `progress_bars()` (or DAFT_TPU_PROGRESS=1,
    wired in context.py); disable with `progress_bars(False)`."""

    def __init__(self, use_tqdm: Optional[bool] = None):
        if use_tqdm is None:
            try:
                import tqdm  # noqa: F401

                use_tqdm = True
            except ImportError:
                use_tqdm = False
        self._use_tqdm = use_tqdm
        self._bars = {}
        self._counts = {}

    def __call__(self, op_name: str, rows: int) -> None:
        if self._use_tqdm:
            from tqdm import tqdm

            bar = self._bars.get(op_name)
            if bar is None:
                bar = self._bars[op_name] = tqdm(
                    desc=op_name, unit=" rows", position=len(self._bars),
                    leave=False)
            bar.update(rows)
        else:
            import sys

            self._counts[op_name] = self._counts.get(op_name, 0) + rows
            line = " | ".join(f"{k}: {v:,}" for k, v in self._counts.items())
            print("\r" + line[:160], end="", file=sys.stderr, flush=True)

    def close(self) -> None:
        for bar in self._bars.values():
            bar.close()
        self._bars.clear()
        if self._counts:
            import sys

            print("", file=sys.stderr)
        self._counts.clear()


def query_finished() -> None:
    """Close per-query progress state (bars restart fresh next query)."""
    cb = _progress_cb
    if isinstance(cb, ProgressBar):
        cb.close()


def progress_bars(enable: bool = True) -> None:
    """Toggle terminal progress reporting for subsequent queries."""
    global _progress_cb
    if isinstance(_progress_cb, ProgressBar):
        _progress_cb.close()
    set_progress_callback(ProgressBar() if enable else None)
