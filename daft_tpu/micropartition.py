"""MicroPartition: the unit of execution — a lazily-materialized batch.

Role-equivalent to the reference's src/daft-micropartition/src/micropartition.rs:35-78:
a partition is either Unloaded (a ScanTask — schema + pushdowns + file metadata,
no bytes decoded yet) or Loaded (one or more concrete Tables). Compute ops force
materialization; metadata ops (len/schema/stats) answer from file footers when
possible so planning never triggers IO. Concat of loaded partitions is O(1)
(tables are chained, not copied) — matching the reference's Vec<Table> design.
"""

from __future__ import annotations

import threading
from typing import (Any, Dict, Iterator, List, Optional, Sequence, Tuple,
                    Union)

from .schema import Schema
from .stats import TableStats
from .table import Table


class MicroPartition:
    __slots__ = ("schema", "_state", "_tables", "_scan_task", "_stats", "_lock",
                 "_device_cache", "owner_process", "_pending",
                 "_count_preserving", "lineage_recipe")

    def __init__(self, schema: Schema, tables: Optional[List[Table]] = None,
                 scan_task=None, stats: Optional[TableStats] = None):
        if (tables is None) == (scan_task is None):
            raise ValueError("MicroPartition needs exactly one of tables / scan_task")
        self.schema = schema
        self._tables = tables
        self._scan_task = scan_task
        self._state = "loaded" if tables is not None else "unloaded"
        self._stats = stats
        self._lock = threading.Lock()
        # HBM residency: staged DeviceColumns keyed by (col, bucket, x64 mode).
        # The host->device link, not compute, bounds device-path throughput, so
        # repeated queries over a cached/collected partition reuse staged
        # columns instead of re-transferring (lifetime == partition lifetime).
        self._device_cache: Dict[Any, Any] = {}
        # Per-host scan locality (reference: per-node dispatch,
        # ray_runner.py:504-685): owner_process marks a scan partition whose
        # rows are CONTRIBUTED by exactly one process of a multi-host run;
        # _pending defers map-op evaluation on foreign-owned unloaded
        # partitions (Table -> Table transforms replayed at materialization)
        # so a projection/filter chain between scan and exchange never forces
        # a foreign read. Any consumer that DOES materialize gets the correct
        # post-op rows — correctness never depends on ownership.
        self.owner_process: Optional[int] = None
        self._pending: Optional[List[Any]] = None
        self._count_preserving = True
        # lineage recipe (integrity/lineage.py): a zero-arg closure that
        # re-derives this partition's exact tables from stable storage.
        # Attached by producers whose derivation is cheap to replay (e.g.
        # shuffle fanout over a scan-backed source); consumed by the spill
        # layer so a corrupted spill file recomputes instead of failing
        # the query. Never pickled (closures are driver-local).
        self.lineage_recipe = None

    def device_stage_cache(self) -> Dict[Any, Any]:
        return self._device_cache

    # ------------------------------------------------------------- pickling
    # Partitions cross process boundaries on the dist/ worker transport.
    # Loaded partitions ship their tables; unloaded ones ship the scan task
    # (the WORKER reads the file — per-worker scan locality). Deferred op
    # chains are closures that cannot cross a process boundary, so they
    # materialize first (the dist backend declines those tasks anyway).
    def __getstate__(self):
        with self._lock:
            if self._state == "loaded":
                return {"schema": self.schema, "tables": list(self._tables),
                        "stats": self._stats, "owner": self.owner_process}
            if not self._pending:
                task = self._scan_task
                # a PrefetchedScanTask wrapper carries driver-local state
                # (queue slot, future): ship the UNDERLYING task — the
                # receiving process performs its own read
                task = getattr(task, "_task", task)
                return {"schema": self.schema, "scan_task": task,
                        "stats": self._stats, "owner": self.owner_process}
        return {"schema": self.schema, "tables": [self.table()],
                "stats": self._stats, "owner": self.owner_process}

    def __setstate__(self, state):
        # a freshly-unpickled partition is visible to exactly one thread:
        # its lock does not exist yet, so lock discipline cannot apply
        self.schema = state["schema"]
        self._tables = state.get("tables")  # daftlint: disable=DTL002
        self._scan_task = state.get("scan_task")  # daftlint: disable=DTL002
        self._state = ("loaded" if self._tables is not None  # daftlint: disable=DTL002
                       else "unloaded")
        self._stats = state.get("stats")
        self._lock = threading.Lock()
        self._device_cache = {}
        self.owner_process = state.get("owner")
        self._pending = None  # daftlint: disable=DTL002
        self._count_preserving = True
        self.lineage_recipe = None

    def with_pending_op(self, fn, schema: Schema,
                        count_preserving: bool) -> "MicroPartition":
        """Deferred map op over an unloaded partition: same scan task, the
        transform replays at table() time. Used only for foreign-owned
        partitions in multi-host mode."""
        out = MicroPartition(schema, scan_task=self._scan_task,
                            stats=None)
        out.owner_process = self.owner_process
        out._pending = list(self._pending or []) + [fn]
        out._count_preserving = self._count_preserving and count_preserving
        return out

    # ------------------------------------------------------------------ ctors
    @staticmethod
    def from_table(tbl: Table) -> "MicroPartition":
        return MicroPartition(tbl.schema, tables=[tbl])

    @staticmethod
    def from_tables(tables: List[Table]) -> "MicroPartition":
        if not tables:
            raise ValueError("from_tables requires at least one table (use empty())")
        return MicroPartition(tables[0].schema, tables=list(tables))

    @staticmethod
    def from_scan_task(task) -> "MicroPartition":
        return MicroPartition(task.materialized_schema, scan_task=task, stats=task.stats)

    @staticmethod
    def empty(schema: Optional[Schema] = None) -> "MicroPartition":
        schema = schema or Schema.empty()
        return MicroPartition.from_table(Table.empty(schema))

    @staticmethod
    def from_pydict(data: Dict[str, Any]) -> "MicroPartition":
        return MicroPartition.from_table(Table.from_pydict(data))

    @staticmethod
    def from_arrow(tbl) -> "MicroPartition":
        return MicroPartition.from_table(Table.from_arrow(tbl))

    # ------------------------------------------------------------------ state
    def is_loaded(self) -> bool:
        return self._state == "loaded"

    def scan_task(self):
        return self._scan_task

    def table(self) -> Table:
        """Materialize to a single concrete Table (loads + concats if needed)."""
        with self._lock:
            if self._state == "unloaded":
                tbl = self._scan_task.read()
                for fn in self._pending or ():
                    tbl = fn(tbl)
                self._pending = None
                self._tables = [tbl]
                self._state = "loaded"
                self._scan_task = None
            if len(self._tables) > 1:
                self._tables = [Table.concat(self._tables)]
            return self._tables[0]

    def chunk_tables(self) -> List[Table]:
        """Materialize preserving the reader's chunk structure (one Table per
        file / reader chunk) instead of collapsing to a single Table. The map
        side of a shuffle hashes and splits each chunk independently, so the
        O(partition-bytes) memcpy that `table()`'s Table.concat pays never
        happens (measured: the concat dominated the out-of-core rung's map
        phase). Falls back to the collapsing path when deferred ops are
        pending — a deferred limit/head chain is defined over the WHOLE
        partition, not per chunk. Reference role: the reference MicroPartition
        is a Vec<Table> whose ops iterate the pieces (micropartition.rs:35-78);
        this surfaces that same contract to row-local consumers."""
        with self._lock:
            if self._state == "loaded":
                return list(self._tables)
            if not self._pending:
                task = self._scan_task
                read_chunks = getattr(task, "read_chunks", None)
                tbls = list(read_chunks()) if read_chunks is not None else [task.read()]
                tbls = [t for t in tbls if len(t)] or [Table.empty(self.schema)]
                self._tables = tbls
                self._state = "loaded"
                self._scan_task = None
                return list(self._tables)
        return [self.table()]

    def iter_chunk_tables(self) -> Iterator[Table]:
        """LAZY counterpart of ``chunk_tables`` for the streaming
        producers (daft_tpu/stream/): a loaded partition yields its
        resident tables; an unloaded one decodes chunk by chunk via
        ``ScanTask.iter_chunks`` (parquet: one row group at a time), so
        the first morsel flows before the rest of the partition is read.
        The load state is NOT mutated — the streaming producer consumes
        the chunks exactly once, and a failed iteration can restart from
        scratch (the partition-level transient-retry contract). Deferred
        pending ops collapse to ``chunk_tables()``: they are defined over
        the whole partition."""
        with self._lock:
            if self._state == "loaded":
                return iter(list(self._tables))
            task = None if self._pending else self._scan_task
        if task is None or not hasattr(task, "iter_chunks"):
            return iter(self.chunk_tables())
        return (t for t in task.iter_chunks() if len(t))

    def __len__(self) -> int:
        n = self.num_rows_or_none()
        if n is not None:
            return n
        return len(self.table())

    def num_rows_or_none(self) -> Optional[int]:
        """Row count without IO, if knowable (loaded, or exact scan metadata)."""
        if self._state == "loaded":
            return sum(len(t) for t in self._tables)
        if not self._count_preserving:
            return None  # a deferred filter changes the count
        return self._scan_task.num_rows()

    def size_bytes(self) -> Optional[int]:
        if self._state == "loaded":
            return sum(t.size_bytes() for t in self._tables)
        if self._pending:
            return None  # deferred ops change the width/count
        return self._scan_task.size_bytes()

    def statistics(self) -> Optional[TableStats]:
        return self._stats

    @property
    def column_names(self) -> List[str]:
        return self.schema.field_names()

    def __repr__(self) -> str:
        if self._state == "unloaded":
            return f"MicroPartition(Unloaded {self._scan_task!r})"
        return f"MicroPartition(Loaded rows={len(self)})"

    # ------------------------------------------------------------------ conversions
    def to_arrow(self):
        return self.table().to_arrow()

    def to_pydict(self) -> Dict[str, list]:
        return self.table().to_pydict()

    def to_pylist(self) -> List[dict]:
        return self.table().to_pylist()

    def to_pandas(self):
        return self.table().to_pandas()

    def get_column(self, name: str):
        return self.table().get_column(name)

    # ------------------------------------------------------------------ compute ops
    # Each materializes and delegates to Table, returning a Loaded partition.

    def _wrap(self, tbl: Table) -> "MicroPartition":
        out = MicroPartition.from_table(tbl)
        # contribution ownership survives per-partition transforms so the
        # multi-host exchange keeps exactly-once semantics by OWNER, not by
        # a fragile stream-index coincidence
        out.owner_process = self.owner_process
        return out

    def eval_expression_list(self, exprs) -> "MicroPartition":
        return self._wrap(self.table().eval_expression_list(exprs))

    def filter(self, predicate) -> "MicroPartition":
        return self._wrap(self.table().filter(predicate))

    def take(self, indices) -> "MicroPartition":
        return self._wrap(self.table().take(indices))

    def slice(self, start: int, end: int) -> "MicroPartition":
        return self._wrap(self.table().slice(start, end))

    def head(self, n: int) -> "MicroPartition":
        if self._state == "unloaded":
            if self._pending:
                # a limit must not push BELOW deferred ops (the deferred
                # filter changes which rows the first n are): defer it too
                return self.with_pending_op(lambda t: t.head(n), self.schema,
                                            count_preserving=False)
            # narrow the scan's limit instead of reading everything
            task = self._scan_task
            pd = task.pushdowns
            new_limit = n if pd.limit is None else min(pd.limit, n)
            narrowed = task.with_pushdowns(pd.with_limit(new_limit))
            out = MicroPartition.from_scan_task(narrowed)
            out.owner_process = self.owner_process
            return out
        return self._wrap(self.table().head(n))

    def sample(self, fraction=None, size=None, with_replacement=False, seed=None) -> "MicroPartition":
        return self._wrap(self.table().sample(fraction, size, with_replacement, seed))

    def sort(self, sort_keys, descending=None, nulls_first=None) -> "MicroPartition":
        return self._wrap(self.table().sort(sort_keys, descending, nulls_first))

    def argsort(self, sort_keys, descending=None, nulls_first=None):
        return self.table().argsort(sort_keys, descending, nulls_first)

    def agg(self, to_agg, group_by=None) -> "MicroPartition":
        if group_by and self._state == "loaded" and len(self._tables) > 1:
            # multi-piece partitions (shuffle buckets) aggregate through ONE
            # chunked acero pass instead of concatenating the pieces first
            out = Table.acero_grouped_agg_chunked(self._tables, to_agg, group_by)
            if out is not None:
                return self._wrap(out)
        return self._wrap(self.table().agg(to_agg, group_by))

    def distinct(self, subset=None) -> "MicroPartition":
        return self._wrap(self.table().distinct(subset))

    def explode(self, exprs) -> "MicroPartition":
        return self._wrap(self.table().explode(exprs))

    def unpivot(self, ids, values, variable_name="variable", value_name="value") -> "MicroPartition":
        return self._wrap(self.table().unpivot(ids, values, variable_name, value_name))

    def pivot(self, group_by, pivot_col, value_col, names, agg_fn="sum") -> "MicroPartition":
        return self._wrap(self.table().pivot(group_by, pivot_col, value_col, names, agg_fn))

    def hash_join(self, right: "MicroPartition", left_on, right_on, how="inner",
                  suffix="right.") -> "MicroPartition":
        return self._wrap(self.table().hash_join(right.table(), left_on, right_on, how, suffix))

    def sort_merge_join(self, right: "MicroPartition", left_on, right_on, how="inner",
                        suffix="right.", is_sorted=False) -> "MicroPartition":
        return self._wrap(self.table().sort_merge_join(right.table(), left_on, right_on,
                                                       how, suffix, is_sorted))

    def add_monotonic_id(self, partition_offset: int = 0, column_name: str = "id") -> "MicroPartition":
        return self._wrap(self.table().add_monotonic_id(partition_offset, column_name))

    def select_columns(self, names: List[str]) -> "MicroPartition":
        if self._state == "unloaded":
            if self._pending:
                # the names may only exist in a deferred projection's output:
                # never push them into the file scan — defer the select
                from .schema import Schema as _S

                return self.with_pending_op(
                    lambda t: t.select_columns(names),
                    _S([self.schema[c] for c in names]),
                    count_preserving=True)
            task = self._scan_task
            pd = task.pushdowns
            cols = [c for c in names]
            narrowed = task.with_pushdowns(pd.with_columns(cols))
            out = MicroPartition.from_scan_task(narrowed)
            out.owner_process = self.owner_process
            return out
        return self._wrap(self.table().select_columns(names))

    def rename_columns(self, mapping: Dict[str, str]) -> "MicroPartition":
        return self._wrap(self.table().rename_columns(mapping))

    def cast_to_schema(self, schema: Schema) -> "MicroPartition":
        return self._wrap(self.table().cast_to_schema(schema))

    def partition_by_hash(self, exprs, num_partitions: int) -> List["MicroPartition"]:
        return self._partition_chunkwise(
            lambda t: t.partition_by_hash(exprs, num_partitions), num_partitions)

    def partition_by_random(self, num_partitions: int, seed: int = 0) -> List["MicroPartition"]:
        # NOT chunk-wise: the assignment is a seeded permutation over row
        # positions, so per-chunk application with the same seed would
        # correlate buckets across chunks instead of matching the collapsed
        # partition's assignment
        return [self._wrap(t) for t in self.table().partition_by_random(num_partitions, seed)]

    def partition_by_range(self, exprs, boundaries: Table, descending=None,
                           nulls_first=None) -> List["MicroPartition"]:
        return self._partition_chunkwise(
            lambda t: t.partition_by_range(exprs, boundaries, descending, nulls_first),
            len(boundaries) + 1)

    def _partition_chunkwise(self, split, num: int) -> List["MicroPartition"]:
        """Row-local partitioners (hash/range: a row's bucket depends only on
        its own values) run per chunk; each bucket chains its per-chunk pieces
        without copying, so a multi-chunk scan partition never pays the full
        concat on the shuffle map side."""
        tabs = self.chunk_tables()
        if len(tabs) == 1:
            return [self._wrap(t) for t in split(tabs[0])]
        buckets: List[List[Table]] = [[] for _ in range(num)]
        for t in tabs:
            for i, bt in enumerate(split(t)):
                if len(bt):
                    buckets[i].append(bt)
        out = []
        for bs in buckets:
            mp = (MicroPartition(self.schema, tables=bs) if bs
                  else MicroPartition.empty(self.schema))
            mp.owner_process = self.owner_process
            out.append(mp)
        return out

    def partition_by_value(self, exprs) -> Tuple[List["MicroPartition"], Table]:
        parts, uniq = self.table().partition_by_value(exprs)
        return [self._wrap(t) for t in parts], uniq

    def hash_rows(self, exprs=None, seed: int = 0):
        return self.table().hash_rows(exprs, seed)

    @staticmethod
    def concat(parts: List["MicroPartition"]) -> "MicroPartition":
        """O(1) concat: chains loaded tables; forces unloaded inputs."""
        if not parts:
            raise ValueError("concat of zero partitions")
        tables: List[Table] = []
        for p in parts:
            if p._state == "loaded":
                tables.extend(p._tables)
            else:
                tables.append(p.table())
        tables = [t for t in tables if len(t) > 0] or [tables[0]]
        return MicroPartition(parts[0].schema, tables=tables)

    def write_tabular(self, root_dir: str, format: str = "parquet",
                      compression: Optional[str] = None, partition_cols=None) -> "MicroPartition":
        from .io.writer import write_tabular

        return self._wrap(write_tabular(self.table(), root_dir, format, compression, partition_cols))
