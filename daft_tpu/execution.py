"""Execution driver: pull-based streaming over the physical operator tree.

Role-equivalent to the reference's src/daft-local-execution/src/run.rs:117
(streaming pipeline executor) + daft/execution/physical_plan.py (the
partition-task generator chain). Each PhysicalOp.execute is a generator;
composing them yields a fully streaming pipeline with early-stop (limit) and
bounded buffering at pipeline breakers.

The ExecutionContext also owns the device-kernel routing decision: eligible
projections run through kernels/device.py (jit'd XLA) when enabled, host
pyarrow otherwise — the TPU analog of the reference's fused
pipeline_instruction execution.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, Iterator, List, Optional

from .context import ExecutionConfig
from .micropartition import MicroPartition
from .physical import PhysicalOp


class QueryCancelledError(RuntimeError):
    """Raised inside a running query after RuntimeStats.cancel()."""


class ResourceRequest:
    """What one task needs while it runs (reference: ResourceRequest,
    src/common/resource-request — num_cpus/num_gpus/memory)."""

    __slots__ = ("num_cpus", "num_gpus", "memory_bytes")

    def __init__(self, num_cpus: float = 0.0, num_gpus: float = 0.0,
                 memory_bytes: int = 0):
        self.num_cpus = num_cpus or 0.0
        self.num_gpus = num_gpus or 0.0
        self.memory_bytes = memory_bytes or 0

    def __bool__(self) -> bool:
        return bool(self.num_cpus or self.num_gpus or self.memory_bytes)

    def __repr__(self):
        return (f"ResourceRequest(cpus={self.num_cpus}, gpus={self.num_gpus}, "
                f"memory={self.memory_bytes})")


def op_resource_request(op) -> ResourceRequest:
    """Sum the resource requests of every UDF an op evaluates (multiple UDFs
    in one projection all run for the same task)."""
    from .expressions import PyUdf

    cpus = gpus = mem = 0

    def walk(node):
        nonlocal cpus, gpus, mem
        if isinstance(node, PyUdf) and node.resource_request:
            c, g, m = node.resource_request
            cpus += c or 0
            gpus += g or 0
            mem += m or 0
        for ch in node.children():
            walk(ch)

    for e in op._map_exprs():
        walk(e._node)
    return ResourceRequest(cpus, gpus, mem)


class ResourceAccountant:
    """Admission control for in-flight tasks (reference: the PyRunner
    admission loop, daft/runners/pyrunner.py:352-370): a task dispatches only
    when its declared cpus/accelerators/memory fit the remaining capacity; an
    impossible request fails fast instead of deadlocking."""

    def __init__(self, cpus: float, gpus, memory_bytes: Optional[int]):
        """gpus may be a float or a zero-arg callable resolved on FIRST use —
        counting accelerators touches the jax backend, which host-only
        queries must never do (a wedged device link would hang them)."""
        self.total_cpus = cpus
        self._gpu_src = gpus
        self._gpus_resolved: Optional[float] = (
            float(gpus) if not callable(gpus) else None)
        self.total_memory = memory_bytes
        self._cpus = cpus
        self._gpus_used = 0.0
        self._memory = memory_bytes
        self._cond = threading.Condition()

    @property
    def total_gpus(self) -> float:
        if self._gpus_resolved is None:
            self._gpus_resolved = float(self._gpu_src())
        return self._gpus_resolved

    def check(self, req: ResourceRequest) -> None:
        """Raise if the request can NEVER be admitted on this host."""
        from .errors import DaftResourceError

        if req.num_cpus > self.total_cpus:
            raise DaftResourceError(
                f"task requests {req.num_cpus} CPUs but only "
                f"{self.total_cpus} exist")
        if req.num_gpus and req.num_gpus > self.total_gpus:
            raise DaftResourceError(
                f"task requests {req.num_gpus} accelerator(s) but only "
                f"{self.total_gpus} exist")
        if self.total_memory is not None and req.memory_bytes > self.total_memory:
            raise DaftResourceError(
                f"task requests {req.memory_bytes} bytes but the memory "
                f"budget is {self.total_memory}")

    def _fits(self, req: ResourceRequest) -> bool:
        gpu_ok = (not req.num_gpus
                  or req.num_gpus <= self.total_gpus - self._gpus_used + 1e-9)
        return (req.num_cpus <= self._cpus + 1e-9 and gpu_ok
                and (self._memory is None or req.memory_bytes <= self._memory))

    def admit(self, req: ResourceRequest) -> None:
        """Block until the request fits, then reserve it."""
        self.check(req)
        with self._cond:
            while not self._fits(req):
                self._cond.wait()
            self._cpus -= req.num_cpus
            self._gpus_used += req.num_gpus
            if self._memory is not None:
                self._memory -= req.memory_bytes

    def release(self, req: ResourceRequest) -> None:
        with self._cond:
            self._cpus += req.num_cpus
            self._gpus_used -= req.num_gpus
            if self._memory is not None:
                self._memory += req.memory_bytes
            self._cond.notify_all()


def _accelerator_count() -> int:
    """Non-CPU jax devices on this host (0 on a CPU-only test mesh)."""
    try:
        import jax

        return sum(1 for d in jax.devices() if d.platform != "cpu")
    except Exception:
        return 0


class RuntimeStats:
    """Per-query counters + the cancellation handle (reference: runtime stats
    in daft-local-execution, and driver-side stop_plan/MaterializedResult
    .cancel() — ray_runner.py:489-502, partitioning.py:192)."""

    def __init__(self):
        from .profile.spans import DISARMED

        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {}
        self.op_rows: Dict[str, int] = {}
        self.op_wall_ns: Dict[str, int] = {}
        self.op_bytes: Dict[str, int] = {}
        self._cancelled = threading.Event()
        # the per-query span/event recorder (profile/spans.py). DISARMED is
        # the shared no-op profiler — collect(profile=...) or an armed
        # chrome trace swaps in a live one before execution starts
        self.profiler = DISARMED
        # the QueryRecord of this handle's most recent plan execution
        # (set by execute_plan's completion hook; df.last_query_record())
        self.last_record = None
        # FDO site observations (daft_tpu/adapt/): canonical subtree
        # fingerprint -> [rows, bytes] accumulated by tagged exchanges/
        # joins, folded into the process history at query end
        self.fdo_obs: Dict[str, list] = {}

    def cancel(self) -> None:
        """Stop the query this handle is attached to at the next partition
        boundary (safe from any thread)."""
        self._cancelled.set()

    def reset_cancel(self) -> None:
        """Re-arm the handle for a fresh run (a cancelled query's DataFrame
        stays usable: retrying clears the previous cancellation)."""
        self._cancelled.clear()

    def is_cancelled(self) -> bool:
        return self._cancelled.is_set()

    def bump(self, key: str, n: int = 1) -> None:
        # counter updates are read-modify-write and arrive concurrently from
        # pool workers, the async spill writer, and prefetch threads — the
        # lock is load-bearing (tests/test_profile.py hammers this)
        with self._lock:
            self.counters[key] = self.counters.get(key, 0) + n

    def bump_max(self, key: str, n: int) -> None:
        """Monotonic-max counter (channel high-water marks and the like):
        the stored value only ever ratchets up to ``n``."""
        with self._lock:
            if n > self.counters.get(key, 0):
                self.counters[key] = n

    def fdo_observe(self, site_fp: str, rows: int, nbytes: int) -> None:
        """Accumulate one FDO site observation (what actually flowed
        through a tagged plan subtree this query)."""
        with self._lock:
            cur = self.fdo_obs.get(site_fp)
            if cur is None:
                self.fdo_obs[site_fp] = [rows, nbytes]
            else:
                cur[0] += rows
                cur[1] += nbytes

    def take_fdo_obs(self) -> Dict[str, tuple]:
        """Drain the accumulated observations (history fold consumes them
        exactly once per execution)."""
        with self._lock:
            out = {k: (v[0], v[1]) for k, v in self.fdo_obs.items()}
            self.fdo_obs.clear()
        return out

    def io_wait(self, ns: int) -> None:
        """Record consumer-thread blocked IO time: the counter AND the
        io_wait phase of the innermost open profiler span, so per-op
        io_wait in a QueryProfile reconciles with the io_wait_ns total."""
        self.bump("io_wait_ns", ns)
        p = self.profiler
        if p.armed:
            p.phase("io_wait", ns)

    def dispatch_wait(self, ns: int) -> None:
        """Head-of-line blocked time in the dispatch loop (queue_wait phase
        on the pulling op's span)."""
        self.bump("dispatch_wait_ns", ns)
        p = self.profiler
        if p.armed:
            p.phase("queue_wait", ns)

    def record_op(self, name: str, rows: int, wall_ns: int,
                  bytes_out: int = 0) -> None:
        with self._lock:
            self.op_rows[name] = self.op_rows.get(name, 0) + rows
            self.op_wall_ns[name] = self.op_wall_ns.get(name, 0) + wall_ns
            if bytes_out:
                self.op_bytes[name] = self.op_bytes.get(name, 0) + bytes_out

    def io_wait_share(self) -> float:
        """Fraction of accumulated operator wall time the execution threads
        spent BLOCKED on IO (scan-prefetch waits and sync scan reads, spill
        read-backs on the consumer thread, sync spill writes, writer-queue
        backpressure). Background prefetch/readahead reads that overlapped
        compute are excluded — this is the residual serialization the
        pipelined-IO layer exists to shrink."""
        with self._lock:
            wait = self.counters.get("io_wait_ns", 0)
            total = sum(self.op_wall_ns.values())
        if wait <= 0:
            return 0.0
        return min(1.0, wait / max(total, wait))

    def io_breakdown(self) -> Dict[str, float]:
        """The io_wait-vs-compute split plus prefetch hit/miss and spill
        write/read throughput — the explain_analyze / bench-snapshot view
        of the pipelined IO layer."""
        with self._lock:
            c = dict(self.counters)

        def mbps(b, ns):
            return (b / 2**20) / (ns / 1e9) if ns > 0 else 0.0

        return {
            "io_wait_share": round(self.io_wait_share(), 4),
            "io_wait_ms": round(c.get("io_wait_ns", 0) / 1e6, 1),
            "prefetch_hits": c.get("prefetch_hits", 0),
            "prefetch_misses": c.get("prefetch_misses", 0),
            "prefetch_throttled": c.get("prefetch_throttled", 0),
            "unspill_readahead_hits": c.get("unspill_readahead_hits", 0),
            "spill_write_mbps": round(
                mbps(c.get("spill_write_bytes", 0),
                     c.get("spill_write_ns", 0)), 1),
            "spill_read_mbps": round(
                mbps(c.get("spill_read_bytes", 0),
                     c.get("spill_read_ns", 0)), 1),
        }

    def op_throughput(self) -> Dict[str, Dict[str, float]]:
        """Per-operator rows/sec and bytes/sec over accumulated wall time —
        the explain_analyze / bench-snapshot throughput view (VERDICT item 1:
        ready to fire on first real-TPU contact)."""
        with self._lock:
            out: Dict[str, Dict[str, float]] = {}
            for name, ns in self.op_wall_ns.items():
                secs = ns / 1e9
                if secs <= 0:
                    continue
                out[name] = {
                    "rows_per_sec": self.op_rows.get(name, 0) / secs,
                    "bytes_per_sec": self.op_bytes.get(name, 0) / secs,
                }
            return out

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self.counters),
                "op_rows": dict(self.op_rows),
                "op_wall_ns": dict(self.op_wall_ns),
                "op_bytes": dict(self.op_bytes),
            }


class DeviceHealth:
    """Circuit breaker for one accelerator resource (device kernels, mesh
    collectives). Closed = normal; after `threshold` CONSECUTIVE failures it
    opens and allow() answers False — callers route straight to the host
    path instead of re-paying the failure per partition (the BENCH_r05
    tpu_unreachable tax). After `cooldown_s` the breaker goes half-open and
    lets exactly ONE probe attempt through: success re-closes it, failure
    re-opens it for another cooldown.

    Counter names are prefixed by `kind` ("device" → device_breaker_trips,
    device_breaker_probes, device_breaker_recoveries, ...)."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, threshold: int = 3, cooldown_s: float = 30.0,
                 kind: str = "device"):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self.kind = kind
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self._probe_started = 0.0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self, stats: Optional[RuntimeStats] = None) -> bool:
        """May an attempt use the resource right now? Open → False; open
        past the cooldown → half-open, admitting one probe."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            now = time.monotonic()
            if (self._state == self.OPEN
                    and now - self._opened_at >= self.cooldown_s):
                self._state = self.HALF_OPEN
            if self._state == self.HALF_OPEN and (
                    not self._probe_inflight
                    # a probe whose resolver was abandoned (limit early-stop
                    # closed the stream before the deferred result resolved)
                    # must not wedge the breaker open forever: reclaim the
                    # slot after one cooldown and let a new probe through
                    or now - self._probe_started >= self.cooldown_s):
                self._probe_inflight = True
                self._probe_started = now
                if stats is not None:
                    stats.bump(f"{self.kind}_breaker_probes")
                    self._emit(stats, "probe")
                return True
            return False

    def _emit(self, stats: Optional["RuntimeStats"], transition: str) -> None:
        """Breaker state transitions are typed events on the profile
        timeline (kind `breaker`) AND structured log lines, so both a trace
        and the always-on flight recorder show exactly when the device path
        opened/recovered relative to the pipeline."""
        from .obs.log import get_logger

        get_logger("breaker").info(f"breaker_{transition}", breaker=self.kind,
                                   state=self._state)
        if stats is not None and stats.profiler.armed:
            stats.profiler.event("breaker", kind=self.kind,
                                 transition=transition, state=self._state)

    def record_success(self, stats: Optional[RuntimeStats] = None) -> None:
        with self._lock:
            self._consecutive = 0
            if self._state == self.HALF_OPEN:
                # only the probe path re-closes the breaker: a straggler
                # async success that launched BEFORE the trip must not close
                # an OPEN breaker and route new work back to a dead device
                self._state = self.CLOSED
                self._probe_inflight = False
                if stats is not None:
                    stats.bump(f"{self.kind}_breaker_recoveries")
                    self._emit(stats, "recovery")

    def record_failure(self, stats: Optional[RuntimeStats] = None) -> None:
        with self._lock:
            self._consecutive += 1
            if self._state == self.HALF_OPEN:
                # probe failed: straight back to open for another cooldown
                self._state = self.OPEN
                self._opened_at = time.monotonic()
                self._probe_inflight = False
                if stats is not None:
                    stats.bump(f"{self.kind}_breaker_reopens")
                    self._emit(stats, "reopen")
            elif (self._state == self.CLOSED
                    and self._consecutive >= self.threshold):
                self._state = self.OPEN
                self._opened_at = time.monotonic()
                if stats is not None:
                    stats.bump(f"{self.kind}_breaker_trips")
                    self._emit(stats, "trip")

    def release_probe(self) -> None:
        """An admitted attempt DECLINED (no failure, no success — e.g. the
        kernel layer judged the data ineligible): free the probe slot so the
        half-open breaker isn't wedged waiting on a result that never comes."""
        with self._lock:
            self._probe_inflight = False


class ExecutionContext:
    def __init__(self, cfg: ExecutionConfig, stats: Optional[RuntimeStats] = None,
                 deadline: Optional[float] = None,
                 device_health: Optional[DeviceHealth] = None,
                 qctx=None):
        self.cfg = cfg
        # the per-query mutable state — stats, deadline, breakers, ledger
        # share, cancellation — lives on a QueryContext (serve/qcontext.py).
        # Runners/the serving runtime build one per query so AQE stages
        # share a single time budget, breaker, and memory share; a context
        # built directly (tests) assembles an implicit solo one from the
        # legacy keyword arguments.
        if qctx is None:
            from .serve.qcontext import QueryContext

            qctx = QueryContext.build(cfg, stats=stats, deadline=deadline,
                                      device_health=device_health)
        self.qctx = qctx
        self.stats = qctx.stats
        self.deadline = qctx.deadline
        self.device_health = qctx.device_health
        # this query's MemoryLedger (a child share of the process root
        # under the serving runtime) and byte budget: every buffer,
        # prefetcher, and the accountant charge/read THESE, never the
        # process-global account
        self.ledger = qctx.ledger
        self.memory_budget = qctx.memory_budget_bytes
        self._pool = None
        # dispatch backend for map-class partition tasks (scheduler.
        # DispatchBackend): None = the in-process pool; the
        # DistributedRunner attaches the supervised WorkerPool here so
        # eligible tasks execute in worker processes
        self.dist_backend = None
        # live-progress tracker (obs/cluster.QueryProgress), set by
        # execute_plan for the execution's lifetime; None for direct op
        # execution in tests — every hook guards on it
        self.progress = None
        # terminal once the query's stream closed: unspill readahead stops
        # submitting (its buffers are settled by finish_query anyway); the
        # scan prefetcher MAY still recreate the pool for late reads — see
        # pool() below
        self._pool_finished = False
        self._spill_scope = None
        self._lineage = None
        self._buffers: List = []
        self._accountant: Optional[ResourceAccountant] = None
        # live streaming segments (stream/pipeline.py): each registers its
        # shutdown so query teardown can close the stream tree even when
        # the pipeline generator is unreachable by close() — an op ABOVE
        # the segment raising leaves the pipeline suspended at a yield,
        # and the exception traceback keeps its frame (and its parked
        # producers) alive until the exception object dies
        self._active_streams: dict = {}
        # shuffle ids whose pieces live on PEER workers (dist/peerplane.py):
        # finish_query tells the pool to drop them fleet-wide — by then
        # every root output has been forced local (see rooted())
        self._peer_shuffles: set = set()

    def register_peer_shuffle(self, sid: int) -> None:
        """Record a peer-hosted shuffle for drop at query finish."""
        self._peer_shuffles.add(sid)

    def check_deadline(self) -> None:
        """Cooperative deadline check (morsel loop, pipeline breakers):
        raises DaftTimeoutError carrying the partial stats accumulated so
        far when execution_timeout_s has been exceeded. Doubles as the
        barrier where async-spill writer-internal errors surface on the
        query thread instead of dying with the writer."""
        if self._spill_scope is not None:
            self._spill_scope.raise_async_errors()
        if self.deadline is not None and time.monotonic() > self.deadline:
            from .errors import DaftTimeoutError
            from .obs.log import get_logger

            limit = (self.qctx.timeout_s if self.qctx.timeout_s is not None
                     else self.cfg.execution_timeout_s)
            self.stats.bump("deadline_expired")
            get_logger("scheduler").warning(
                "deadline_expired", timeout_s=limit)
            raise DaftTimeoutError(
                f"query exceeded execution_timeout_s={limit}",
                stats=self.stats.snapshot())

    @property
    def spill_scope(self):
        """Per-query spill directory (lazily created; removed at query end)."""
        if self._spill_scope is None:
            from .spill import SpillScope

            self._spill_scope = SpillScope()
        return self._spill_scope

    @property
    def lineage(self):
        """This query's bounded LineageLog (integrity/lineage.py), or None
        when lineage recomputation is off. Spilled partitions record how
        they were produced here so a corrupted/missing spill artifact
        recomputes instead of failing the query."""
        if not getattr(self.cfg, "lineage_recomputation", True):
            return None
        if self._lineage is None:
            from .integrity.lineage import LineageLog

            self._lineage = LineageLog(
                getattr(self.cfg, "lineage_log_depth", 4096))
        return self._lineage

    def partition_buffer(self):
        """A spillable PartitionBuffer bound to this query's budget, stats,
        and spill directory. Tracked so abandoned queries (limit early-stop,
        cancellation, errors) still return their held bytes to the ledger."""
        # pipeline breakers are the other cooperative deadline checkpoint
        # (besides the morsel loop): a breaker about to buffer its whole
        # input first proves the query still has time budget
        self.check_deadline()
        from .spill import PartitionBuffer

        buf = PartitionBuffer(
            self.memory_budget, self.stats,
            scope=self.spill_scope,
            async_spill=self.cfg.async_spill_writes,
            readahead=(self._bg_submit if self.cfg.unspill_readahead
                       else None),
            ledger=self.ledger,
            integrity=getattr(self.cfg, "partition_integrity", True),
            lineage=self.lineage)
        self._buffers.append(buf)
        return buf

    def _bg_submit(self, fn):
        """Submit background IO (unspill readahead) onto the shared worker
        pool; raises RuntimeError after shutdown (callers degrade to
        synchronous reads)."""
        if self._pool_finished:
            raise RuntimeError("worker pool already shut down")
        return self.pool().submit(fn)

    @property
    def accountant(self) -> ResourceAccountant:
        """Per-query admission control, sized from host cores, accelerator
        count, and the configured memory budget."""
        if self._accountant is None:
            import os as _os

            try:
                cores = len(_os.sched_getaffinity(0))
            except AttributeError:
                cores = _os.cpu_count() or 1
            self._accountant = ResourceAccountant(
                cpus=float(max(cores, self.num_workers)),
                gpus=_accelerator_count,  # resolved only if a task asks
                memory_bytes=self.memory_budget)
        return self._accountant

    def register_stream(self, shutdown) -> object:
        """Track a running streaming segment's shutdown for teardown;
        returns a token for :meth:`unregister_stream`."""
        token = object()
        self._active_streams[token] = shutdown
        return token

    def unregister_stream(self, token) -> None:
        self._active_streams.pop(token, None)

    def close_streams(self, short_circuit: bool) -> None:
        """Shut down every still-registered streaming segment (idempotent
        per segment). ``short_circuit`` says whether abandoned work counts
        as ``morsels_short_circuited`` (deliberate early stop) or not
        (error/cancel/deadline teardown — a failed query's record must not
        read as if a limit fired)."""
        while self._active_streams:
            _, shutdown = self._active_streams.popitem()
            try:
                shutdown(short_circuit=short_circuit)
            except BaseException as e:
                from .obs.log import get_logger

                get_logger("execution").warning(
                    "stream_shutdown_failed", error=repr(e))

    def finish_query(self) -> None:
        """Release buffer accounting and delete this query's spill files."""
        for b in self._buffers:
            b.release()
        self._buffers.clear()
        if self._spill_scope is not None:
            self._spill_scope.cleanup()
            self._spill_scope = None
        if self._peer_shuffles:
            sids, self._peer_shuffles = list(self._peer_shuffles), set()
            backend = self.dist_backend
            drop = getattr(backend, "drop_shuffles", None)
            if drop is not None:
                try:
                    drop(sids)
                except Exception:
                    pass  # pool mid-teardown: workers clear on exit anyway

    @property
    def num_workers(self) -> int:
        from .context import resolve_executor_threads

        n = resolve_executor_threads(self.cfg)
        if self.dist_backend is not None:
            # a remote-dispatched task occupies a LOCAL pool thread for the
            # round trip, so the local pool must cover the whole worker
            # fleet (plus one driver-side slot) or the cluster idles
            n = max(n, self.dist_backend.capacity() + 1)
        return n

    def pool(self):
        """Lazily-created worker pool; shut down by execute_plan. Under the
        serving runtime this is a per-query CLIENT of the shared
        SharedExecutorPool (fair FIFO across admitted queries) instead of a
        private executor. A post-shutdown call (scan-prefetch serving late
        reads, e.g. to_pydict over an unforced collect) recreates a private
        pool; the recreated pool is released by GC when the last partition
        referencing the prefetcher loads or dies."""
        if self._pool is None:
            shared = self.qctx.shared_pool
            if shared is not None and not self._pool_finished:
                self._pool = shared.client(
                    self.qctx.query_id or f"ctx-{id(self):x}")
            else:
                from concurrent.futures import ThreadPoolExecutor

                self._pool = ThreadPoolExecutor(
                    max_workers=self.num_workers,
                    thread_name_prefix="daft-exec")
        return self._pool

    def shutdown_pool(self) -> None:
        self._pool_finished = True
        if self._pool is not None:
            # a shared-pool client interprets this as close(): the SHARED
            # executor outlives the query; only its queue is torn down
            self._pool.shutdown(wait=False)
            self._pool = None

    def _device_allowed(self) -> bool:
        """Breaker gate for work that IS device-eligible: an open breaker
        sends it to the host path and counts the degraded completion."""
        if self.device_health.allow(self.stats):
            return True
        self.stats.bump("degraded_completions")
        return False

    def _device_eligible(self, part: MicroPartition) -> bool:
        return (self.cfg.use_device_kernels
                and (part.num_rows_or_none() or 0) >= self.cfg.device_min_rows
                and self._device_allowed())

    def _device_attempt(self, fn, launch: bool = False):
        """Run one device-path attempt under the fault registry + breaker.
        An exception records a breaker failure and returns None (the device
        layer's decline convention); a None result is a decline (probe slot
        released, breaker untouched). A non-None result records success —
        unless `launch` is set, in which case the caller owns the outcome
        (async dispatch: the launch succeeding says nothing about the
        deferred computation, whose resolver records for real)."""
        from . import faults

        prof = self.stats.profiler
        t0 = time.perf_counter_ns() if prof.armed else 0
        try:
            faults.check("device.kernel", self.stats)
            out = fn()
        except Exception:
            self.device_health.record_failure(self.stats)
            return None
        finally:
            if prof.armed:
                # the host-side cost of staging + launching (sync attempts
                # include the kernel wall; async launches just the dispatch)
                prof.phase("device_dispatch", time.perf_counter_ns() - t0)
        if out is None:
            self.device_health.release_probe()
        elif not launch:
            self.device_health.record_success(self.stats)
        return out

    def foreign_owned(self, part: MicroPartition) -> bool:
        """True when this process must not materialize `part` (another host
        of a multi-process run owns its rows). Single-process: never."""
        return False

    def _defer_projection(self, part: MicroPartition, exprs):
        """Foreign-owned unloaded partition: append the projection to the
        partition's pending op chain instead of reading the file (per-host
        scan locality through map chains; the owner evaluates for real)."""
        from .schema import Schema

        exprs = list(exprs)
        schema = Schema([e._node.to_field(part.schema) for e in exprs])
        return part.with_pending_op(
            lambda t: t.eval_expression_list(exprs), schema,
            count_preserving=True)

    def eval_projection(self, part: MicroPartition, exprs) -> MicroPartition:
        """Route a projection through the device kernel layer when eligible,
        else the host path."""
        if self.foreign_owned(part) and not part.is_loaded():
            return self._defer_projection(part, exprs)
        if self._device_eligible(part):
            def _run():
                from .kernels.device import eval_projection_device

                return eval_projection_device(
                    part.table(), list(exprs),
                    stage_cache=part.device_stage_cache())

            out = self._device_attempt(_run)
            if out is not None:
                self.stats.bump("device_projections")
                return part._wrap(out)
        self.stats.bump("host_projections")
        return part.eval_expression_list(exprs)

    def eval_projection_dispatch(self, part: MicroPartition, exprs):
        """Launch a device projection without blocking; returns a zero-arg
        resolver yielding the output MicroPartition, or None when the device
        path is ineligible (caller falls back to the synchronous
        eval_projection). The resolver itself falls back to the host kernel
        if the deferred device computation fails at materialization."""
        if self.foreign_owned(part) and not part.is_loaded():
            deferred = self._defer_projection(part, exprs)
            return lambda: deferred
        if not self._device_eligible(part):
            return None

        def _launch():
            from .kernels.device import eval_projection_device_async

            return eval_projection_device_async(
                part.table(), list(exprs), stage_cache=part.device_stage_cache())

        resolve = self._device_attempt(_launch, launch=True)
        if resolve is None:
            return None
        self.stats.bump("device_projections")
        self.stats.bump("device_projection_dispatches")

        def finish() -> MicroPartition:
            try:
                out = part._wrap(resolve())
            except Exception:
                # the partition was NOT computed on device after all: keep
                # the counters truthful (same attribution the synchronous
                # path's fallback produces)
                self.device_health.record_failure(self.stats)
                self.stats.bump("device_projections", -1)
                self.stats.bump("device_projection_fallbacks")
                self.stats.bump("host_projections")
                return part.eval_expression_list(exprs)
            self.device_health.record_success(self.stats)
            return out

        return finish

    def _defer_fused(self, part: MicroPartition, program):
        """Foreign-owned unloaded partition: the whole fused program joins
        the pending op chain (one deferred single-pass map), preserving
        per-host scan locality exactly like the unfused chain's deferred
        Project/Filter ops would."""
        return part.with_pending_op(
            lambda t: program.run_host(t), program.out_schema,
            count_preserving=program.count_preserving)

    def _eval_fused_host(self, part: MicroPartition, program) -> MicroPartition:
        """Host single-pass evaluation of a fused chain. The legacy per-op
        class counters advance by the chain's op counts so per-path
        attribution stays comparable with the unfused engine."""
        self.stats.bump("host_fused_maps")
        g = program.graph
        if g.n_project_ops:
            self.stats.bump("host_projections", g.n_project_ops)
        if g.n_filter_ops:
            self.stats.bump("host_filters", g.n_filter_ops)
        return part._wrap(program.run_host(part.table()))

    def _bump_fused_device(self, program, n: int = 1) -> None:
        g = program.graph
        self.stats.bump("device_fused_maps", n)
        if g.n_project_ops:
            self.stats.bump("device_projections", n * g.n_project_ops)
        if g.n_filter_ops:
            self.stats.bump("device_filters", n * g.n_filter_ops)

    def eval_fused(self, part: MicroPartition, program) -> MicroPartition:
        """Route a fused map chain through the device kernel layer as ONE
        jit program when eligible, else the segmented host pass."""
        if self.foreign_owned(part) and not part.is_loaded():
            return self._defer_fused(part, program)
        if program.device_exprs is not None and self._device_eligible(part):
            def _run():
                from .kernels.device import eval_projection_device

                out = eval_projection_device(
                    part.table(), program.device_exprs,
                    stage_cache=part.device_stage_cache())
                return None if out is None else program.assemble_device(out)

            out = self._device_attempt(_run)
            if out is not None:
                self._bump_fused_device(program)
                return part._wrap(out)
        return self._eval_fused_host(part, program)

    def eval_fused_dispatch(self, part: MicroPartition, program):
        """Non-blocking launch of the fused device program; same resolver
        contract as eval_projection_dispatch (host fallback inside,
        truthful counters)."""
        if self.foreign_owned(part) and not part.is_loaded():
            deferred = self._defer_fused(part, program)
            return lambda: deferred
        if program.device_exprs is None or not self._device_eligible(part):
            return None

        def _launch():
            from .kernels.device import eval_projection_device_async

            return eval_projection_device_async(
                part.table(), program.device_exprs,
                stage_cache=part.device_stage_cache())

        resolve = self._device_attempt(_launch, launch=True)
        if resolve is None:
            return None
        self._bump_fused_device(program)
        self.stats.bump("device_fused_map_dispatches")

        def finish() -> MicroPartition:
            try:
                out = program.assemble_device(resolve())
            except Exception:
                # the chain was NOT computed on device after all: keep the
                # counters truthful, inform the breaker, host pass takes over
                self.device_health.record_failure(self.stats)
                self._bump_fused_device(program, -1)
                self.stats.bump("device_fused_map_fallbacks")
                return self._eval_fused_host(part, program)
            self.device_health.record_success(self.stats)
            return part._wrap(out)

        return finish

    def eval_sort(self, part: MicroPartition, sort_by, descending=None,
                  nulls_first=None) -> MicroPartition:
        """Route a per-partition sort through the device argsort when
        eligible: keys compile + sort on device, only the payload take runs
        on host. Host pyarrow sort otherwise."""
        if self._device_eligible(part):
            def _run():
                from .kernels.device import device_table_argsort

                return device_table_argsort(
                    part.table(), sort_by, descending, nulls_first,
                    stage_cache=part.device_stage_cache())

            idx = self._device_attempt(_run)
            if idx is not None:
                import numpy as np

                from .series import Series

                self.stats.bump("device_sorts")
                tbl = part.table().take(
                    Series.from_numpy(idx.astype(np.uint64), "indices"))
                return MicroPartition.from_table(tbl)
        self.stats.bump("host_sorts")
        return part.sort(sort_by, descending, nulls_first)

    def eval_distinct(self, part: MicroPartition, subset) -> MicroPartition:
        """Route distinct through the device group-codes kernel when the keys
        are device-eligible; host dictionary encode otherwise."""
        if self._device_eligible(part):
            def _run():
                from .expressions import col
                from .kernels.device_agg import device_distinct_indices

                keys = list(subset) if subset else [
                    col(n) for n in part.column_names]
                return device_distinct_indices(
                    part.table(), keys, part.device_stage_cache(),
                    len(part.table()))

            idx = self._device_attempt(_run)
            if idx is not None:
                import numpy as np

                from .series import Series

                self.stats.bump("device_distincts")
                tbl = part.table().take(
                    Series.from_numpy(idx.astype(np.uint64), "idx"))
                return MicroPartition.from_table(tbl)
        self.stats.bump("host_distincts")
        return part.distinct(subset)

    def _sketch_build_device(self, part: MicroPartition, aggregations,
                             groupby, predicate):
        """Stage-1 sketch builds (all-sketch_hll agg lists) run their
        register scatter on device when eligible — behind the same
        DeviceHealth breaker + device.kernel fault site as every other
        device kernel. The agg-kind gate runs FIRST, before any breaker or
        fault-site touch, so non-sketch aggregations never consume a probe
        slot or a planned fault. Returns a zero-arg resolver (launch
        already dispatched; the resolver fetches + assembles, host-fallback
        inside) or None = declined (the normal agg routing takes over)."""
        from .sketch.device import aggs_all_sketch_hll

        if (predicate is not None
                or not aggs_all_sketch_hll(aggregations)
                or not self._device_eligible(part)):
            return None

        def _launch():
            from .sketch.device import hll_build_table_device_launch

            return hll_build_table_device_launch(
                part.table(), list(aggregations), list(groupby or []))

        resolve = self._device_attempt(_launch, launch=True)
        if resolve is None:
            return None
        self.stats.bump("device_sketch_builds")

        def finish() -> MicroPartition:
            try:
                out = resolve()
            except Exception:
                # the scatter was NOT computed on device: truthful counters,
                # breaker informed, host build takes over
                self.device_health.record_failure(self.stats)
                self.stats.bump("device_sketch_builds", -1)
                self.stats.bump("device_sketch_fallbacks")
                return self._eval_agg_host(part, aggregations, groupby,
                                           predicate)
            self.device_health.record_success(self.stats)
            return MicroPartition.from_table(out)

        return finish

    def eval_agg(self, part: MicroPartition, aggregations, groupby,
                 predicate=None) -> MicroPartition:
        """Route a (optionally filter-fused) grouped aggregation through the
        fused device kernel when eligible, else the host path (host applies
        the predicate first when one was fused)."""
        fin = self._sketch_build_device(part, aggregations, groupby,
                                        predicate)
        if fin is not None:
            return fin()
        if self._device_eligible(part):
            def _run():
                from .kernels.device_agg import device_grouped_agg

                return device_grouped_agg(part.table(), list(aggregations),
                                          list(groupby or []),
                                          stage_cache=part.device_stage_cache(),
                                          predicate=predicate,
                                          stats=self.stats)

            out = self._device_attempt(_run)
            if out is not None:
                self.stats.bump("device_aggregations")
                return MicroPartition.from_table(out)
        return self._eval_agg_host(part, aggregations, groupby, predicate)

    def _eval_agg_host(self, part: MicroPartition, aggregations, groupby,
                       predicate=None) -> MicroPartition:
        self.stats.bump("host_aggregations")
        if predicate is not None:
            tbl = part.table()
            # acero single-pass pays off when the hash-agg subsumes the
            # filtered-table materialization; ungrouped reductions are faster
            # through the pruned filter+agg below (measured on TPC-H Q6)
            out = tbl.acero_fused_agg(list(aggregations), list(groupby or []),
                                      predicate) if groupby else None
            if out is not None:
                self.stats.bump("fused_host_aggregations")
                return MicroPartition.from_table(out)
            # unfused fallback: prune to referenced columns before filtering
            # so the compaction doesn't copy payload the agg never reads
            from .expressions import required_columns

            need = set()
            for e in list(aggregations) + list(groupby or []) + [predicate]:
                need.update(required_columns(e))
            if need and need < set(part.column_names):
                keep = [n for n in part.column_names if n in need]
                part = MicroPartition.from_table(tbl.select_columns(keep))
            part = part.filter([predicate])
        return part.agg(aggregations, groupby or None)

    def eval_agg_dispatch(self, part: MicroPartition, aggregations, groupby,
                          predicate=None):
        """Non-blocking launch of the fused device aggregation; returns a
        zero-arg resolver (host-fallback inside, truthful counters) or None
        when ineligible — same contract as eval_projection_dispatch."""
        fin = self._sketch_build_device(part, aggregations, groupby,
                                        predicate)
        if fin is not None:
            return fin  # scatter already dispatched; resolver fetches
        if not self._device_eligible(part):
            return None

        def _launch():
            from .kernels.device_agg import device_grouped_agg_async

            return device_grouped_agg_async(
                part.table(), list(aggregations), list(groupby or []),
                stage_cache=part.device_stage_cache(), predicate=predicate,
                stats=self.stats)

        resolve = self._device_attempt(_launch, launch=True)
        if resolve is None:
            return None
        self.stats.bump("device_aggregations")
        self.stats.bump("device_agg_dispatches")

        def finish() -> MicroPartition:
            try:
                out = resolve()
            except Exception:
                out = None
                self.device_health.record_failure(self.stats)
            if out is not None:
                self.device_health.record_success(self.stats)
                return MicroPartition.from_table(out)
            # overflow guard (a decline, not a device failure) or deferred
            # failure: partition was NOT aggregated on device — keep the
            # counters truthful
            self.device_health.release_probe()
            self.stats.bump("device_aggregations", -1)
            self.stats.bump("device_agg_fallbacks")
            return self._eval_agg_host(part, aggregations, groupby, predicate)

        return finish

    def eval_segment(self, part: MicroPartition, op) -> MicroPartition:
        """Route a compiled plan segment (fuse/segment.py DeviceSegmentOp)
        through the HBM-resident pipeline when eligible, else the retained
        staged per-op path — byte-identical either way."""
        with self.stats.profiler.span("fuse.segment", kind="phase"):
            fin = self.eval_segment_dispatch(part, op)
            if fin is not None:
                return fin()
            # device-ineligible partition (size/breaker/foreign): plain
            # routing to the staged pipeline, not a degradation
            return self._eval_segment_staged(part, op, degraded=False)

    def eval_segment_dispatch(self, part: MicroPartition, op):
        """Non-blocking launch of the resident segment pipeline; returns a
        zero-arg resolver (staged fallback inside, truthful counters) or
        None when this partition is device-ineligible. The whole leg sits
        behind the DeviceHealth breaker: a launch exception (including an
        armed ``fuse.segment`` fault) records a breaker failure; a decline
        releases the probe slot."""
        if self.foreign_owned(part) and not part.is_loaded():
            return None
        if not self._device_eligible(part):
            return None

        def _launch():
            from .fuse.segment import run_segment_async

            return run_segment_async(part.table(), op.program,
                                     part.device_stage_cache(),
                                     stats=self.stats, cfg=self.cfg)

        resolve = self._device_attempt(_launch, launch=True)
        if resolve is None:
            # the resident attempt was made and failed/declined: degraded
            return lambda: self._eval_segment_staged(part, op, degraded=True)
        self.stats.bump("device_aggregations")
        self.stats.bump("segment_dispatches")

        def finish() -> MicroPartition:
            with self.stats.profiler.span("fuse.segment", kind="phase"):
                try:
                    out = resolve()
                except Exception:
                    out = None
                    self.device_health.record_failure(self.stats)
                if out is not None:
                    self.device_health.record_success(self.stats)
                    # ONE boundary crossed resident: the map→agg Arrow
                    # round-trip of the staged plan did not happen
                    self.stats.bump("device_handoffs_elided")
                    op._record_resident(self)
                    from .fuse.segment import _proc_bump

                    _proc_bump("handoffs_elided")
                    return MicroPartition.from_table(out)
                # overflow guard (a decline) or deferred failure: the
                # segment was NOT executed resident — keep counters truthful
                self.device_health.release_probe()
                self.stats.bump("device_aggregations", -1)
                return self._eval_segment_staged(part, op, degraded=True)

        return finish

    def _eval_segment_staged(self, part: MicroPartition, op,
                             degraded: bool = True) -> MicroPartition:
        """The segment as its retained staged ops: the fused map chain,
        Arrow materialization, then the (filter-fused) aggregation —
        EXACTLY the plan the segment pass collapsed, so results are
        byte-identical. `degraded` marks a resident attempt that failed
        (counted), vs. plain routing of an ineligible partition (not)."""
        if degraded:
            self.stats.bump("segment_fallbacks")
            from .fuse.segment import _proc_bump

            _proc_bump("segment_fallbacks")
        mid = op.staged_map(part, self)
        return op.staged_agg(mid, self)

    def prepare_broadcast(self, part: MicroPartition, on_exprs,
                          how: str = "inner") -> MicroPartition:
        """Hook for runners with a device mesh: replicate a broadcast-join
        build side into every device's HBM once, so per-partition probes use
        a local replica instead of re-shipping the build keys. Single-host
        base context: no-op."""
        return part

    def eval_join(self, lpart: MicroPartition, rpart: MicroPartition,
                  left_on, right_on, how: str, suffix: str) -> MicroPartition:
        """Blocking join: the pipelined dispatch-or-declined pair in one
        call, so there is exactly ONE join code path (kernels/device_join.py
        when eligible, host acero otherwise)."""
        fin = self.eval_join_dispatch(lpart, rpart, left_on, right_on, how,
                                      suffix)
        if fin is not None:
            return fin()
        return self.eval_join_declined(lpart, rpart, left_on, right_on, how,
                                       suffix)

    def _join_eligible(self, lpart, rpart, left_on, right_on, how) -> bool:
        return (self.cfg.use_device_kernels
                and how in ("inner", "left", "semi", "anti")
                and 1 <= len(left_on) == len(right_on) <= 4
                and max(lpart.num_rows_or_none() or 0,
                        rpart.num_rows_or_none() or 0)
                >= self.cfg.device_min_rows
                and self._device_allowed())

    def _assemble_join(self, res, lpart, rpart, left_on, right_on, how,
                       suffix) -> MicroPartition:
        """(side, hit, bidx) probe result -> output partition (shared by the
        blocking and pipelined join paths)."""
        import numpy as np

        from .series import Series

        side, hit, bidx = res
        ltbl, rtbl = lpart.table(), rpart.table()
        if side == "expanded":
            # N:M range join: (lidx, ridx) pairs already expanded on
            # host from the device range probe (-1 = left-outer miss)
            out = ltbl.join_from_indices(rtbl, hit, bidx,
                                         left_on, right_on, suffix)
        elif side == "right_build":
            if how == "semi":
                out = ltbl.filter_with_mask(Series.from_numpy(hit, "m"))
            elif how == "anti":
                out = ltbl.filter_with_mask(Series.from_numpy(~hit, "m"))
            elif how == "inner":
                lidx = np.nonzero(hit)[0]
                out = ltbl.join_from_indices(rtbl, lidx, bidx[hit],
                                             left_on, right_on, suffix)
            else:  # left outer: every left row, -1 -> null right
                lidx = np.arange(len(ltbl), dtype=np.int64)
                ridx = np.where(hit, bidx, -1)
                out = ltbl.join_from_indices(rtbl, lidx, ridx,
                                             left_on, right_on, suffix)
        else:  # left_build (inner only): re-sort to host (lidx, ridx) order
            ridx = np.nonzero(hit)[0]
            lidx = bidx[hit]
            order = np.argsort(lidx, kind="stable")
            out = ltbl.join_from_indices(rtbl, lidx[order], ridx[order],
                                         left_on, right_on, suffix)
        return MicroPartition.from_table(out)

    def eval_join_dispatch(self, lpart: MicroPartition, rpart: MicroPartition,
                           left_on, right_on, how: str, suffix: str):
        """Non-blocking join launch: stage both sides' keys and dispatch the
        right-build range probe now; the returned finisher resolves the
        probe and assembles the output — the join op stages pair i+1 while
        pair i probes (same contract as eval_projection_dispatch; PARITY
        known-gap 36). Returns None when ineligible (caller joins
        synchronously)."""
        if not self._join_eligible(lpart, rpart, left_on, right_on, how):
            return None

        def _launch():
            from .kernels.device_join import (device_join_launch,
                                              join_key_replicas)

            single = len(left_on) == 1
            return device_join_launch(
                lpart.table(), rpart.table(), list(left_on), list(right_on),
                lpart.device_stage_cache(), rpart.device_stage_cache(), how,
                left_replicas=(join_key_replicas(lpart, left_on[0])
                               if single else None),
                right_replicas=(join_key_replicas(rpart, right_on[0])
                                if single else None))

        launch = self._device_attempt(_launch, launch=True)
        if launch is None:
            return None
        self.stats.bump("device_join_dispatches")

        def finish() -> MicroPartition:
            try:
                res = launch()
            except Exception:
                self.device_health.record_failure(self.stats)
                self.stats.bump("device_join_fallbacks")
                self.stats.bump("host_joins")
                return lpart.hash_join(rpart, left_on, right_on, how, suffix)
            self.device_health.record_success(self.stats)
            # assembly runs OUTSIDE the catch-all: a defect there must crash
            # loudly, not silently recompute on host (same error contract
            # as the blocking path)
            out = self._assemble_join(res, lpart, rpart, left_on,
                                      right_on, how, suffix)
            self.stats.bump("device_join_probes")
            return out

        return finish

    def eval_join_declined(self, lpart, rpart, left_on, right_on, how,
                           suffix) -> MicroPartition:
        """Host join for a pair the dispatch already proved device-
        ineligible — never re-stage a doomed attempt (the
        map_partition_declined convention)."""
        self.stats.bump("host_joins")
        return lpart.hash_join(rpart, left_on, right_on, how, suffix)

    def _defer_filter(self, part: MicroPartition, predicate):
        return part.with_pending_op(
            lambda t: t.filter([predicate]), part.schema,
            count_preserving=False)

    def eval_filter(self, part: MicroPartition, predicate) -> MicroPartition:
        """Filter a partition: when eligible, the predicate mask is computed on
        device and only the compaction happens on host."""
        if self.foreign_owned(part) and not part.is_loaded():
            return self._defer_filter(part, predicate)
        if self._device_eligible(part):
            def _run():
                from .kernels.device import eval_projection_device

                return eval_projection_device(
                    part.table(), [predicate],
                    stage_cache=part.device_stage_cache())

            out = self._device_attempt(_run)
            if out is not None:
                self.stats.bump("device_filters")
                mask = out._columns[0]
                return part._wrap(part.table().filter_with_mask(mask))
        self.stats.bump("host_filters")
        return part.filter([predicate])

    def eval_filter_dispatch(self, part: MicroPartition, predicate):
        """Non-blocking launch of the device filter mask; the resolver pulls
        the mask back and compacts on host — same contract as
        eval_projection_dispatch."""
        if self.foreign_owned(part) and not part.is_loaded():
            deferred = self._defer_filter(part, predicate)
            return lambda: deferred
        if not self._device_eligible(part):
            return None

        def _launch():
            from .kernels.device import eval_projection_device_async

            return eval_projection_device_async(
                part.table(), [predicate],
                stage_cache=part.device_stage_cache())

        resolve = self._device_attempt(_launch, launch=True)
        if resolve is None:
            return None
        self.stats.bump("device_filters")
        self.stats.bump("device_filter_dispatches")

        def finish() -> MicroPartition:
            try:
                out = resolve()
                mask = out._columns[0]
                result = part._wrap(part.table().filter_with_mask(mask))
            except Exception:
                self.device_health.record_failure(self.stats)
                self.stats.bump("device_filters", -1)
                self.stats.bump("device_filter_fallbacks")
                self.stats.bump("host_filters")
                return part.filter([predicate])
            self.device_health.record_success(self.stats)
            return result

        return finish


_QUERY_SEQ = itertools.count(1)
_DONE = object()  # stream-exhausted sentinel for the per-pull context loop


def _classify_outcome(e: BaseException) -> str:
    from .errors import DaftTimeoutError

    if isinstance(e, DaftTimeoutError):
        return "timeout"
    if isinstance(e, QueryCancelledError):
        return "cancelled"
    return "error"


def _record_query(root: PhysicalOp, ctx: ExecutionContext, query_id: str,
                  fingerprint: str, plan_ops: Dict[str, int], wall_ns: int,
                  outcome: str, error, rows_emitted: int) -> None:
    """Completion hook: append the QueryRecord (every outcome, including
    the error/timeout paths — this runs in execute_plan's ``finally``) and
    hand it to the slow/failed-query auto-capture. ``enable_query_log``
    gates only the ring (and ``last_query_record``); the diagnostics
    capture contract — errored/deadline-killed queries always bundle when
    ``diagnostics_dir`` is set — survives a disabled log. Observability
    must never fail the query: any defect here degrades to an error log."""
    cfg = ctx.cfg
    canonical = getattr(root, "_canonical_fp", "")
    want_log = getattr(cfg, "enable_query_log", True)
    want_capture = (getattr(cfg, "diagnostics_dir", None)
                    or getattr(cfg, "slow_query_threshold_s", None)
                    is not None)
    rec = None
    if want_log or want_capture:
        try:
            from .obs import capture as obs_capture
            from .obs.querylog import QUERY_LOG, build_record

            prof = ctx.stats.profiler
            rec = build_record(query_id, fingerprint, plan_ops, cfg,
                               ctx.stats, wall_ns, outcome, error=error,
                               profiled=prof.armed,
                               rows_emitted=rows_emitted,
                               canonical=canonical)
            if want_log:
                QUERY_LOG.resize(cfg.query_log_depth)
                QUERY_LOG.append(rec)
                ctx.stats.last_record = rec
            obs_capture.maybe_capture(rec, cfg, ctx.stats, prof)
        except Exception as e:
            from .obs.log import get_logger

            get_logger("obs").error("query_record_failed", error=repr(e))
    if getattr(cfg, "history_fdo", True):
        # fold this execution's FDO observations + profile into the
        # process history (daft_tpu/adapt/history.py) — the input of the
        # next plan of this shape. Never fails the query.
        try:
            from .adapt.history import HISTORY

            HISTORY.fold(canonical, ctx.stats, rec if rec is not None
                         else {"outcome": outcome,
                               "wall_s": wall_ns / 1e9,
                               "counters": ctx.stats.snapshot()["counters"]})
        except Exception as e:
            from .obs.log import get_logger

            get_logger("obs").error("history_fold_failed", error=repr(e))
    if getattr(cfg, "cache_dir", None) is not None:
        # warm-start artifact leg (daft_tpu/persist/): snapshot the plan
        # cache + history to disk when they moved this query. maybe_save
        # is fail-open by contract; the guard here is belt-and-braces.
        try:
            from . import persist

            persist.maybe_save(cfg, ctx.stats)
        except Exception as e:
            from .obs.log import get_logger

            get_logger("obs").error("persist_save_failed", error=repr(e))


def execute_plan(root: PhysicalOp, ctx: ExecutionContext,
                 trace: bool = True) -> Iterator[MicroPartition]:
    """Wire up the generator tree and return the root partition stream.

    Every op is wrapped with per-partition accounting (rows + wall time into
    RuntimeStats, feeding explain_analyze) and — when the query's profiler
    is armed — with profiler spans. A chrome trace armed without an armed
    profiler (tracing.chrome_trace / DAFT_TPU_CHROME_TRACE) arms one here:
    the chrome output is rendered FROM the span tree at query end (one
    consolidated writer, re-armed per query) — and so does the slow-query
    auto-capture when a previous run of this plan fingerprint crossed
    ``cfg.slow_query_threshold_s``.

    The flight recorder (daft_tpu/obs/) hooks both ends: the query id is
    bound as structured-log context for the query's lifetime, and EVERY
    completion — success, error, deadline kill, cancel, abandoned stream —
    appends a QueryRecord to the process query log."""
    from . import tracing
    from .obs import log as obs_log
    from .obs.querylog import plan_signature

    fingerprint, plan_ops = plan_signature(root)
    # the query's canonical (literal-masked) shape fingerprint, stamped by
    # the planner (adapt/plancache.plan_query); ops consult it for FDO
    # mispredict demotion, the completion hook for the QueryRecord
    ctx.canonical_fp = getattr(root, "_canonical_fp", "")
    prof = ctx.stats.profiler
    if prof.armed:
        query_id = prof.query_id
    else:
        # serving-runtime queries carry their admission-visible id through
        # the whole observability stack (records, logs, health)
        query_id = ctx.qctx.query_id or f"q-{next(_QUERY_SEQ)}"
        arm = tracing.active()
        if not arm:
            # slow-query auto-arm is part of the capture contract, which
            # survives a disabled query log
            from .obs import capture as obs_capture

            arm = obs_capture.take_arm(fingerprint)
        if arm:
            from .profile.spans import Profiler

            ctx.stats.profiler = Profiler(query_id=query_id)
    parallel = ctx.num_workers > 1

    def build(op: PhysicalOp) -> Iterator[MicroPartition]:
        # sub-plan result cache (daft_tpu/adapt/resultcache.py): a
        # scan+project/filter prefix another query already materialized
        # replays its cached partitions (or tees its output in on this
        # first execution). Declines (knob off, mesh/multi-host, UDFs,
        # unstattable sources) fall through; fails open.
        from .adapt.resultcache import try_result_cache

        served = try_result_cache(op, ctx, build, trace)
        if served is not None:
            return served
        # morsel-driven streaming (daft_tpu/stream/): a streamable segment
        # rooted here replaces its whole op chain with one pipelined
        # stream — bounded channels, producer stages on the worker pool,
        # byte-identical re-chunked output. Declines (device path, mesh,
        # UDFs, no streamable chain) fall through to the normal build.
        from .stream.pipeline import try_stream

        pipe = try_stream(op, ctx, build, trace)
        if pipe is not None:
            return pipe
        child_streams = [build(c) for c in op.children]
        if getattr(op, "batch_declared", False) and ctx.dist_backend is None:
            # dynamic-batching UDFs (physical.BatchedUdfOp): the op's own
            # execute() coalesces across partitions — thread fan-out would
            # re-pin batch size to partition size. Under a distributed
            # backend we fall through instead: workers run map_partition
            # and host the pinned model actors process-locally.
            stream = op.execute(child_streams, ctx)
            return _traced(op, stream, ctx) if trace else stream
        if (parallel and op.map_partition is not None and len(child_streams) == 1
                and op.parallel_safe()):
            if op.device_pipelinable(ctx) and not op_resource_request(op):
                # device compute serializes on one chip: prefer the
                # double-buffered sequential driver — but fall back to thread
                # fan-out if the first partition declines the device path
                return _adaptive_device_map(op, child_streams[0], ctx, trace)
            # instrumentation happens inside the workers (the consumer-side
            # wrapper would only measure blocked-wait time)
            return _parallel_map(op, child_streams[0], ctx)
        stream = op.execute(child_streams, ctx)
        if trace:
            return _traced(op, stream, ctx)
        return stream

    built = build(root)

    def rooted():
        t0 = time.perf_counter_ns()
        outcome, error = "ok", None
        rows_out = 0
        saw_first_rows = False
        it = iter(built)
        # live query progress (obs/cluster.py): registered while this
        # execution runs, snapshotted by dt.health()["queries"] /
        # QueryHandle.progress(); last-wins per query id across AQE stages
        from .obs.cluster import (QueryProgress, register_progress,
                                  unregister_progress)

        progress = QueryProgress(query_id, ctx.stats, plan_ops)
        ctx.progress = progress
        register_progress(progress)
        try:
            # the query id binds per PULL, never across a yield: two lazily
            # interleaved streams on one thread would otherwise cross-
            # attribute (and unbind) each other's log context
            while True:
                with obs_log.query_context(query_id):
                    part = next(it, _DONE)
                if part is _DONE:
                    break
                if ctx._peer_shuffles:
                    # a root output backed by peer-hosted shuffle pieces
                    # must not outlive them: force it local BEFORE the
                    # finally-block's finish_query drops the shuffles
                    from .dist.peerplane import ensure_local

                    with obs_log.query_context(query_id):
                        ensure_local(part)
                # exact root output count for the QueryRecord (the op-name
                # rollup can't distinguish a root op from same-class
                # upstream ops); metadata-only, never forces a load
                n = part.num_rows_or_none()
                if n:
                    rows_out += n
                    progress.add_rows(n)
                    if not saw_first_rows:
                        # time-to-first-row: how long the first non-empty
                        # partition took to surface (the streaming
                        # executor's first-row latency metric; rendered by
                        # the explain_analyze "streaming:" line and the
                        # bench ttfr rung)
                        saw_first_rows = True
                        ctx.stats.bump("time_to_first_row_ns",
                                       time.perf_counter_ns() - t0)
                yield part
        except GeneratorExit:
            # consumer closed the stream early (limit/abandoned iterator):
            # not a failure, but the record says the plan never finished
            outcome = "abandoned"
            raise
        except BaseException as e:
            outcome, error = _classify_outcome(e), e
            raise
        finally:
            # teardown (and the record/capture hooks it runs) still logs
            # under this query's id. The progress entry unregisters in the
            # inner finally: a teardown step raising must not leak a
            # phantom "running" query into the process registry forever.
            try:
                with obs_log.query_context(query_id):
                    # close the stream tree BEFORE the pool goes away: a
                    # streaming pipeline's producers may be blocked on
                    # their channels, and generator close is what shuts
                    # the channels and unblocks them (GC would get there
                    # eventually; an abandoned/erroring query must not
                    # leave pool workers parked until then)
                    close = getattr(it, "close", None)
                    if close is not None:
                        try:
                            close()
                        except BaseException as e:
                            # a generator's own teardown raising must not
                            # skip pool shutdown or the record-on-every-
                            # completion contract (and must not mask the
                            # query's error)
                            obs_log.get_logger("execution").warning(
                                "stream_close_failed", error=repr(e))
                    # close(it) cannot reach a pipeline suspended below an
                    # op whose raise terminated the chain above it (the
                    # traceback keeps those frames alive — see
                    # register_stream): shut down the stragglers directly.
                    # Only a deliberate early stop (success/abandoned
                    # consumer) counts short-circuits.
                    ctx.close_streams(
                        short_circuit=outcome in ("ok", "abandoned"))
                    ctx.shutdown_pool()
                    ctx.finish_query()
                    prof = ctx.stats.profiler
                    prof.finish()
                    if tracing.active() and prof.armed:
                        # span tree -> chrome events, then rewrite the
                        # armed trace file (buffer kept: the next query
                        # appends to the same consolidated writer)
                        tracing.add_span_events(prof)
                        tracing.flush_query()
                    from .profile.metrics import record_query_metrics

                    wall_ns = time.perf_counter_ns() - t0
                    record_query_metrics(ctx.stats, wall_ns)
                    _record_query(root, ctx, query_id, fingerprint,
                                  plan_ops, wall_ns, outcome, error,
                                  rows_out)
                    tracing.query_finished()
            finally:
                unregister_progress(progress)
                ctx.progress = None

    return rooted()


def _adaptive_device_map(op: PhysicalOp, child: Iterator[MicroPartition],
                         ctx: ExecutionContext,
                         trace: bool) -> Iterator[MicroPartition]:
    """Peek at the first partition: if it accepts the device dispatch, run the
    whole stream through the double-buffered sequential driver (the launched
    resolver is handed over as `_primed`, nothing recomputes); if it declines
    (below device_min_rows, staging failure, ...), thread fan-out would have
    been the better strategy after all — delegate the stream, first partition
    included, to the worker pool.

    The accepted branch wraps in _traced like every other sequential stream
    (per-partition stats, chrome-trace events, cancellation checks); the
    declined branch's _parallel_map instruments inside its workers."""
    import itertools

    it = iter(child)
    first = next(it, None)
    if first is None:
        yield from op.execute([iter(())], ctx)
        return
    dispatch = op.map_partition_dispatch(first, ctx)
    if dispatch is None:
        yield from _parallel_map(op, itertools.chain([first], it), ctx)
        return
    stream = op._map_execute([it], ctx, _primed=dispatch)
    if trace:
        stream = _traced(op, stream, ctx)
    yield from stream


def _parallel_map(op: PhysicalOp, child: Iterator[MicroPartition],
                  ctx: ExecutionContext) -> Iterator[MicroPartition]:
    """Morsel-parallel per-partition map with bounded in-flight window and
    order-preserving output (reference: worker-per-core IntermediateOps with
    round-robin morsel dispatch, intermediate_op.rs:71).

    Stats are recorded around the worker-side call, so explain_analyze sees
    real work time, not the consumer's blocked waits. The worker-side op
    SPAN (queue-wait phase included) is opened by scheduler.dispatch, which
    also carries the dispatching thread's span context across the hop —
    run_one only annotates it with the row count."""
    from . import tracing
    from .scheduler import PartitionTask, dispatch, run_map_task

    name = op.name()
    req = op_resource_request(op)

    def run_one(part, seq=0):
        out, rows_hint, dt = run_map_task(op, part, ctx, name, seq)
        if rows_hint is not None:
            rows = rows_hint
        else:
            n = out.num_rows_or_none()
            rows = n if n is not None else 0
        ctx.stats.record_op(name, rows, dt, _part_bytes(out))
        prof = ctx.stats.profiler
        if prof.armed:
            sp = prof.current()
            if sp is not None:
                sp.set_attr("rows", rows)
        return out

    saw_any = False

    def tasks():
        nonlocal saw_any
        for i, part in enumerate(child):
            saw_any = True
            yield PartitionTask(part, lambda p, _i=i: run_one(p, _i),
                                req, name, i)

    for out in dispatch(tasks(), ctx):
        n = out.num_rows_or_none()
        tracing.report_progress(name, n if n is not None else 0)
        yield out
    if not saw_any:
        yield from op.map_empty(ctx)
    progress = getattr(ctx, "progress", None)
    if progress is not None:
        progress.op_done(name)


def _part_bytes(part: MicroPartition) -> int:
    """Output bytes for throughput accounting — loaded partitions only, so
    instrumentation never triggers IO or forces a deferred op."""
    if not part.is_loaded():
        return 0
    b = part.size_bytes()
    return b if b is not None else 0


_tl = threading.local()


def _traced(op: PhysicalOp, stream: Iterator[MicroPartition],
            ctx: ExecutionContext) -> Iterator[MicroPartition]:
    from . import tracing

    name = op.name()
    stats = ctx.stats
    seq = 0
    while True:
        if stats.is_cancelled():
            raise QueryCancelledError(f"query cancelled (at {name})")
        ctx.check_deadline()
        # Self-time accounting: pulling next(stream) recursively runs the
        # child wrappers on this same thread, so each wrapper pushes a frame,
        # accumulates its INCLUSIVE time into the parent frame, and reports
        # inclusive - children as its own wall time. explain_analyze then
        # ranks operators by where time is actually spent, not by depth.
        # The profiler span covers the same interval (kind "op"): its export
        # self-time subtracts the same same-thread child op spans, so the
        # QueryProfile reconciles with RuntimeStats by construction.
        stack = getattr(_tl, "stack", None)
        if stack is None:
            stack = _tl.stack = []
        stack.append(0)
        prof = stats.profiler
        sp = prof.begin(name, op=name, part=seq) if prof.armed else None
        t0 = time.perf_counter_ns()
        pulled = False
        try:
            part = next(stream)
            pulled = True
        except StopIteration:
            progress = getattr(ctx, "progress", None)
            if progress is not None:
                progress.op_done(name)
            return
        finally:
            dt = time.perf_counter_ns() - t0
            child_ns = stack.pop()
            if stack:
                stack[-1] += dt
            if sp is not None:
                # the final StopIteration pull is not a partition: close
                # its span unrecorded so per-op partition counts stay exact
                (prof.end if pulled else prof.cancel)(sp)
        n = part.num_rows_or_none()
        rows = n if n is not None else 0
        stats.record_op(name, rows, max(dt - child_ns, 0),
                        _part_bytes(part))
        if sp is not None:
            sp.set_attr("rows", rows)
        seq += 1
        tracing.report_progress(name, rows)
        yield part
