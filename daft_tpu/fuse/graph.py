"""Column-level dataflow DAG construction for expression-pipeline fusion.

Role-equivalent to the reference's physical-plan pipeline builder
(src/daft-local-execution/src/pipeline.rs:141-211), which replaces per-op
interpretation with one fused streaming pipeline per map chain. Here the
chain's Project/Filter expressions are inlined through each other into a
single DAG over the INPUT columns:

- `Column` references resolve through upstream projections (alias-preserving
  substitution via `ExprNode.with_children`), so a chain of N ops becomes
  one set of root expressions;
- hash-consing CSE (structural `_key()` interning) makes shared subtrees a
  single DAG node, so each distinct subexpression is evaluated exactly once
  per partition;
- filters become mask nodes that split the DAG into *segments*: everything
  in segment j evaluates on the rows surviving masks 1..j-1, preserving
  filter-then-project row semantics exactly;
- conservative fusion barriers: `PyUdf` nodes are *pinned* — evaluated once,
  at the row set of their original chain position, never duplicated or
  reordered across a filter (stateful/batched UDFs keep their observable
  call pattern); aggregations and UDFs with resource requests decline
  fusion entirely;
- *carries* materialize subtrees shared across segments (e.g. a predicate
  pushdown duplicated an expensive projection into the filter below it) as
  scratch columns at their FIRST use's row set, so later segments reuse the
  filtered column instead of recomputing — never evaluated earlier than the
  unfused chain would have;
- consecutive masks separated only by *total* expressions (ones that cannot
  raise on a filtered-out row) conjoin into one mask, saving a compaction.

The result (`FusedGraph`) is schedule + DAG; `fuse/compile.py` turns it into
an executable `FusedProgram`.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..errors import DaftError
from ..expressions import (
    Alias,
    Between,
    BinaryOp,
    Column,
    Expression,
    ExprNode,
    FillNull,
    IfElse,
    IsIn,
    IsNull,
    Literal,
    Not,
    PyUdf,
)
from ..schema import Field, Schema

# reserved scratch-column prefixes (declined if the input schema collides)
PIN_PREFIX = "__fuse_pin_"
CSE_PREFIX = "__fuse_cse_"
MASK_PREFIX = "__fuse_mask_"


class FuseDecline(DaftError):
    """Fusion is not applicable/safe for this chain; callers fall back to
    the unfused op chain (never a query failure)."""


class Segment:
    """One row-set epoch of the fused program: scratch-column evaluations
    (`lets`: pinned UDFs + cross-segment carries), then an optional mask
    that compacts the working set before the next segment."""

    __slots__ = ("lets", "mask")

    def __init__(self):
        self.lets: List[Tuple[str, ExprNode]] = []
        self.mask: Optional[ExprNode] = None


class FusedGraph:
    """The compiled dataflow of one Project/Filter chain (see module doc)."""

    __slots__ = ("input_schema", "segments", "outputs", "device_masks",
                 "device_outputs", "n_ops", "n_project_ops", "n_filter_ops",
                 "cse_hits", "carries", "has_pins", "source_exprs")

    def __init__(self, input_schema: Schema):
        self.input_schema = input_schema
        self.segments: List[Segment] = [Segment()]
        self.outputs: List[Tuple[str, ExprNode]] = []
        # pre-carry roots: the device path hands the WHOLE DAG to XLA as one
        # jit program (XLA does its own CSE), so carries are host-only
        self.device_masks: List[ExprNode] = []
        self.device_outputs: List[Tuple[str, ExprNode]] = []
        self.n_ops = 0
        self.n_project_ops = 0
        self.n_filter_ops = 0
        self.cse_hits = 0
        self.carries = 0
        self.has_pins = False
        self.source_exprs: List[Expression] = []


# binary ops that cannot raise on data (comparisons yield bool; kleene
# logic over bools); arithmetic is handled separately (int kernels are
# checked and can raise on overflow/div-by-zero)
_TOTAL_BINOPS = {"==", "!=", "<", "<=", ">", ">=", "<=>", "&", "|", "^"}
_TOTAL_ARITH = {"+", "-", "*"}


class _Builder:
    def __init__(self, input_schema: Schema):
        self.graph = FusedGraph(input_schema)
        self._canon: Dict[tuple, ExprNode] = {}
        self._canon_ids: Set[int] = set()
        self._keep: List[ExprNode] = []  # canonical nodes stay alive: id()s
        # in _canon_ids / pin / memo maps must never be reused by GC
        self._has_udf_memo: Dict[int, bool] = {}
        self._pin_map: Dict[int, str] = {}  # id(udf node) -> pin column
        self._pin_seg: Dict[str, int] = {}  # pin column -> segment index
        self._subst_memo: Dict[int, ExprNode] = {}
        self._total_memo: Dict[int, bool] = {}
        self._inline_seen: Set[int] = set()

    # ----------------------------------------------------------- consing
    def cons(self, node: ExprNode) -> ExprNode:
        """Intern `node` (children first). UDF-bearing subtrees are interned
        by identity only — two *distinct* UDF call sites must never merge
        (their side-effect counts are observable); the same site reached
        twice through inlining shares one node and evaluates once."""
        if id(node) in self._canon_ids:
            return node
        kids = node.children()
        if kids:
            new = [self.cons(c) for c in kids]
            if any(a is not b for a, b in zip(new, kids)):
                node = node.with_children(new)
                if id(node) in self._canon_ids:
                    return node
        if self._contains_udf(node):
            self._register(node)
            return node
        try:
            key = node._key()
            hash(key)
        except TypeError:
            self._register(node)
            return node
        hit = self._canon.get(key)
        if hit is not None:
            if hit is not node and kids:
                self.graph.cse_hits += 1
            # the discarded duplicate's id is already in _has_udf_memo:
            # keep it alive for the build's lifetime so a recycled address
            # can never inherit its stale UDF-containment verdict
            self._keep.append(node)
            return hit
        self._canon[key] = node
        self._register(node)
        return node

    def _register(self, node: ExprNode) -> None:
        self._canon_ids.add(id(node))
        self._keep.append(node)

    def _contains_udf(self, node: ExprNode) -> bool:
        hit = self._has_udf_memo.get(id(node))
        if hit is None:
            hit = isinstance(node, PyUdf) or any(
                self._contains_udf(c) for c in node.children())
            self._has_udf_memo[id(node)] = hit
        return hit

    # ---------------------------------------------------------- inlining
    def inline(self, node: ExprNode, scope: Dict[str, ExprNode]) -> ExprNode:
        """Resolve Column references through the visible projection scope,
        alias-wrapping when the defining node's name differs so downstream
        name-sensitive typing (e.g. `BinaryOp.name()`) is unchanged."""
        if isinstance(node, Column):
            d = scope.get(node.cname)
            if d is None:
                raise FuseDecline(f"unresolvable column {node.cname!r}")
            if d.children():
                # every reference past the first to a COMPUTED def is a
                # subexpression a naive inliner would have re-evaluated;
                # the shared DAG node evaluates it once
                if id(d) in self._inline_seen:
                    self.graph.cse_hits += 1
                else:
                    self._inline_seen.add(id(d))
            if _node_name(d) != node.cname:
                d = self.cons(Alias(d, node.cname))
            return d
        kids = node.children()
        if not kids:
            return self.cons(node)
        return self.cons(node.with_children(
            [self.inline(c, scope) for c in kids]))

    # ------------------------------------------------------------ pinning
    def pin_udfs(self, node: ExprNode) -> None:
        """Register every not-yet-pinned PyUdf in `node` as a scratch-column
        evaluation of the CURRENT segment (post-order: nested UDFs pin
        before their consumers). The pinned call runs exactly once, at the
        row set of its original chain position."""
        for udf in _udf_nodes_postorder(node):
            if id(udf) in self._pin_map:
                continue
            if udf.resource_request:
                # fusing would SUM the chain's admission requests into one
                # task where the unfused chain admitted them one op at a
                # time — an impossible combined request must not fail a
                # query that used to run
                raise FuseDecline("UDF carries a resource request")
            name = f"{PIN_PREFIX}{len(self._pin_map)}"
            self._pin_map[id(udf)] = name
            stored = udf.with_children(
                [self.subst_pins(c) for c in udf.children()])
            seg = len(self.graph.segments) - 1
            self.graph.segments[-1].lets.append((name, stored))
            self._pin_seg[name] = seg
            self.graph.has_pins = True

    def subst_pins(self, node: ExprNode) -> ExprNode:
        """Pin-free view of `node`: pinned UDF calls become references to
        their scratch column (consed, so structural sharing survives)."""
        pin = self._pin_map.get(id(node))
        if pin is not None:
            return self.cons(Column(pin))
        cached = self._subst_memo.get(id(node))
        if cached is not None:
            return cached
        kids = node.children()
        if kids:
            new = [self.subst_pins(c) for c in kids]
            out = node if all(a is b for a, b in zip(new, kids)) \
                else self.cons(node.with_children(new))
        else:
            out = node
        self._subst_memo[id(node)] = out
        return out

    # ----------------------------------------------------------- totality
    def is_total(self, node: ExprNode, schema: Schema) -> bool:
        """True when evaluating `node` on a superset of its unfused row set
        cannot raise or observably differ (pure, elementwise, non-raising).
        Gates mask conjoining only; unproven nodes simply keep their
        compaction point — never a correctness risk."""
        hit = self._total_memo.get(id(node))
        if hit is not None:
            return hit
        out = self._is_total(node, schema)
        self._total_memo[id(node)] = out
        return out

    def _is_total(self, node: ExprNode, schema: Schema) -> bool:
        kids_total = all(self.is_total(c, schema) for c in node.children())
        if not kids_total:
            return False
        if isinstance(node, (Column, Literal, Alias, Not, IsNull, IsIn,
                             Between, FillNull, IfElse)):
            return True
        if isinstance(node, BinaryOp):
            if node.op in _TOTAL_BINOPS:
                return True
            if node.op in _TOTAL_ARITH:
                try:
                    return node.to_field(schema).dtype.is_floating()
                except Exception:
                    return False
        return False


def _node_name(node: ExprNode) -> Optional[str]:
    try:
        return node.name()
    except Exception:
        return None


def _udf_nodes_postorder(node: ExprNode, seen: Optional[Set[int]] = None
                         ) -> List[PyUdf]:
    if seen is None:
        seen = set()
    out: List[PyUdf] = []
    if id(node) in seen:
        return out
    seen.add(id(node))
    for c in node.children():
        out.extend(_udf_nodes_postorder(c, seen))
    if isinstance(node, PyUdf):
        out.append(node)
    return out


def _contains_agg(node: ExprNode) -> bool:
    return node.is_aggregation()


def build_fused_graph(stages: List[Tuple[str, object]],
                      input_schema: Schema) -> FusedGraph:
    """Build the fused DAG for a chain of map-class stages.

    `stages` is the chain in EXECUTION order (bottom-up):
    ``("project", [Expression, ...])`` or ``("filter", Expression)``.
    Raises FuseDecline when fusion would be unsafe; callers keep the
    unfused chain.
    """
    for name in input_schema.field_names():
        if name.startswith((PIN_PREFIX, CSE_PREFIX, MASK_PREFIX)):
            raise FuseDecline(f"input column {name!r} collides with fusion "
                              "scratch names")
    b = _Builder(input_schema)
    g = b.graph
    scope: Dict[str, ExprNode] = {
        n: b.cons(Column(n)) for n in input_schema.field_names()}
    for kind, payload in stages:
        g.n_ops += 1
        if kind == "project":
            g.n_project_ops += 1
            new_scope: Dict[str, ExprNode] = {}
            for e in payload:
                g.source_exprs.append(e)
                if _contains_agg(e._node):
                    raise FuseDecline("aggregation inside a map chain")
                node = b.inline(e._node, scope)
                b.pin_udfs(node)
                new_scope[e.name()] = b.subst_pins(node)
            scope = new_scope
        elif kind == "filter":
            g.n_filter_ops += 1
            g.source_exprs.append(payload)
            if _contains_agg(payload._node):
                raise FuseDecline("aggregation inside a filter predicate")
            node = b.inline(payload._node, scope)
            b.pin_udfs(node)
            mask = b.subst_pins(node)
            cur = g.segments[-1]
            prev = g.segments[-2] if len(g.segments) > 1 else None
            if (not cur.lets and cur.mask is None and prev is not None
                    and prev.mask is not None
                    and b.is_total(mask, input_schema)):
                # conjoin: a total mask cannot raise on the rows the
                # previous mask would have dropped, and kleene `&` drops
                # exactly the same survivors as sequential filtering
                prev.mask = b.cons(BinaryOp("&", prev.mask, mask))
                continue
            cur.mask = mask
            g.segments.append(Segment())
        else:  # pragma: no cover - planner bug
            raise FuseDecline(f"unknown stage kind {kind!r}")
    g.outputs = [(name, node) for name, node in scope.items()]
    g.device_masks = [s.mask for s in g.segments if s.mask is not None]
    g.device_outputs = list(g.outputs)
    _plant_carries(b)
    return g


def _plant_carries(b: _Builder) -> None:
    """Cross-segment CSE: subtrees used in 2+ row-set epochs materialize as
    scratch columns at their FIRST use's segment (same row set the unfused
    chain first evaluated them on) and are reused — filtered, never
    recomputed — downstream. This is where pushdown-duplicated expressions
    (the predicate below a projection that also outputs the value) collapse
    back to one evaluation per partition."""
    g = b.graph
    nsegs = len(g.segments)
    # roots per segment: let bodies + mask; outputs belong to the trailing
    # (maskless) segment
    roots: List[Tuple[int, ExprNode]] = []
    for si, seg in enumerate(g.segments):
        for _name, body in seg.lets:
            roots.append((si, body))
        if seg.mask is not None:
            roots.append((si, seg.mask))
    for _name, node in g.outputs:
        roots.append((nsegs - 1, node))

    usage: Dict[int, Set[int]] = {}
    nodes_by_id: Dict[int, ExprNode] = {}

    def visit(node: ExprNode, si: int, seen: Set[int]) -> None:
        if id(node) in seen:
            return
        seen.add(id(node))
        usage.setdefault(id(node), set()).add(si)
        nodes_by_id[id(node)] = node
        for c in node.children():
            visit(c, si, seen)

    for si, root in roots:
        visit(root, si, set())

    def subtree_size(node: ExprNode) -> int:
        return 1 + sum(subtree_size(c) for c in node.children())

    cands = []
    for order, (nid, segs) in enumerate(usage.items()):
        node = nodes_by_id[nid]
        if len(segs) < 2 or not node.children():
            continue
        if isinstance(node, Alias):
            continue  # its child spans the same segments; carry that
        if b._contains_udf(node):
            continue  # pinned columns already carry the UDF result
        cands.append((min(segs), subtree_size(node), order, node))
    if not cands:
        return
    # inner shared subtrees evaluate before the nodes that embed them
    cands.sort(key=lambda t: (t[0], t[1], t[2]))
    carry_map: Dict[int, str] = {}

    def subst_carries(node: ExprNode, exclude: Optional[int] = None
                      ) -> ExprNode:
        cname = carry_map.get(id(node))
        if cname is not None and id(node) != exclude:
            return Column(cname)
        kids = node.children()
        if not kids:
            return node
        new = [subst_carries(c) for c in kids]
        if all(a is b_ for a, b_ in zip(new, kids)):
            return node
        return node.with_children(new)

    for first_seg, _size, _order, node in cands:
        cname = f"{CSE_PREFIX}{len(carry_map)}"
        body = subst_carries(node, exclude=id(node))
        carry_map[id(node)] = cname
        g.segments[first_seg].lets.append((cname, body))
        g.carries += 1
    # rewrite every root against the carry columns (let bodies were
    # rewritten incrementally above; masks/outputs/pin bodies here)
    for seg in g.segments:
        seg.lets = [(n, subst_carries(body, exclude=id(body))
                     if n.startswith(CSE_PREFIX) else subst_carries(body))
                    for n, body in seg.lets]
        if seg.mask is not None:
            seg.mask = subst_carries(seg.mask)
        _toposort_lets(seg)
    g.outputs = [(n, subst_carries(node)) for n, node in g.outputs]


def _let_refs(body: ExprNode, names: Set[str]) -> Set[str]:
    out: Set[str] = set()

    def walk(n: ExprNode) -> None:
        if isinstance(n, Column) and n.cname in names:
            out.add(n.cname)
        for c in n.children():
            walk(c)

    walk(body)
    return out


def _toposort_lets(seg: Segment) -> None:
    """Order a segment's scratch evaluations so every referenced scratch
    column is defined first (carries may feed pinned UDF args and vice
    versa). Stable for independent lets; cycles are impossible (the DAG is
    acyclic by construction)."""
    if len(seg.lets) < 2:
        return
    names = {n for n, _ in seg.lets}
    deps = {n: _let_refs(body, names) - {n} for n, body in seg.lets}
    emitted: Set[str] = set()
    pending = list(seg.lets)
    out: List[Tuple[str, ExprNode]] = []
    while pending:
        progressed = False
        rest = []
        for item in pending:
            if deps[item[0]] <= emitted:
                out.append(item)
                emitted.add(item[0])
                progressed = True
            else:
                rest.append(item)
        if not progressed:  # pragma: no cover - DAG invariant violated
            raise FuseDecline("cyclic scratch-column dependencies")
        pending = rest
    seg.lets = out
