"""Fused-program emission + the `FusedMapOp` physical operator.

`compile_chain` turns a Project/Filter op chain into a `FusedProgram`:

- **host path**: one pass per partition — per segment, scratch columns
  (pinned UDFs + cross-segment CSE carries) append to the working set, the
  segment mask compacts it, and the final projection evaluates every output
  in ONE `eval_expression_list` (the table-level structural memo makes the
  hash-consed shared subtrees evaluate exactly once). No intermediate
  partition is ever materialized.
- **device path**: the WHOLE DAG — every mask and every output — goes
  through `kernels/device.normalize_and_check` and runs as ONE jit program
  behind the existing device breaker; the host then ANDs the mask columns
  and compacts once. N staged dispatches and N intermediate
  materializations become one XLA-fused kernel over the resident buffer.

The planner pass `fuse_map_chains` (called from `physical.translate` behind
``cfg.expr_fusion``) replaces each maximal chain with a `FusedMapOp`. Any
compile-time failure — including an armed ``fuse.compile`` fault — falls
back to the unfused op chain, never a query failure. The hard invariant is
that results are byte-identical with fusion on or off.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

from .. import faults
from ..expressions import Alias, Expression, col, required_columns
from ..physical import PhysicalOp, summarize_exprs
from ..schema import Field, Schema
from .graph import (
    MASK_PREFIX,
    FusedGraph,
    FuseDecline,
    build_fused_graph,
)


class FusedProgram:
    """Executable form of a fused map chain (host + optional device plan)."""

    def __init__(self, graph: FusedGraph, out_schema: Schema):
        self.graph = graph
        self.out_schema = out_schema
        self.n_masks = len(graph.device_masks)
        self.has_masks = self.n_masks > 0
        # count-preserving chains (no filter) keep exact scan row counts
        # through multi-host deferral
        self.count_preserving = not self.has_masks

        aug_fields = list(graph.input_schema)
        host_segments: List[Tuple[List[Expression], Optional[Expression]]] = []
        for seg in graph.segments:
            lets: List[Expression] = []
            for name, body in seg.lets:
                dt = body.to_field(Schema(aug_fields)).dtype
                aug_fields.append(Field(name, dt))
                lets.append(Expression(Alias(body, name)))
            mask_expr = None
            if seg.mask is not None:
                mdt = seg.mask.to_field(Schema(aug_fields)).dtype
                if not (mdt.is_boolean() or mdt.is_null()):
                    raise FuseDecline(f"mask resolves to {mdt}, not bool")
                mask_expr = Expression(seg.mask)
            host_segments.append((lets, mask_expr))
        self._host_segments = host_segments

        aug = Schema(aug_fields)
        out_names = [n for n, _ in graph.outputs]
        if out_names != out_schema.field_names():
            raise FuseDecline("fused outputs do not match the chain schema")
        self.output_exprs: List[Expression] = []
        for (name, node), field in zip(graph.outputs, out_schema):
            dt = node.to_field(aug).dtype
            if dt != field.dtype:
                # inlining changed type resolution (e.g. a weak literal
                # adopting a different operand dtype across a stage
                # boundary): byte-identity cannot be guaranteed — decline
                raise FuseDecline(
                    f"output {name!r} resolves to {dt} fused vs "
                    f"{field.dtype} unfused")
            self.output_exprs.append(Expression(Alias(node, name)))

        # input columns the fused pass actually reads (dead-column
        # elimination: everything else never leaves the source partition)
        req = set()
        input_names = set(graph.input_schema.field_names())
        for _lets, _mask in host_segments:
            for e in _lets:
                req.update(required_columns(e))
            if _mask is not None:
                req.update(required_columns(_mask))
        for e in self.output_exprs:
            req.update(required_columns(e))
        self.required_input_columns = req & input_names

        # one-program device plan: masks first, then outputs. Pinned UDFs
        # never compile for the device, so pin-bearing programs stay
        # host-only; carries are host-only too (XLA CSEs the shared DAG
        # itself), so the device sees the pre-carry roots.
        if graph.has_pins:
            self.device_exprs = None
        else:
            self.device_exprs = (
                [Expression(Alias(m, f"{MASK_PREFIX}{i}"))
                 for i, m in enumerate(graph.device_masks)]
                + [Expression(Alias(node, name))
                   for name, node in graph.device_outputs])

    # ------------------------------------------------------------- host
    def run_host(self, table):
        """Single-pass host evaluation: segments of scratch-eval + mask
        compaction over a pruned working set, then one fused projection."""
        cols = table.column_names
        needed = [c for c in cols if c in self.required_input_columns]
        if not needed and cols:
            needed = cols[:1]  # literal-only outputs still broadcast to n
        work = table if needed == cols else table.select_columns(needed)
        for lets, mask_expr in self._host_segments:
            for let_e in lets:
                work = work.eval_expression_list(
                    [col(c) for c in work.column_names] + [let_e])
            if mask_expr is not None:
                work = work.filter([mask_expr])
        return work.eval_expression_list(self.output_exprs)

    # ----------------------------------------------------------- device
    def assemble_device(self, result_table):
        """Device program result -> output table: AND the mask columns
        (kleene, same null semantics as sequential filters) and compact the
        output columns once."""
        if not self.n_masks:
            return result_table
        mask_cols = result_table._columns[:self.n_masks]
        mask = mask_cols[0]
        for m in mask_cols[1:]:
            mask = mask & m
        out_names = result_table.column_names[self.n_masks:]
        return result_table.select_columns(out_names).filter_with_mask(mask)


def compile_chain(stages, input_schema: Schema,
                  out_schema: Schema) -> FusedProgram:
    """stages (bottom-up ``("project", exprs) | ("filter", pred)``) ->
    FusedProgram. Raises FuseDecline when fusion is unsafe."""
    graph = build_fused_graph(stages, input_schema)
    return FusedProgram(graph, out_schema)


class FusedMapOp(PhysicalOp):
    """A maximal Project/Filter chain collapsed to one single-pass operator.

    Executes through ExecutionContext.eval_fused (device one-program path
    when eligible, segmented host pass otherwise) with the same pipelined
    dispatch contract as ProjectOp/FilterOp. Byte-identical to the chain it
    replaced; `fused_chains` / `fused_ops_eliminated` / `cse_hits` counters
    make the collapse visible in every plan dump."""

    # the fused program is a composition of row-local projections and
    # filters, so the chain streams morsel-wise exactly like its
    # constituent ops would (pin-bearing programs are declined by the
    # driver's UDF gate via _map_exprs)
    morsel_streamable = True

    def __init__(self, child: PhysicalOp, program: FusedProgram,
                 schema: Schema):
        super().__init__([child], schema, child.num_partitions)
        self.program = program
        self._recorded = False
        self._record_lock = threading.Lock()

    def __getstate__(self):
        # the record lock is per-process coordination state, not program
        # identity: drop it so a fused op can ship over the dist/ worker
        # transport (the receiving process records against ITS stats)
        state = dict(self.__dict__)
        state.pop("_record_lock", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._record_lock = threading.Lock()

    def _record(self, ctx) -> None:
        """Chain-level counters, once per query (the op tree is rebuilt per
        translate, so instance state is query-scoped)."""
        if self._recorded:
            return
        with self._record_lock:
            if self._recorded:
                return
            self._recorded = True
        g = self.program.graph
        ctx.stats.bump("fused_chains")
        ctx.stats.bump("fused_ops_eliminated", g.n_ops - 1)
        if g.cse_hits:
            ctx.stats.bump("cse_hits", g.cse_hits)
        if ctx.stats.profiler.armed:
            # compile outcome as a typed profile event: what fused, how much
            # it collapsed, and whether a one-program device plan exists
            ctx.stats.profiler.event(
                "fusion", ops=g.n_ops, cse_hits=g.cse_hits,
                device_program=self.program.device_exprs is not None)

    def map_partition(self, part, ctx):
        self._record(ctx)
        return ctx.eval_fused(part, self.program)

    def map_partition_dispatch(self, part, ctx):
        self._record(ctx)
        return ctx.eval_fused_dispatch(part, self.program)

    def map_partition_declined(self, part, ctx):
        # dispatch already proved this partition device-ineligible
        return ctx._eval_fused_host(part, self.program)

    def device_pipelinable(self, ctx) -> bool:
        if not ctx.cfg.use_device_kernels:
            return False
        if self.program.device_exprs is None:
            return False
        try:
            from ..kernels.device import normalize_and_check

            return normalize_and_check(self.program.device_exprs,
                                       self.children[0].schema) is not None
        except Exception:
            return False

    def _map_exprs(self):
        # the ORIGINAL chain expressions: UDF parallel-safety and resource
        # accounting see exactly what the unfused chain declared
        return self.program.graph.source_exprs

    def execute(self, inputs, ctx):
        self._record(ctx)
        return self._map_execute(inputs, ctx)

    def describe(self) -> str:
        g = self.program.graph
        n_exprs = self.n_exprs
        body = summarize_exprs(self.program.output_exprs)
        # masks (and scratch lets) are part of the chain's identity: the
        # plan fingerprint hashes this display, so `where x > 5` and
        # `where x > 9` must not collide just because fusion folded the
        # filter out of the op list
        segs = []
        for lets, mask in self.program._host_segments:
            if lets:
                segs.append("let " + summarize_exprs(lets))
            if mask is not None:
                segs.append("where " + summarize_exprs([mask]))
        tail = (" | " + " | ".join(segs)) if segs else ""
        return (f"FusedMap[{g.n_ops} ops, {n_exprs} exprs, "
                f"{g.cse_hits} cse]: {body}{tail}")

    @property
    def n_exprs(self) -> int:
        return (len(self.program.output_exprs) + self.program.n_masks
                + sum(len(lets) for lets, _ in self.program._host_segments))


def fuse_map_chains(op: PhysicalOp, cfg) -> PhysicalOp:
    """Planner pass: collapse every maximal chain of >= 2 map-class ops
    (ProjectOp/FilterOp) into one FusedMapOp. Runs inside
    physical.translate() AFTER fuse_for_device, so a filter feeding an
    aggregation has already folded into FusedFilterAggregateOp and only the
    residual map chain fuses here (the two passes compose). Chains that
    decline — UDF resource requests, aggregations, type-resolution drift,
    an armed ``fuse.compile`` fault — stay as the unfused op chain."""
    from ..physical import FilterOp, ProjectOp

    if isinstance(op, (ProjectOp, FilterOp)):
        chain = [op]
        cur = op
        while isinstance(cur.children[0], (ProjectOp, FilterOp)):
            cur = cur.children[0]
            chain.append(cur)
        base = fuse_map_chains(cur.children[0], cfg)
        cur.children[0] = base
        if len(chain) >= 2:
            fused = _try_fuse_chain(chain, base)
            if fused is not None:
                return fused
        return op
    for i, c in enumerate(op.children):
        op.children[i] = fuse_map_chains(c, cfg)
    return op


def _try_fuse_chain(chain: List[PhysicalOp],
                    base: PhysicalOp) -> Optional[FusedMapOp]:
    """Compile one top-down chain, or None to keep it unfused. EVERY
    failure mode lands here — a fusion-compiler defect degrades to the
    pre-fusion plan instead of failing the query (proven by the armed
    ``fuse.compile`` fault-site test)."""
    from ..physical import ProjectOp

    try:
        faults.check("fuse.compile")
        stages = []
        for op in reversed(chain):
            if isinstance(op, ProjectOp):
                stages.append(("project", list(op.exprs)))
            else:
                stages.append(("filter", op.predicate))
        program = compile_chain(stages, base.schema, chain[0].schema)
    except Exception:
        return None
    return FusedMapOp(base, program, chain[0].schema)
