"""Expression-pipeline fusion: collapse Project/Filter chains into
single-pass FusedMap programs (README "Expression fusion").

- `graph.py`  — column-level dataflow DAG: inlining through upstream
  projections, hash-consing CSE, dead-column elimination, UDF pinning,
  cross-segment carries, mask conjoining.
- `compile.py` — FusedProgram (host segmented pass / one-jit device
  program), the FusedMapOp physical operator, and the `fuse_map_chains`
  planner pass wired into `physical.translate` behind ``cfg.expr_fusion``.
- `segment.py` — the plan-segment compiler (README "Device residency"):
  collapses whole project→filter→agg segments into HBM-resident
  DeviceSegmentOps behind ``cfg.device_residency``.
"""

from .compile import FusedMapOp, FusedProgram, compile_chain, fuse_map_chains
from .graph import FusedGraph, FuseDecline, build_fused_graph
from .segment import (DeviceSegmentOp, SegmentProgram, compile_plan_segments,
                      run_segment_async)

__all__ = [
    "DeviceSegmentOp",
    "FusedGraph",
    "FusedMapOp",
    "FusedProgram",
    "FuseDecline",
    "SegmentProgram",
    "build_fused_graph",
    "compile_chain",
    "compile_plan_segments",
    "fuse_map_chains",
    "run_segment_async",
]
