"""Plan-segment compiler: whole project→filter→agg segments stay HBM-resident.

``compile_plan_segments`` (wired into ``physical.translate`` after
``fuse_for_device``/``fuse_map_chains``, behind ``cfg.device_residency``)
finds maximal device-eligible segments — an Aggregate (plain or
filter-fused) whose child is a fused map chain (or a single Project/Filter)
— and collapses each into one ``DeviceSegmentOp``. At runtime the segment
executes as a resident pipeline (``run_segment_async``):

- ONE host→device stage at segment entry (the map program's input columns,
  reused from the partition's HBM residency cache);
- the map program's outputs — every mask lane and every intermediate
  column the aggregation reads — stay on device as DeviceArrays and feed
  the fused aggregation program directly (``env2``), with the mask
  conjunction acting as the aggregation predicate;
- ONE device→host gather at segment exit (the aggregated partials).

Zero Arrow materialization happens between the map and the aggregation:
the ``FusedMapOp → Aggregate`` handoff that previously round-tripped
Arrow↔DeviceArray is elided (counted as ``device_handoffs_elided``).

Sharding/donation contract: consecutive programs run on the same default
device with identical size buckets, so the map outputs are consumed by the
aggregation with no resharding; when every intermediate is provably fresh
(no bare column passthrough that could alias the partition's residency
cache) and the backend is not CPU, the intermediate env is donated
(``donate_argnums``) so XLA reuses its HBM for the reduction outputs.

Invariants (tests/test_segment.py): results are byte-identical with
``cfg.device_residency`` off; ANY segment-compile or resident-run failure
— including an armed ``fuse.segment`` fault — degrades to the staged
per-op path, never a query failure; the whole leg sits behind the existing
DeviceHealth breaker; warm plan-cache runs perform zero segment compiles
(the pass runs inside ``translate``, which a warm hit skips entirely).
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

from .. import faults
from ..datatypes import DataType
from ..expressions import Alias, BinaryOp, Column, Expression
from ..micropartition import MicroPartition
from ..physical import (
    AggregateOp,
    FilterOp,
    FusedFilterAggregateOp,
    PhysicalOp,
    ProjectOp,
)
from ..schema import Field, Schema
from .compile import FusedMapOp, FusedProgram, compile_chain
from .graph import MASK_PREFIX

__all__ = ["DeviceSegmentOp", "SegmentProgram", "compile_plan_segments",
           "run_segment_async", "process_counters"]


# ---------------------------------------------------------------------------
# process-level counters (the dt.health() "device" section mirrors these —
# health snapshots are engine-wide, RuntimeStats is per-query)
# ---------------------------------------------------------------------------

_PROC_LOCK = threading.Lock()
_PROC_COUNTERS = {
    "resident_segments": 0,
    "handoffs_elided": 0,
    "segment_fallbacks": 0,
    "segment_compiles": 0,
    "hbm_resident_bytes_high_water": 0,
}


def _proc_bump(key: str, n: int = 1) -> None:
    with _PROC_LOCK:
        _PROC_COUNTERS[key] += n


def _proc_max(key: str, n: int) -> None:
    with _PROC_LOCK:
        if n > _PROC_COUNTERS[key]:
            _PROC_COUNTERS[key] = n


def process_counters() -> dict:
    """Snapshot of the process-wide residency counters (obs/health.py)."""
    with _PROC_LOCK:
        return dict(_PROC_COUNTERS)


def reset_process_counters() -> None:
    """Test hook: zero the process-wide residency counters."""
    with _PROC_LOCK:
        for k in _PROC_COUNTERS:
            _PROC_COUNTERS[k] = 0


# ---------------------------------------------------------------------------
# compile-time artifact
# ---------------------------------------------------------------------------

def _peel(node):
    while isinstance(node, Alias):
        node = node.child
    return node


class SegmentProgram:
    """Everything the resident runtime needs, planned once at translate:

    - ``seg_exprs``: the pruned device map program (mask aliases + only the
      intermediate columns the aggregation actually reads);
    - ``inter_schema``: the schema those outputs form (mask lanes as bool
      fields, so the aggregation's predicate/children normalize against it);
    - ``specs``/``child_nodes``/``pred_node``/``kinds``/``modes``: the
      planned aggregation (``_plan_agg_specs`` over ``inter_schema``, the
      mask conjunction folded into the predicate);
    - ``gb_inputs``: group keys remapped to the INPUT table's columns —
      group codes compute over the unfiltered input (rows stay aligned with
      the mask lanes; the pruning output restores filtered-first-occurrence
      group order, exactly the staged FusedFilterAggregate semantics);
    - ``donation_safe``: True when every resident intermediate is provably
      fresh (no bare column passthrough whose jitted identity could hand
      back the partition's residency-cache buffer) — the gate for
      ``donate_argnums`` on the aggregation program.

    The per-binding sharding key of a compiled segment is
    (nodes, inter_schema, input_names, kinds, modes, segment bucket,
    x64 mode, donate) — ``_compile_agg``'s cache key — so repeat traffic
    with the same shape and size bucket reuses ONE XLA executable, and the
    plan cache (adapt/plancache.py) serves the whole SegmentProgram warm
    with zero translate/segment-compile calls."""

    __slots__ = ("seg_exprs", "input_schema", "inter_schema", "specs",
                 "child_nodes", "pred_node", "input_names", "kinds", "modes",
                 "gb_inputs", "has_groupby", "n_masks", "donation_safe")

    def __init__(self, seg_exprs, input_schema, inter_schema, specs,
                 child_nodes, pred_node, input_names, kinds, modes,
                 gb_inputs, n_masks):
        self.seg_exprs = seg_exprs
        self.input_schema = input_schema
        self.inter_schema = inter_schema
        self.specs = specs
        self.child_nodes = tuple(child_nodes)
        self.pred_node = pred_node
        self.input_names = tuple(input_names)
        self.kinds = tuple(kinds)
        self.modes = tuple(modes)
        self.gb_inputs = list(gb_inputs)
        self.has_groupby = bool(gb_inputs)
        self.n_masks = n_masks
        self.donation_safe = all(
            not isinstance(_peel(e._node), Column) for e in seg_exprs)


def _map_program_for(child: PhysicalOp) -> Optional[FusedProgram]:
    """The device map program of the segment's map stage: a FusedMapOp
    carries one already; a lone Project/Filter (below the 2-op fusion
    threshold) compiles through the same ``compile_chain`` machinery."""
    if isinstance(child, FusedMapOp):
        return child.program
    base = child.children[0]
    if isinstance(child, ProjectOp):
        stages: List[Tuple] = [("project", list(child.exprs))]
    elif isinstance(child, FilterOp):
        stages = [("filter", child.predicate)]
    else:
        return None
    return compile_chain(stages, base.schema, child.schema)


def _try_compile_segment(op, child, cfg) -> Optional[SegmentProgram]:
    """One segment compile, or None to keep the staged ops. EVERY failure
    mode lands here — including an armed ``fuse.segment`` fault — and
    degrades to the per-op plan, never a query failure."""
    from ..kernels.device import (device_required_columns, epoch_cmps_for,
                                  normalize_and_check)
    from ..kernels.device_agg import _ExprView, _plan_agg_specs

    try:
        faults.check("fuse.segment")
        program = _map_program_for(child)
        if program is None or program.device_exprs is None:
            return None
        input_schema = child.children[0].schema
        if normalize_and_check(program.device_exprs, input_schema) is None:
            return None

        # the intermediate schema the aggregation normalizes against:
        # mask lanes first (bool), then the map chain's output columns
        inter_fields = [Field(f"{MASK_PREFIX}{i}", DataType.bool())
                        for i in range(program.n_masks)]
        inter_fields += [Field(f.name, f.dtype) for f in child.schema]
        inter_schema = Schema(inter_fields)

        # group keys must be bare passthroughs of input columns: codes are
        # computed over the UNFILTERED input table, so the key values must
        # exist there unchanged (computed keys would need the intermediate
        # gathered back to host — exactly the handoff this pass deletes)
        out_nodes = dict(program.graph.device_outputs)
        gb_inputs: List[Expression] = []
        for e in (getattr(op, "groupby", None) or []):
            node = _peel(e._node)
            if not isinstance(node, Column):
                return None
            mapped = out_nodes.get(node.cname)
            if mapped is None:
                return None
            mapped = _peel(mapped)
            if not isinstance(mapped, Column):
                return None
            gb_inputs.append(
                Expression(Alias(Column(mapped.cname), e._node.name())))

        # mask conjunction (+ a fused filter's predicate) becomes the
        # aggregation predicate: masked segment reductions + the pruning
        # output replace the staged path's host compaction
        pred = None
        for i in range(program.n_masks):
            m = Column(f"{MASK_PREFIX}{i}")
            pred = m if pred is None else BinaryOp("&", pred, m)
        if isinstance(op, FusedFilterAggregateOp):
            pnode = op.predicate._node
            pred = pnode if pred is None else BinaryOp("&", pred, pnode)

        planned = _plan_agg_specs(
            list(op.aggregations), inter_schema,
            predicate=_ExprView(pred) if pred is not None else None)
        if planned is None:
            return None
        specs, child_nodes, pred_nodes = planned
        pred_node = pred_nodes[0] if pred_nodes else None

        # residency gates: the aggregation env is built purely from the map
        # program's on-device outputs — no dictionaries, no host-evaluated
        # epoch lanes — so anything needing those declines here
        check_nodes = list(child_nodes) + (
            [pred_node] if pred_node is not None else [])
        if epoch_cmps_for(check_nodes, inter_schema):
            return None
        needed = sorted(device_required_columns(check_nodes, inter_schema))
        if not needed:
            return None  # nothing resident to hand off: no segment to win
        for nm in needed:
            if inter_schema[nm].dtype.is_string():
                return None  # string lanes need the dictionaries host-side
        needed_set = set(needed)
        seg_exprs = [e for e in program.device_exprs
                     if e.name() in needed_set]
        if not seg_exprs:
            return None

        kinds = tuple(s[1] for s in specs)
        modes = tuple(s[3] for s in specs)
        return SegmentProgram(seg_exprs, input_schema, inter_schema, specs,
                              child_nodes, pred_node, tuple(needed), kinds,
                              modes, gb_inputs, program.n_masks)
    except Exception:
        return None


def compile_plan_segments(op: PhysicalOp, cfg, stats=None) -> PhysicalOp:
    """Planner pass (physical.translate, after fuse_for_device +
    fuse_map_chains): collapse each eligible Aggregate-over-map-chain into
    one DeviceSegmentOp. ``segment_compiles`` counts real compiles — a warm
    plan-cache hit skips translate entirely, so warm runs pin at zero."""
    for i, c in enumerate(op.children):
        op.children[i] = compile_plan_segments(c, cfg, stats)
    if isinstance(op, (AggregateOp, FusedFilterAggregateOp)):
        child = op.children[0]
        if isinstance(child, (FusedMapOp, ProjectOp, FilterOp)):
            prog = _try_compile_segment(op, child, cfg)
            if prog is not None:
                if stats is not None:
                    stats.bump("segment_compiles")
                _proc_bump("segment_compiles")
                return DeviceSegmentOp(child, op, prog)
    return op


# ---------------------------------------------------------------------------
# the physical operator
# ---------------------------------------------------------------------------

class DeviceSegmentOp(PhysicalOp):
    """A project→filter→agg plan segment compiled for whole-segment device
    residency. Executes through ``ExecutionContext.eval_segment``: the
    resident pipeline when the partition is device-eligible, the retained
    staged ops (``map_op`` then ``agg_op``) otherwise — byte-identical
    either way. NOT morsel-streamable: the aggregation is a pipeline
    breaker; the morsel stream runs BELOW it (device-morsel mode in
    stream/pipeline.py) and re-chunks at this op's boundary."""

    morsel_streamable = False

    def __init__(self, map_op: PhysicalOp, agg_op: PhysicalOp,
                 program: SegmentProgram):
        super().__init__([map_op.children[0]], agg_op.schema,
                         map_op.children[0].num_partitions)
        self.map_op = map_op
        self.agg_op = agg_op
        self.program = program
        self._recorded = False
        self._resident_recorded = False
        self._record_lock = threading.Lock()

    def __getstate__(self):
        # per-process coordination state, not program identity (the same
        # contract as FusedMapOp: a shipped op records against the
        # receiving process's stats)
        state = dict(self.__dict__)
        state.pop("_record_lock", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._record_lock = threading.Lock()

    def _record(self, ctx) -> None:
        """Once per query: the fusion counters the staged plan would have
        bumped (the chain IS still fused — residency only changes where its
        outputs live), so counter-level dashboards read identically with
        residency on or off."""
        if self._recorded:
            return
        with self._record_lock:
            if self._recorded:
                return
            self._recorded = True
        if isinstance(self.map_op, FusedMapOp):
            g = self.map_op.program.graph
            ctx.stats.bump("fused_chains")
            ctx.stats.bump("fused_ops_eliminated", g.n_ops - 1)
            if g.cse_hits:
                ctx.stats.bump("cse_hits", g.cse_hits)
            if ctx.stats.profiler.armed:
                ctx.stats.profiler.event(
                    "fusion", ops=g.n_ops, cse_hits=g.cse_hits,
                    device_program=True)

    def _record_resident(self, ctx) -> None:
        """Once per query, on the FIRST successful resident execution."""
        if self._resident_recorded:
            return
        with self._record_lock:
            if self._resident_recorded:
                return
            self._resident_recorded = True
        ctx.stats.bump("device_resident_segments")
        _proc_bump("resident_segments")

    # ----------------------------------------------------------- execution
    def map_partition(self, part, ctx):
        self._record(ctx)
        return ctx.eval_segment(part, self)

    def map_partition_dispatch(self, part, ctx):
        self._record(ctx)
        return ctx.eval_segment_dispatch(part, self)

    def map_partition_declined(self, part, ctx):
        # dispatch already proved this partition device-ineligible: plain
        # routing to the staged per-op pipeline, NOT a degradation
        return ctx._eval_segment_staged(part, self, degraded=False)

    def staged_map(self, part, ctx):
        """The staged map stage, WITHOUT re-recording the fusion counters
        (this op's ``_record`` already did — FusedMapOp.map_partition has
        its own once-per-query latch that a fallback must not double-bump)."""
        if isinstance(self.map_op, FusedMapOp):
            return ctx.eval_fused(part, self.map_op.program)
        return self.map_op.map_partition(part, ctx)

    def staged_agg(self, mid, ctx):
        return self.agg_op.map_partition(mid, ctx)

    def map_empty(self, ctx):
        # same contract as the staged AggregateOp: a global agg over zero
        # partitions still yields one row (count=0, sum=null, ...)
        if not (getattr(self.agg_op, "groupby", None) or []):
            yield MicroPartition.empty(self.map_op.schema).agg(
                self.agg_op.aggregations, None)

    def _map_exprs(self):
        return list(self.map_op._map_exprs()) + list(self.agg_op._map_exprs())

    def execute(self, inputs, ctx):
        self._record(ctx)
        return self._map_execute(inputs, ctx)

    def describe(self) -> str:
        p = self.program
        return (f"DeviceSegment[{len(p.seg_exprs)} resident col(s), "
                f"{p.n_masks} mask(s)]: {self.map_op.describe()} => "
                f"{self.agg_op.describe()}")


# ---------------------------------------------------------------------------
# the resident runtime
# ---------------------------------------------------------------------------

def run_segment_async(table, prog: SegmentProgram,
                      stage_cache: Optional[dict], stats=None, cfg=None):
    """Dispatch one partition through the resident segment pipeline:
    stage inputs → launch the map program → feed its on-device outputs
    straight into the fused aggregation program → return a zero-arg
    resolver for the ONE result fetch. Returns None when this partition is
    resident-ineligible (the caller degrades to the staged per-op path);
    raises only for real device failures (the breaker's concern)."""
    import jax

    from ..kernels.device import _stage_and_run, int64_wrap_safe, size_bucket
    from ..kernels.device_agg import (_compile_agg, _finish_agg,
                                      group_codes_cached)

    # runtime firing point of the fuse.segment fault site: the resident
    # handoff (the compile-time firing point is _try_compile_segment)
    faults.check("fuse.segment", stats)

    n = len(table)
    if n == 0:
        return None

    staged = _stage_and_run(table, prog.seg_exprs, stage_cache)
    if staged is None:
        return None
    outs, _dts, _nodes, _dcs, _aux = staged  # async: device computes already
    env2 = {e.name(): out for e, out in zip(prog.seg_exprs, outs)}

    b = size_bucket(n)
    check_nodes = list(prog.child_nodes) + (
        [prog.pred_node] if prog.pred_node is not None else [])
    # the wrap guard runs over the INTERMEDIATE env (stage_cache=None: these
    # lanes are fresh compute, not cacheable staged columns — and must not
    # collide cache keys with same-named input columns)
    if not int64_wrap_safe(check_nodes, prog.inter_schema, env2, None, b):
        return None

    # group codes over the INPUT table: rows stay aligned with the mask
    # lanes (no compaction happened); the pruning output below restores the
    # filtered first-occurrence group order the host path produces
    codes_dev, uniq, num_groups = group_codes_cached(
        table, prog.gb_inputs, stage_cache, n, b, stats)
    gbk = max(16, 1 << (num_groups - 1).bit_length())

    use_pallas = bool(getattr(cfg, "use_pallas_segment_sums", False))
    use_deep = bool(getattr(cfg, "use_pallas_deep_fusion", False))
    # donation: only fresh intermediates (donation_safe), never on the CPU
    # backend (jax warns + no-ops), and never when XLA could see one buffer
    # twice (duplicate outputs would be a double donation)
    donate = prog.donation_safe and jax.default_backend() != "cpu"
    if donate:
        bufs = [id(a) for vm in env2.values() for a in vm]
        donate = len(set(bufs)) == len(bufs)

    run = _compile_agg(prog.child_nodes, prog.pred_node, prog.inter_schema,
                       prog.input_names, prog.kinds, prog.modes, gbk,
                       use_pallas, use_deep, donate=donate)

    nkey = ("nrows", n)
    n_dev = stage_cache.get(nkey) if stage_cache is not None else None
    if n_dev is None:
        import jax.numpy as jnp

        n_dev = jnp.int32(n)
        if stage_cache is not None:
            stage_cache[nkey] = n_dev

    hbm = sum(int(v.nbytes) + int(m.nbytes) for v, m in env2.values())
    if stats is not None:
        stats.bump_max("hbm_resident_bytes_high_water", hbm)
    _proc_max("hbm_resident_bytes_high_water", hbm)

    outs_dev = run(env2, codes_dev, n_dev)  # async: device computes from here

    def resolve():
        import numpy as np

        from ..schema import Field as _Field
        from ..schema import Schema as _Schema
        from ..series import Series
        from ..table import Table

        got = jax.device_get(outs_dev)
        out_cols = list(uniq._columns) if uniq is not None else []
        out_fields = list(uniq.schema) if uniq is not None else []
        agg_outs = got[:len(prog.specs)]
        for (alias, kind, agg_node, _mode), out in zip(prog.specs, agg_outs):
            expected_dt = agg_node.to_field(prog.inter_schema).dtype
            if expected_dt.is_string():
                return None  # unreachable: string intermediates declined
            merged = _finish_agg(kind, out, num_groups, expected_dt, n,
                                 dictionary=None)
            if merged is None:
                return None  # overflow guard tripped: staged path recomputes
            out_cols.append(merged.rename(alias))
            out_fields.append(_Field(alias, expected_dt))
        result = Table(_Schema(out_fields), out_cols)
        if prog.pred_node is not None and prog.has_groupby:
            # prune filtered-away groups; order survivors like the host
            # path (first occurrence within the filtered rows)
            sel_cnt, first_idx = (np.asarray(a)[:num_groups]
                                  for a in got[-1])
            surv = np.nonzero(sel_cnt > 0)[0]
            order = surv[np.argsort(first_idx[surv], kind="stable")]
            if len(order) != num_groups \
                    or (order != np.arange(num_groups)).any():
                import pyarrow as pa

                result = result.take(Series.from_arrow(
                    pa.array(order.astype(np.uint64)), "idx"))
        return result

    return resolve
