"""Native (C++) host kernel loader.

The runtime pieces that the reference implements in Rust
(src/daft-core/src/kernels/*) are C++ here, compiled once per machine into
build/libdtkernels.so and loaded via ctypes (this image has no pybind11; the
raw-buffer C ABI keeps the boundary dependency-free). Every entry point has a
bit-identical numpy fallback in kernels/host_hash.py / kernels/murmur.py, so
`available() == False` (no compiler, build failure, DAFT_TPU_NATIVE=0) only
costs speed, never correctness.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "kernels.cc")
_BUILD_DIR = os.path.join(_DIR, "build")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _so_path() -> str:
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(_BUILD_DIR, f"libdtkernels-{tag}.so")


def _build(so: str) -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tmp = so + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        sys.stderr.write(f"daft_tpu: native kernel build failed ({e}); using numpy fallbacks\n")
        try:
            if os.path.exists(tmp):
                os.unlink(tmp)
        except OSError:
            pass
        return False


_U64P = ctypes.POINTER(ctypes.c_uint64)
_I64P = ctypes.POINTER(ctypes.c_int64)
_I32P = ctypes.POINTER(ctypes.c_int32)
_U8P = ctypes.POINTER(ctypes.c_uint8)

_SIGNATURES = {
    "dt_hash_fixed64": (None, [_U64P, _U8P, ctypes.c_int64, _U64P, _U64P]),
    "dt_hash_bytes": (None, [_U8P, _I64P, _U8P, ctypes.c_int64, _U64P, _U64P]),
    "dt_hash_segments": (None, [_U64P, _I64P, _U8P, ctypes.c_int64, _U64P, _U64P]),
    "dt_murmur3_bytes": (None, [_U8P, _I64P, _U8P, ctypes.c_int64, ctypes.c_uint32, _I32P]),
    "dt_dense_codes": (ctypes.c_int64, [_I64P, ctypes.c_int64, _I64P, _I64P]),
    "dt_bucket_stable_order": (None, [_I64P, ctypes.c_int64, ctypes.c_int64, _I64P, _I64P]),
}


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first use; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("DAFT_TPU_NATIVE", "1") in ("0", "false", "off"):
            return None
        so = _so_path()
        if not os.path.exists(so) and not _build(so):
            return None
        try:
            cdll = ctypes.CDLL(so)
            for name, (restype, argtypes) in _SIGNATURES.items():
                fn = getattr(cdll, name)
                fn.restype = restype
                fn.argtypes = argtypes
            _lib = cdll
        except OSError as e:
            sys.stderr.write(f"daft_tpu: native kernel load failed ({e})\n")
            return None
    return _lib


def available() -> bool:
    return lib() is not None


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def _opt_mask(valid: Optional[np.ndarray]):
    if valid is None:
        return ctypes.cast(None, _U8P)
    return _ptr(np.ascontiguousarray(valid, dtype=np.uint8), ctypes.c_uint8)


# ---------------------------------------------------------------------------
# typed wrappers (each asserts availability; callers gate on available())
# ---------------------------------------------------------------------------

def hash_fixed64(bits: np.ndarray, valid: Optional[np.ndarray], seeds: np.ndarray) -> np.ndarray:
    n = len(bits)
    out = np.empty(n, dtype=np.uint64)
    bits = np.ascontiguousarray(bits, dtype=np.uint64)
    seeds = np.ascontiguousarray(seeds, dtype=np.uint64)
    lib().dt_hash_fixed64(_ptr(bits, ctypes.c_uint64), _opt_mask(valid), n,
                          _ptr(seeds, ctypes.c_uint64), _ptr(out, ctypes.c_uint64))
    return out


def hash_bytes(data: np.ndarray, offsets: np.ndarray, valid: Optional[np.ndarray],
               seeds: np.ndarray) -> np.ndarray:
    n = len(offsets) - 1
    out = np.empty(n, dtype=np.uint64)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    seeds = np.ascontiguousarray(seeds, dtype=np.uint64)
    if data.size == 0:
        data = np.zeros(1, dtype=np.uint8)  # valid pointer for the empty buffer
    lib().dt_hash_bytes(_ptr(data, ctypes.c_uint8), _ptr(offsets, ctypes.c_int64),
                        _opt_mask(valid), n, _ptr(seeds, ctypes.c_uint64),
                        _ptr(out, ctypes.c_uint64))
    return out


def hash_segments(inner: np.ndarray, offsets: np.ndarray, valid: Optional[np.ndarray],
                  seeds: np.ndarray) -> np.ndarray:
    n = len(offsets) - 1
    out = np.empty(n, dtype=np.uint64)
    inner = np.ascontiguousarray(inner, dtype=np.uint64)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    seeds = np.ascontiguousarray(seeds, dtype=np.uint64)
    if inner.size == 0:
        inner = np.zeros(1, dtype=np.uint64)
    lib().dt_hash_segments(_ptr(inner, ctypes.c_uint64), _ptr(offsets, ctypes.c_int64),
                           _opt_mask(valid), n, _ptr(seeds, ctypes.c_uint64),
                           _ptr(out, ctypes.c_uint64))
    return out


def murmur3_bytes(data: np.ndarray, offsets: np.ndarray, valid: Optional[np.ndarray],
                  seed: int) -> np.ndarray:
    n = len(offsets) - 1
    out = np.empty(n, dtype=np.int32)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    if data.size == 0:
        data = np.zeros(1, dtype=np.uint8)
    lib().dt_murmur3_bytes(_ptr(data, ctypes.c_uint8), _ptr(offsets, ctypes.c_int64),
                           _opt_mask(valid), n, ctypes.c_uint32(seed),
                           _ptr(out, ctypes.c_int32))
    return out


def dense_codes(vals: np.ndarray):
    """Exact dense group codes over int64 keys, first-occurrence order.
    Returns (codes[n] int64, first_idx[num] int64)."""
    n = len(vals)
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    codes = np.empty(n, dtype=np.int64)
    first_idx = np.empty(n, dtype=np.int64)
    num = lib().dt_dense_codes(_ptr(vals, ctypes.c_int64), n,
                               _ptr(codes, ctypes.c_int64), _ptr(first_idx, ctypes.c_int64))
    return codes, first_idx[:num].copy()


def bucket_stable_order(buckets: np.ndarray, num_buckets: int):
    """Counts + stable row ordering grouped by bucket (hash-shuffle fanout)."""
    n = len(buckets)
    buckets = np.ascontiguousarray(buckets, dtype=np.int64)
    if n and (buckets.min() < 0 or buckets.max() >= num_buckets):
        raise ValueError(f"bucket ids out of range [0, {num_buckets})")
    counts = np.empty(num_buckets, dtype=np.int64)
    order = np.empty(n, dtype=np.int64)
    lib().dt_bucket_stable_order(_ptr(buckets, ctypes.c_int64), n, num_buckets,
                                 _ptr(counts, ctypes.c_int64), _ptr(order, ctypes.c_int64))
    return counts, order
