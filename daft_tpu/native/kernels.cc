// Native host kernels for daft_tpu (C ABI, loaded via ctypes).
//
// Role-equivalent to the reference's Rust kernel crates
// (src/daft-core/src/kernels/hashing.rs, src/daft-core/src/array/ops/groups.rs):
// single-pass byte hashing, segment hashing, murmur3, and open-addressing
// dense group codes. Every function is BIT-IDENTICAL to the numpy fallback in
// daft_tpu/kernels/host_hash.py — the Python layer may mix both freely
// (e.g. hashes computed natively on one partition must match a numpy-hashed
// partition for shuffles to line up).
//
// ABI notes: plain C functions over raw buffers; `valid` is an optional
// per-row byte mask (1 = valid, NULL = all valid); offsets are int64 and
// ABSOLUTE into `data`.

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

static const uint64_t GOLDEN = 0x9E3779B97F4A7C15ULL;
static const uint64_t MIX1 = 0xBF58476D1CE4E5B9ULL;
static const uint64_t MIX2 = 0x94D049BB133111EBULL;
static const uint64_t NULL_HASH = 0x7FB5D329728EA185ULL;
static const uint64_t POLY_P = 0x100000001B3ULL;
static const uint64_t LEN_K = 0xC2B2AE3D27D4EB4FULL;

static inline uint64_t splitmix64(uint64_t x) {
  x += GOLDEN;
  x = (x ^ (x >> 30)) * MIX1;
  x = (x ^ (x >> 27)) * MIX2;
  return x ^ (x >> 31);
}

// fixed-width values already widened to u64 lanes by the caller
void dt_hash_fixed64(const uint64_t* bits, const uint8_t* valid, int64_t n,
                     const uint64_t* seeds, uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    if (valid && !valid[i]) {
      out[i] = splitmix64(NULL_HASH ^ seeds[i]);
    } else {
      out[i] = splitmix64(bits[i] ^ seeds[i]);
    }
  }
}

// var-len bytes: polynomial rolling hash, matches host_hash._hash_varlen
void dt_hash_bytes(const uint8_t* data, const int64_t* offsets,
                   const uint8_t* valid, int64_t n, const uint64_t* seeds,
                   uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    if (valid && !valid[i]) {
      out[i] = splitmix64(NULL_HASH ^ seeds[i]);
      continue;
    }
    const int64_t lo = offsets[i], hi = offsets[i + 1];
    uint64_t sum = 0, w = 1;
    for (int64_t j = lo; j < hi; ++j) {
      sum += ((uint64_t)data[j] + 1ULL) * w;
      w *= POLY_P;
    }
    const uint64_t len = (uint64_t)(hi - lo);
    out[i] = splitmix64(sum ^ (LEN_K * len) ^ seeds[i]);
  }
}

// list-of-hashes segments: matches host_hash._hash_segments_from_offsets
// (inner element hashes combined positionally; xor with plain length)
void dt_hash_segments(const uint64_t* inner, const int64_t* offsets,
                      const uint8_t* valid, int64_t n, const uint64_t* seeds,
                      uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    if (valid && !valid[i]) {
      out[i] = splitmix64(NULL_HASH ^ seeds[i]);
      continue;
    }
    const int64_t lo = offsets[i], hi = offsets[i + 1];
    uint64_t sum = 0, w = 1;
    for (int64_t j = lo; j < hi; ++j) {
      sum += inner[j] * w;
      w *= POLY_P;
    }
    out[i] = splitmix64(sum ^ (uint64_t)(hi - lo) ^ seeds[i]);
  }
}

// murmur3_32 over var-len rows (Iceberg-spec), matches kernels/murmur.py
static inline uint32_t rotl32(uint32_t x, int r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t mm3_finalize(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85EBCA6BU;
  h ^= h >> 13;
  h *= 0xC2B2AE35U;
  h ^= h >> 16;
  return h;
}

void dt_murmur3_bytes(const uint8_t* data, const int64_t* offsets,
                      const uint8_t* valid, int64_t n, uint32_t seed,
                      int32_t* out) {
  const uint32_t C1 = 0xCC9E2D51U, C2 = 0x1B873593U;
  for (int64_t i = 0; i < n; ++i) {
    if (valid && !valid[i]) {
      out[i] = 0;  // caller re-applies null mask
      continue;
    }
    const uint8_t* p = data + offsets[i];
    const int64_t len = offsets[i + 1] - offsets[i];
    uint32_t h = seed;
    const int64_t nblocks = len / 4;
    for (int64_t b = 0; b < nblocks; ++b) {
      uint32_t k;
      std::memcpy(&k, p + 4 * b, 4);
      k *= C1;
      k = rotl32(k, 15);
      k *= C2;
      h ^= k;
      h = rotl32(h, 13);
      h = h * 5 + 0xE6546B64U;
    }
    uint32_t k = 0;
    const int64_t tail = len & 3;
    if (tail >= 3) k ^= (uint32_t)p[4 * nblocks + 2] << 16;
    if (tail >= 2) k ^= (uint32_t)p[4 * nblocks + 1] << 8;
    if (tail >= 1) {
      k ^= (uint32_t)p[4 * nblocks];
      k *= C1;
      k = rotl32(k, 15);
      k *= C2;
      h ^= k;
    }
    h ^= (uint32_t)len;
    out[i] = (int32_t)mm3_finalize(h);
  }
}

// Dense group codes over exact int64 keys (open addressing, linear probing).
// Codes come out in first-occurrence order. Returns the group count.
// first_idx must have capacity n.
int64_t dt_dense_codes(const int64_t* vals, int64_t n, int64_t* codes,
                       int64_t* first_idx) {
  if (n == 0) return 0;
  uint64_t cap = 16;
  while (cap < (uint64_t)n * 2) cap <<= 1;
  const uint64_t mask = cap - 1;
  std::vector<int64_t> slot_key(cap);
  std::vector<int64_t> slot_code(cap, -1);
  int64_t num = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t v = vals[i];
    uint64_t h = splitmix64((uint64_t)v) & mask;
    for (;;) {
      if (slot_code[h] == -1) {
        slot_key[h] = v;
        slot_code[h] = num;
        first_idx[num] = i;
        codes[i] = num;
        ++num;
        break;
      }
      if (slot_key[h] == v) {
        codes[i] = slot_code[h];
        break;
      }
      h = (h + 1) & mask;
    }
  }
  return num;
}

// Bucketed partition counts + stable row order for hash shuffles:
// given per-row bucket ids, produce counts[num_buckets] and row indices
// grouped by bucket in stable (original) order — one pass, no sort.
void dt_bucket_stable_order(const int64_t* buckets, int64_t n,
                            int64_t num_buckets, int64_t* counts,
                            int64_t* order) {
  std::vector<int64_t> offs(num_buckets + 1, 0);
  for (int64_t i = 0; i < n; ++i) ++offs[buckets[i] + 1];
  for (int64_t b = 0; b < num_buckets; ++b) {
    counts[b] = offs[b + 1];
    offs[b + 1] += offs[b];
  }
  for (int64_t i = 0; i < n; ++i) order[offs[buckets[i]]++] = i;
}

}  // extern "C"
