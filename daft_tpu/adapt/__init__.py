# daftlint: migrated
"""Query-velocity subsystem for repeat-shaped traffic (README "Plan &
program cache").

"Millions of users" traffic is overwhelmingly repeat-shaped, yet every
repeat of the same plan shape used to re-plan, re-optimize, re-fuse, and
re-jit from scratch. This package closes the loop the flight recorder
(daft_tpu/obs/) opened, with three legs — each behind an
``ExecutionConfig`` knob (default on), each byte-identical off, each
failing open:

- ``plancache``    — a bounded, thread-safe, process-level cache keyed by
                     a CANONICAL plan fingerprint (structure + schema,
                     literals parameterized out) mapping to the optimized
                     logical plan, translated physical plan, and compiled
                     ``FusedProgram``s, so hot serving traffic skips
                     ``optimize()`` + ``translate()`` + fuse-compile
                     entirely.
- ``history``/``fdo`` — feedback-directed optimization: a per-fingerprint
                     history folded from the QueryLog feeds the planner,
                     so broadcast-vs-hash join flips and shuffle fan-out
                     resizes happen on the FIRST run of a repeated shape
                     (upstream's AdaptivePlanner re-plans from
                     *materialized* stats; this re-plans from *recorded*
                     ones). Every decision is a typed profiler event and
                     revertible: a runtime mispredict demotes the entry.
- ``resultcache``  — scan+project/filter prefixes shared across queries
                     memoize their materialized partitions, keyed by the
                     exact sub-plan fingerprint + source mtime.
"""

from .fingerprint import canonical_fingerprint, canonical_site_fp
from .history import HISTORY
from .plancache import PLAN_CACHE
from .resultcache import RESULT_CACHE

__all__ = ["canonical_fingerprint", "canonical_site_fp", "HISTORY",
           "PLAN_CACHE", "RESULT_CACHE"]
