# daftlint: migrated
"""Feedback-directed optimization: planner decisions from RECORDED stats.

Upstream's AdaptivePlanner (PAPER.md L5) re-plans from *materialized*
stats — it has to execute a stage before it learns a side was small. FDO
closes the same loop from *historical* stats: the flight recorder already
measured what this plan shape did last time, so the decision lands on the
FIRST run of a repeated shape, before anything materializes.

Decisions (each counted, logged, and emitted as a typed profiler event;
all behind ``cfg.history_fdo``, byte-identical result sets with it off):

- **join strategy** (``join_strategy_hint``, consulted by
  ``physical._translate_join``): a join side whose static size estimate
  is above (or unknown to) the broadcast threshold but whose OBSERVED
  bytes are safely below it flips to a broadcast join — gated on the
  side's subtree being able to shrink (Filter/Aggregate/Limit/...), so a
  bare source whose static estimate is already truthful never flips.
- **shuffle fan-out** (``agg_shuffle_fanout``, consulted by
  ``physical._translate_aggregate``): the internal hash exchange of a
  two-stage aggregation is resized to
  ``ceil(observed_bytes / shuffle_target_partition_bytes)`` (shrink-only,
  engine-chosen fan-outs only — user Repartition counts are never touched).
- **segment mode** (``apply_query_hints``): a shape whose recorded
  streaming runs spent most of their wall backpressure-stalled executes
  with ``streaming_execution`` off for this query only.

Every decision is *revertible*: its expectation is recorded on the plan
cache entry (``still_valid`` re-derives it as history evolves) and the
runtime mispredict guard (``note_broadcast_mispredict``, fired by
``BroadcastJoinOp`` when a history-says-small side arrives big) demotes
the entry and falls back to the uncached plan on the next run — the
current query completes correctly either way.

Decisions run only inside a ``collecting`` scope (opened by
``plancache.plan_query``'s cold path): AQE stage re-plans and bare
``explain`` translates keep today's static behavior.
"""

from __future__ import annotations

import contextlib
import threading
from typing import List, Optional

from ..obs.log import get_logger

__all__ = ["collecting", "active", "join_strategy_hint",
           "agg_shuffle_fanout", "observation_key", "still_valid",
           "apply_query_hints", "note_broadcast_mispredict"]

logger = get_logger("fdo")

# flip to broadcast only when observed bytes sit at half the threshold or
# less: hysteresis against shapes oscillating around the boundary
_BROADCAST_SLACK = 0.5
# runtime mispredict guard: the materialized side may exceed the
# threshold by this factor before the plan is demoted (observation EWMAs
# drift; a 10% overshoot is not a wrong decision)
_MISPREDICT_SLACK = 1.5
# resize an aggregate exchange only when it is worth a layout change
_FANOUT_MIN_PARTS = 4

_tl = threading.local()


class _Collector:
    __slots__ = ("cfg", "stats", "enabled", "expects", "fanout_ok")

    def __init__(self, cfg, stats, enabled: bool, fanout_ok: bool = True):
        self.cfg = cfg
        self.stats = stats
        self.enabled = enabled
        # mesh plans decline fan-out resizes: the device exchange yields
        # its collective's partition count and cannot honor a reduce-side
        # fan-in, which would desynchronize translate's partition counts
        self.fanout_ok = fanout_ok
        self.expects: List[dict] = []


@contextlib.contextmanager
def collecting(cfg, stats, enabled: bool = True, fanout_ok: bool = True):
    """Scope within which translate's FDO hooks are live; yields the
    collector whose ``expects`` the plan cache stores with the entry."""
    coll = _Collector(cfg, stats, enabled, fanout_ok)
    prev = getattr(_tl, "coll", None)
    _tl.coll = coll
    try:
        yield coll
    finally:
        _tl.coll = prev


def active() -> Optional[_Collector]:
    coll = getattr(_tl, "coll", None)
    if coll is None or not coll.enabled:
        return None
    if not getattr(coll.cfg, "history_fdo", True):
        return None
    return coll


def observation_key(subplan) -> Optional[str]:
    """The site fp a physical exchange/join should observe its payload
    under — None outside a collecting scope (no tagging overhead)."""
    if active() is None:
        return None
    try:
        from .fingerprint import canonical_site_fp

        return canonical_site_fp(subplan)
    except Exception:
        return None


def _bump(coll, counter: str, **log_fields) -> None:
    if coll.stats is not None:
        coll.stats.bump(counter)
        p = coll.stats.profiler
        if p.armed:
            p.event("fdo", kind=counter, **log_fields)
    logger.info(counter, **log_fields)


# --------------------------------------------------------------- decisions

def _shrinkable(side) -> bool:
    """Whether the side's static size estimate can overestimate: a
    cardinality-changing op in the subtree, or a filter/limit PUSHED INTO
    a scan (the optimizer removes the Filter node but the scan still
    reads a fraction of the file its size estimate charges in full)."""
    from ..adaptive import _subtree_can_shrink
    from ..logical import ScanSource

    if _subtree_can_shrink(side):
        return True

    def scan_pushed(p) -> bool:
        if isinstance(p, ScanSource):
            pd = p.pushdowns()
            return pd.filters is not None or pd.limit is not None
        return any(scan_pushed(c) for c in p.children())

    return scan_pushed(side)


def join_strategy_hint(plan) -> Optional[str]:
    """'left' / 'right' — broadcast that side — or None (no hint). Called
    by ``physical._translate_join`` for joins with no explicit strategy.

    Every side the join-type preservation rules ALLOW broadcasting is
    consulted (both for inner joins — a historically small left side
    flips just as well as a right one); each consult records a
    revalidation expectation so fresh history re-derives the decision."""
    coll = active()
    if coll is None:
        return None
    from ..physical import _broadcast_side
    from .history import HISTORY

    if plan.how == "outer":
        return None
    try:
        threshold = int(coll.cfg.broadcast_join_size_bytes_threshold)
        # which sides MAY be broadcast (outer-preservation rules): inner
        # allows either; left/semi/anti only right; right only left
        preferred = _broadcast_side(plan, None, None)
        candidates = [preferred]
        if plan.how == "inner":
            candidates.append("left" if preferred == "right" else "right")
        from .fingerprint import canonical_site_fp

        for side_name in candidates:
            side = plan.left if side_name == "left" else plan.right
            static = side.approx_size_bytes()
            if static is not None and static <= threshold:
                return None  # the static planner already broadcasts it
            if not _shrinkable(side):
                continue  # static estimate is already truthful
            site = canonical_site_fp(side)
            hist = HISTORY.size(site)
            flip = (hist is not None
                    and hist[1] <= threshold * _BROADCAST_SLACK)
            coll.expects.append({
                "kind": "join", "site": site, "threshold": threshold,
                "decided": "broadcast" if flip else "none",
            })
            if flip:
                _bump(coll, "fdo_join_flips", site=site, side=side_name,
                      observed_bytes=hist[1], threshold=threshold)
                return side_name
        return None
    except Exception as e:
        logger.warning("fdo_join_hint_failed", error=repr(e))
        return None


def broadcast_guard(plan, side_name: str):
    """(site_fp, max_bytes) the BroadcastJoinOp checks the materialized
    small side against — the runtime mispredict detector for a
    history-seeded flip."""
    coll = active()
    if coll is None:
        return None
    try:
        from .fingerprint import canonical_site_fp

        side = plan.left if side_name == "left" else plan.right
        threshold = int(coll.cfg.broadcast_join_size_bytes_threshold)
        return (canonical_site_fp(side),
                int(threshold * _MISPREDICT_SLACK))
    except Exception:
        return None


def note_broadcast_mispredict(guard, actual_bytes: int, ctx,
                              canonical_fp: str) -> None:
    """History said broadcast; the side arrived big. Count it, demote the
    shape's plan-cache entries, and record the truth — the query itself
    completes on the (correct, merely slower) broadcast plan, and the
    next plan of this shape derives hash from the fresh observation."""
    site_fp, _max = guard
    ctx.stats.bump("fdo_mispredicts")
    p = ctx.stats.profiler
    if p.armed:
        p.event("fdo", kind="fdo_mispredict", site=site_fp,
                actual_bytes=actual_bytes)
    logger.warning("fdo_mispredict", site=site_fp,
                   actual_bytes=actual_bytes)
    try:
        from .history import HISTORY
        from .plancache import PLAN_CACHE

        HISTORY.note_mispredict(site_fp)
        if canonical_fp:
            PLAN_CACHE.demote(canonical_fp)
    except Exception as e:
        logger.warning("fdo_demote_failed", error=repr(e))


def agg_shuffle_fanout(plan, nparts: int) -> Optional[int]:
    """A smaller fan-out for the internal exchange of a two-stage grouped
    aggregation, derived from the observed map-side payload — or None.
    Shrink-only, and only when the change is material (engine-chosen
    fan-outs of >= _FANOUT_MIN_PARTS shrinking by >= 2x)."""
    coll = active()
    if coll is None or not coll.fanout_ok or nparts < _FANOUT_MIN_PARTS:
        return None
    try:
        from .fingerprint import canonical_site_fp
        from .history import HISTORY

        site = "aggx:" + canonical_site_fp(plan)
        hist = HISTORY.size(site)
        target = max(int(coll.cfg.shuffle_target_partition_bytes), 1)
        ideal = None
        if hist is not None:
            ideal = max(1, -(-hist[1] // target))
        decided = (ideal if ideal is not None
                   and ideal <= nparts // 2 else None)
        coll.expects.append({
            "kind": "fanout", "site": site, "target": target,
            "nparts": nparts, "decided": decided or 0,
        })
        if decided is None:
            return None
        _bump(coll, "fdo_shuffle_resizes", site=site,
              from_parts=nparts, to_parts=decided,
              observed_bytes=hist[1])
        return decided
    except Exception as e:
        logger.warning("fdo_fanout_hint_failed", error=repr(e))
        return None


def agg_observation_key(plan) -> Optional[str]:
    """Site key the aggregate exchange observes its input payload under
    (matches ``agg_shuffle_fanout``'s lookup key)."""
    coll = active()
    if coll is None:
        return None
    try:
        from .fingerprint import canonical_site_fp

        return "aggx:" + canonical_site_fp(plan)
    except Exception:
        return None


def still_valid(exp: dict) -> bool:
    """Re-derive one recorded decision expectation against CURRENT
    history; False drops the cached entry (plancache.revalidate)."""
    from .history import HISTORY

    hist = HISTORY.size(exp["site"])
    if exp["kind"] == "join":
        flip = (hist is not None
                and hist[1] <= exp["threshold"] * _BROADCAST_SLACK)
        return ("broadcast" if flip else "none") == exp["decided"]
    if exp["kind"] == "fanout":
        ideal = None
        if hist is not None:
            ideal = max(1, -(-hist[1] // exp["target"]))
        decided = (ideal if ideal is not None
                   and ideal <= exp["nparts"] // 2 else 0)
        return decided == exp["decided"]
    return True  # unknown kinds never invalidate


# ------------------------------------------------------------ query hints

# stand down streaming only when stalls dominated: > 50% of wall across
# >= 2 recorded runs
_STREAM_STALL_SHARE = 0.5
_STREAM_MIN_RUNS = 2


def apply_query_hints(canonical_fp: str, cfg, stats):
    """Per-query config adjustments from the shape's recorded profile —
    today: streaming-vs-partition segment choice from recorded
    backpressure share. Returns ``cfg`` or a replaced copy; never raises."""
    if not canonical_fp or not getattr(cfg, "history_fdo", True) \
            or not getattr(cfg, "streaming_execution", True):
        return cfg
    try:
        from .history import HISTORY

        prof = HISTORY.query_profile(canonical_fp)
        if (prof is None or prof["runs"] < _STREAM_MIN_RUNS
                or not prof["stream_morsels"]):
            return cfg
        if prof["backpressure_ms"] \
                <= _STREAM_STALL_SHARE * prof["wall_s"] * 1000.0:
            return cfg
        import dataclasses

        if stats is not None:
            stats.bump("fdo_stream_hints")
            p = stats.profiler
            if p.armed:
                p.event("fdo", kind="fdo_stream_hint",
                        fingerprint=canonical_fp)
        logger.info("fdo_stream_hint", fingerprint=canonical_fp,
                    backpressure_ms=round(prof["backpressure_ms"], 1),
                    wall_s=round(prof["wall_s"], 3))
        return dataclasses.replace(cfg, streaming_execution=False)
    except Exception as e:
        logger.warning("fdo_query_hint_failed", error=repr(e))
        return cfg
