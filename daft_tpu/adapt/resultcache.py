# daftlint: migrated
"""Sub-plan result cache: scan+project/filter prefixes memoize their
materialized partitions across queries.

Two different queries often share a prefix — ``scan.filter(x)`` feeding a
groupby in one and a sort in another. The whole-plan PartitionSetCache
(runners.py) only helps when the ENTIRE plan repeats; this cache
memoizes at the prefix boundary instead, hooked into
``execution.execute_plan``'s builder: when a maximal chain of map-class
ops (Project/Filter/FusedMap) bottoms out at a ScanOp, its output
partitions are teed into the cache on first execution and replayed on
the next query that plans the same prefix.

Keying follows the ``_PARTITION_SET_CACHE`` discipline exactly — the
exact structural key of every scan task (``runners._scan_task_key``:
path + MTIME/SIZE + format + pushdowns + schema + storage options) plus
each chain op's literal-bearing expression keys — so an overwritten
source file can never serve stale rows, and UDF-bearing chains decline
(non-deterministic, id-reused). Float-affecting device knobs are part of
the key; every other knob is covered by the engine's byte-identity
invariants (fusion/streaming/prefetch on or off produce identical bytes).

Entries hold detached Table references (never the query's own
MicroPartition objects, which downstream spill may unload) and each hit
serves FRESH MicroPartition wrappers, so one query spilling its copy
can never corrupt another's. Bytes are LRU-shed under
``cfg.subplan_cache_bytes`` and charged to the MemoryLedger's
``subplan_cache_bytes`` account. Fails open (armed
``resultcache.lookup`` fault included): any defect degrades to plain
execution.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Iterator, List, Optional, Tuple

from ..obs.log import get_logger

__all__ = ["SubplanResultCache", "RESULT_CACHE", "try_result_cache"]

logger = get_logger("resultcache")


class _Entry:
    __slots__ = ("tables", "nbytes", "hits", "created")

    def __init__(self, tables, nbytes: int):
        self.tables = tables
        self.nbytes = nbytes
        self.hits = 0
        self.created = time.monotonic()


class SubplanResultCache:
    """Bounded, thread-safe table cache keyed by exact prefix keys."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.inserts = 0
        self.evictions = 0
        self.errors = 0

    def _charge(self, delta: int) -> None:
        if not delta:
            return
        try:
            from ..spill import MEMORY_LEDGER

            MEMORY_LEDGER.cache_account("subplan_cache_bytes", delta)
        except Exception as e:  # ledger unavailable during teardown
            logger.warning("subplan_cache_ledger_charge_failed",
                           error=repr(e))

    def get(self, key: str):
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            e.hits += 1
            self.hits += 1
            return list(e.tables)

    def put(self, key: str, tables, nbytes: int, cap_bytes: int) -> None:
        if nbytes > max(cap_bytes, 0):
            return  # one oversized prefix must not evict everything else
        delta = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
                delta -= old.nbytes
            self._entries[key] = _Entry(tables, nbytes)
            self._bytes += nbytes
            delta += nbytes
            self.inserts += 1
            while self._bytes > cap_bytes and len(self._entries) > 1:
                k, shed = self._entries.popitem(last=False)
                if k == key:
                    self._entries[k] = shed
                    self._entries.move_to_end(k, last=False)
                    break
                self._bytes -= shed.nbytes
                delta -= shed.nbytes
                self.evictions += 1
        self._charge(delta)

    def clear(self) -> None:
        """Drop every entry AND reset the stat counters (a cleared cache
        reads as a fresh one)."""
        with self._lock:
            freed = self._bytes
            self._entries.clear()
            self._bytes = 0
            self.hits = self.misses = 0
            self.inserts = self.evictions = self.errors = 0
        self._charge(-freed)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "hits": self.hits,
                "misses": self.misses,
                "inserts": self.inserts,
                "evictions": self.evictions,
                "errors": self.errors,
            }


RESULT_CACHE = SubplanResultCache()


# float-affecting knobs: the only config under which "byte-identical at
# every knob setting" does not hold (reduced-precision device sums)
_CFG_KEY_FIELDS = ("use_device_kernels", "device_reduced_precision",
                   "use_pallas_segment_sums", "use_pallas_deep_fusion")


def _chain_over_scan(op) -> Optional[Tuple[list, object]]:
    """(map-op chain top-down, scan op) when `op` roots a pure
    Project/Filter/FusedMap chain over a ScanOp; None otherwise."""
    from ..fuse.compile import FusedMapOp
    from ..physical import FilterOp, ProjectOp, ScanOp

    chain = []
    cur = op
    while isinstance(cur, (ProjectOp, FilterOp, FusedMapOp)):
        chain.append(cur)
        cur = cur.children[0]
    if not chain or not isinstance(cur, ScanOp):
        return None
    return chain, cur


def _op_key(op) -> str:
    from ..expressions import expr_has_udf
    from ..fuse.compile import FusedMapOp
    from ..physical import FilterOp

    exprs = list(op._map_exprs())
    if any(expr_has_udf(e) for e in exprs):
        raise _Decline
    kind = ("fused" if isinstance(op, FusedMapOp)
            else "filter" if isinstance(op, FilterOp) else "project")
    return f"{kind}[{';'.join(repr(e._node._key()) for e in exprs)}]"


class _Decline(Exception):
    pass


def _prefix_key(chain, scan, cfg) -> str:
    from ..runners import _Uncacheable, _scan_task_key

    try:
        scan_part = ";".join(_scan_task_key(t) for t in scan.tasks)
    except _Uncacheable:
        raise _Decline from None
    ops_part = "|".join(_op_key(o) for o in chain)
    cfg_part = ",".join(f"{k}={getattr(cfg, k, None)!r}"
                        for k in _CFG_KEY_FIELDS)
    return f"{scan_part}||{ops_part}||{cfg_part}"


def try_result_cache(op, ctx, build, trace) -> Optional[Iterator]:
    """The execute_plan builder hook: replay a cached prefix, or tee this
    prefix's output into the cache. None = not applicable (caller builds
    normally). Fails open on every path."""
    cfg = ctx.cfg
    if not getattr(cfg, "subplan_result_cache", True):
        return None
    if ctx.memory_budget is not None:
        # spill-aware: a budgeted query's working set is governed by the
        # ledger/spill machinery — replaying a process-pinned prefix (or
        # pinning this query's output in one) would silently rewrite the
        # bounded-memory execution profile the budget asked for
        return None
    if getattr(ctx, "try_device_shuffle", None) is not None \
            or getattr(ctx, "scan_owner", None) is not None:
        return None  # mesh/multi-host: partitions may be foreign-owned
    if getattr(ctx, "dist_backend", None) is not None:
        # distributed runner: workers read scan tasks themselves (scan
        # locality) — replaying a driver-pinned prefix would pull the
        # whole scan back onto the driver
        return None
    skip = getattr(ctx, "_rc_inner_ops", None)
    if skip is not None and id(op) in skip:
        return None  # an op inside a prefix already being teed above
    found = _chain_over_scan(op)
    if found is None:
        return None
    chain, scan = found
    try:
        from .. import faults

        faults.check("resultcache.lookup", ctx.stats)
        if faults.any_armed():
            # a replayed prefix would let an armed site (scan.read, ...)
            # silently never fire: fault-injection runs execute for real
            return None
        key = _prefix_key(chain, scan, cfg)
    except _Decline:
        return None
    except Exception as e:
        RESULT_CACHE.errors += 1
        ctx.stats.bump("subplan_cache_errors")
        logger.warning("subplan_cache_key_failed", error=repr(e))
        return None
    cap = getattr(cfg, "subplan_cache_bytes", 64 * 1024 * 1024)
    tables = RESULT_CACHE.get(key)
    if tables is not None:
        ctx.stats.bump("subplan_cache_hits")
        p = ctx.stats.profiler
        if p.armed:
            p.event("resultcache", kind="hit", parts=len(tables))
        return _replay(tables)
    # memory miss: the persistent disk tier (exact replay or incremental
    # refresh), which also re-populates the memory tier on a hit. pmeta
    # is None whenever the tier is off/ineligible — everything below
    # stays byte-for-byte the PR 13 path.
    pmeta = None
    try:
        from ..persist import resultstore

        pmeta = resultstore.prefix_meta(chain, scan, cfg)
        if pmeta is not None:
            tables = resultstore.disk_lookup(pmeta, chain, scan, ctx)
            if tables is not None:
                nbytes = sum(t.size_bytes() or 0 for t in tables)
                RESULT_CACHE.put(key, tables, nbytes, cap)
                return _replay(tables)
    except Exception as e:
        ctx.stats.bump("persist_load_failures")
        logger.warning("persist_tier_failed", error=repr(e))
        pmeta = None
    ctx.stats.bump("subplan_cache_misses")
    # build the real stream. The whole chain (op itself included — the
    # recursive build() below re-enters this hook) is marked so neither
    # the re-entry nor nested sub-prefixes tee duplicate entries.
    if skip is None:
        skip = ctx._rc_inner_ops = set()
    for inner in chain:
        skip.add(id(inner))
    inner_stream = build(op)
    return _teeing(inner_stream, key, cap, ctx, pmeta)


def _replay(tables) -> Iterator:
    from ..micropartition import MicroPartition

    for t in tables:
        yield MicroPartition.from_table(t)


def _teeing(inner, key: str, cap_bytes: int, ctx,
            pmeta: Optional[dict] = None) -> Iterator:
    """Pass-through that stores the prefix's output on CLEAN exhaustion
    (a limit short-circuit or error never stores a partial prefix).
    Accumulation is byte-bounded: once the running total passes the cap
    the tee abandons immediately — it must never RETAIN a giant prefix
    only for put() to reject it at the end. Close propagates promptly so
    limit early-stop semantics survive."""
    acc: List = []
    acc_bytes = 0
    abandon = False
    try:
        for p in inner:
            if not abandon:
                if p.is_loaded():
                    acc.append(p)
                    acc_bytes += p.size_bytes() or 0
                    if acc_bytes > cap_bytes:
                        # oversized prefix: stop holding references now
                        abandon = True
                        acc.clear()
                else:
                    abandon = True  # foreign/unloaded output: don't cache
                    acc.clear()
            yield p
    finally:
        close = getattr(inner, "close", None)
        if close is not None:
            try:
                close()
            except Exception as e:
                # inner teardown failing must not mask the tee's exit
                logger.warning("subplan_cache_close_failed",
                               error=repr(e))
    if abandon:
        return
    try:
        tables = [p.table() for p in acc]
        nbytes = sum(p.size_bytes() or 0 for p in acc)
        RESULT_CACHE.put(key, tables, nbytes, cap_bytes)
        p = ctx.stats.profiler
        if p.armed:
            p.event("resultcache", kind="insert", parts=len(tables),
                    nbytes=nbytes)
    except Exception as e:
        RESULT_CACHE.errors += 1
        ctx.stats.bump("subplan_cache_errors")
        logger.warning("subplan_cache_store_failed", error=repr(e))
        return
    if pmeta is not None:
        # commit to the durable tier too (its own fault site + fail-open
        # path live inside disk_store — a persist defect never surfaces)
        from ..persist import resultstore

        resultstore.disk_store(pmeta, tables, nbytes, ctx)
