# daftlint: migrated
"""Process-level plan/program cache: fingerprint -> planned artifacts.

One entry per (canonical fingerprint, config key): the optimized logical
plan, the translated+fused physical plan (compiled ``FusedProgram``s
included), and the FDO decisions baked into it. Entries hold a small LRU
of *bindings* — the exact, literal- and mtime-bearing structural keys
(``runners.plan_cache_key``) — so ``WHERE x > 5`` and ``WHERE x > 9``
share one entry (shape, byte accounting, demotion state, FDO
expectations) while each literal binding serves its own compiled plan.

Guarantees:

- **warm path**: a hit performs zero ``optimize()`` / ``translate()`` /
  fuse-compile calls (pinned by test) — the cached physical tree is
  *rehydrated* (structural clone with per-query state reset: FusedMapOp
  record latches, join-filter slots) so concurrent serving queries never
  share mutable operator state, and results are byte-identical to a cold
  plan.
- **invalidation**: the binding key embeds source mtime/size and literal
  values; the config key embeds the FULL ExecutionConfig; ``CACHE_VERSION``
  + the runtime generation cover engine/planner changes; FDO revalidation
  (``revalidate``) drops entries whose recorded decision expectations no
  longer match history; ``demote`` drops a shape after a runtime
  mispredict. No stale plan is ever served.
- **bounded**: total estimated bytes are LRU-shed under
  ``cfg.plan_cache_bytes``, charged to the MemoryLedger's
  ``plan_cache_bytes`` account.
- **failing open**: any cache-layer defect (including the armed
  ``plancache.lookup`` fault site) degrades to uncached planning, never a
  query failure. Concurrent misses on one binding build exactly once
  (single-flight); waiters that time out plan uncached.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..obs.log import get_logger

__all__ = ["PlanCache", "PLAN_CACHE", "CACHE_VERSION", "plan_query",
           "clone_plan"]

logger = get_logger("plancache")

# bump when planner/executor internals change plan semantics (also part of
# every lookup key, so stale artifacts from before a bump can never serve)
CACHE_VERSION = 1

_BINDINGS_PER_ENTRY = 8
_SINGLE_FLIGHT_WAIT_S = 30.0


class CompiledPlan:
    """One binding's planned artifacts. ``fdo_expect`` is the list of FDO
    decision expectations baked into THIS compiled plan — per binding,
    not per entry, because two literal bindings of one shape can compile
    under different history states and each must revalidate against what
    IT decided (fdo.still_valid re-derives them as history evolves)."""

    __slots__ = ("optimized", "physical", "nbytes", "fdo_expect")

    def __init__(self, optimized, physical, nbytes: int, fdo_expect=None):
        self.optimized = optimized
        self.physical = physical
        self.nbytes = nbytes
        self.fdo_expect = fdo_expect or []


class _Entry:
    __slots__ = ("canonical_fp", "cfg_key", "bindings",
                 "nbytes", "last_used", "hits")

    def __init__(self, canonical_fp: str, cfg_key: str):
        self.canonical_fp = canonical_fp
        self.cfg_key = cfg_key
        # exact binding key -> CompiledPlan (small LRU: literal variants)
        self.bindings: "OrderedDict[str, CompiledPlan]" = OrderedDict()
        self.nbytes = 0
        self.last_used = time.monotonic()
        self.hits = 0


def _estimate_plan_bytes(optimized, physical) -> int:
    """Working estimate for the byte cap: a cheap structural term (plans
    are python object graphs; exact accounting is not worth a deep walk)
    PLUS the in-memory source partitions a cached plan would PIN — a plan
    over a large from_pydict frame holds its data alive beyond the
    DataFrame's lifetime, so that data must count against (and a frame
    beyond the cap must exclude the plan from) the cache."""
    from ..physical import InMemoryOp

    def pinned(op) -> int:
        n = 0
        if isinstance(op, InMemoryOp):
            for p in op.parts:
                if p.is_loaded():
                    n += p.size_bytes() or 0
        for c in op.children:
            n += pinned(c)
        return n

    try:
        return (8192
                + 24 * (len(optimized.display_tree())
                        + len(physical.display_tree()))
                + pinned(physical))
    except Exception:
        return 65536


def _fresh_slot(slot, memo: dict):
    """Per-query-fresh copy of a JoinFilterSlot; the SAME slot object is
    shared by its feed and probe exchanges, so the copy must be too."""
    import copy

    ns = memo.get(id(slot))
    if ns is None:
        ns = copy.copy(slot)
        ns._builder = None
        ns._filter = None
        ns._sealed = False
        memo[id(slot)] = ns
    return ns


def clone_plan(op, _memo: Optional[dict] = None):
    """Rehydrate a cached physical tree for one execution: structural
    clone (fresh op objects + children lists; expressions, schemas,
    FusedPrograms, and scan tasks are immutable and shared) with every
    per-query latch reset. Cached trees are never executed directly —
    concurrent serving queries each get their own clone."""
    import copy

    from ..fuse.compile import FusedMapOp
    from ..fuse.segment import DeviceSegmentOp

    if _memo is None:
        _memo = {}
    new = copy.copy(op)
    new.children = [clone_plan(c, _memo) for c in op.children]
    if isinstance(new, FusedMapOp):
        # the once-per-query chain-counter latch (the program itself is
        # immutable and shared)
        new._recorded = False
        new._record_lock = threading.Lock()
    if isinstance(new, DeviceSegmentOp):
        # same contract for the resident-segment op: fusion-counter latch,
        # first-resident-success latch; the SegmentProgram is immutable and
        # shared — a warm hit performs ZERO segment compiles
        new._recorded = False
        new._resident_recorded = False
        new._record_lock = threading.Lock()
    ff = getattr(new, "filter_feed", None)
    if ff is not None:
        new.filter_feed = _fresh_slot(ff, _memo)
    pf = getattr(new, "probe_filter", None)
    if pf is not None:
        new.probe_filter = _fresh_slot(pf, _memo)
    return new


def _cfg_key(cfg) -> str:
    """The FULL ExecutionConfig as a deterministic string: ANY knob change
    invalidates (conservative by design — a missed planning-relevant field
    could serve a stale plan; an extra field only costs a re-plan)."""
    import dataclasses

    return ";".join(f"{f.name}={getattr(cfg, f.name)!r}"
                    for f in dataclasses.fields(cfg))


class PlanCache:
    """Bounded, thread-safe plan/program cache (see module docstring)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple[str, str], _Entry]" = OrderedDict()
        self._inflight: Dict[tuple, threading.Event] = {}
        self._bytes = 0
        self._generation = 0
        self.hits = 0
        self.misses = 0
        self.inserts = 0
        self.evictions = 0
        self.demotions = 0
        self.errors = 0

    # ------------------------------------------------------------ ledger
    def _charge(self, delta: int) -> None:
        if not delta:
            return
        try:
            from ..spill import MEMORY_LEDGER

            MEMORY_LEDGER.cache_account("plan_cache_bytes", delta)
        except Exception as e:  # ledger unavailable during teardown
            logger.warning("plan_cache_ledger_charge_failed",
                           error=repr(e))

    # ------------------------------------------------------------ lookup
    def lookup(self, canonical_fp: str, cfg_key: str,
               binding: str) -> Optional[CompiledPlan]:
        with self._lock:
            entry = self._entries.get((canonical_fp, cfg_key))
            if entry is None:
                self.misses += 1
                return None
            cp = entry.bindings.get(binding)
            if cp is None:
                self.misses += 1
                return None
            entry.bindings.move_to_end(binding)
            self._entries.move_to_end((canonical_fp, cfg_key))
            entry.last_used = time.monotonic()
            entry.hits += 1
            self.hits += 1
            return cp

    def store(self, canonical_fp: str, cfg_key: str, binding: str,
              cp: CompiledPlan, cap_bytes: int) -> None:
        if cp.nbytes > max(cap_bytes, 0):
            return  # one oversized plan must not evict the whole cache
        with self._lock:
            key = (canonical_fp, cfg_key)
            entry = self._entries.get(key)
            if entry is None:
                entry = self._entries[key] = _Entry(canonical_fp, cfg_key)
            old = entry.bindings.pop(binding, None)
            if old is not None:
                entry.nbytes -= old.nbytes
                self._bytes -= old.nbytes
            entry.bindings[binding] = cp
            entry.nbytes += cp.nbytes
            self._bytes += cp.nbytes
            self.inserts += 1
            delta = cp.nbytes - (old.nbytes if old is not None else 0)
            while len(entry.bindings) > _BINDINGS_PER_ENTRY:
                _, shed = entry.bindings.popitem(last=False)
                entry.nbytes -= shed.nbytes
                self._bytes -= shed.nbytes
                delta -= shed.nbytes
                self.evictions += 1
            self._entries.move_to_end(key)
            entry.last_used = time.monotonic()
            while self._bytes > cap_bytes and len(self._entries) > 1:
                k, shed_e = self._entries.popitem(last=False)
                if k == key:  # never shed the entry just stored
                    self._entries[k] = shed_e
                    self._entries.move_to_end(k, last=False)
                    break
                self._bytes -= shed_e.nbytes
                delta -= shed_e.nbytes
                self.evictions += 1
            # the cap binds within one entry too: literal variants of a
            # single hot shape must not hold unbounded plan bytes
            while self._bytes > cap_bytes and len(entry.bindings) > 1:
                bk = next(iter(entry.bindings))
                if bk == binding:
                    break  # never shed the binding just stored
                shed = entry.bindings.pop(bk)
                entry.nbytes -= shed.nbytes
                self._bytes -= shed.nbytes
                delta -= shed.nbytes
                self.evictions += 1
        self._charge(delta)

    # -------------------------------------------------------- invalidation
    def demote(self, canonical_fp: str) -> None:
        """Drop every entry of this shape (runtime mispredict: the cached
        plan's FDO decision was wrong — the next run re-plans uncached-
        fresh and re-caches from the corrected history)."""
        freed = 0
        with self._lock:
            for key in [k for k in self._entries if k[0] == canonical_fp]:
                e = self._entries.pop(key)
                freed += e.nbytes
                self._bytes -= e.nbytes
                self.demotions += 1
        if freed:
            self._charge(-freed)
            logger.info("plan_cache_demoted", fingerprint=canonical_fp,
                        freed_bytes=freed)

    def revalidate(self, site_fps) -> None:
        """Drop BINDINGS whose baked FDO expectations consulted any of
        the just-updated sites and no longer re-derive (fresh history
        would now plan differently — e.g. a build side crossed below the
        broadcast threshold). Per binding, not per entry: an older
        literal binding compiled under different history must not hide
        behind a newer sibling's still-valid decisions."""
        from . import fdo

        stale: List[Tuple[Tuple[str, str], str]] = []
        with self._lock:
            items = [(key, list(e.bindings.items()))
                     for key, e in self._entries.items()]
        for key, bindings in items:
            for bk, cp in bindings:
                for exp in cp.fdo_expect:
                    if exp.get("site") not in site_fps:
                        continue
                    try:
                        ok = fdo.still_valid(exp)
                    except Exception:
                        ok = False
                    if not ok:
                        stale.append((key, bk))
                        break
        if not stale:
            return
        freed = 0
        with self._lock:
            for key, bk in stale:
                e = self._entries.get(key)
                if e is None:
                    continue
                cp = e.bindings.pop(bk, None)
                if cp is None:
                    continue
                e.nbytes -= cp.nbytes
                self._bytes -= cp.nbytes
                freed += cp.nbytes
                self.demotions += 1
                if not e.bindings:
                    self._entries.pop(key, None)
        if freed:
            self._charge(-freed)
            logger.info("plan_cache_revalidated", dropped=len(stale))

    def bump_generation(self) -> None:
        """Invalidate everything (the runtime analog of a CACHE_VERSION
        bump; ``clear`` for tests)."""
        self.clear()
        with self._lock:
            self._generation += 1

    def clear(self) -> None:
        """Drop every entry AND reset the stat counters (a cleared cache
        reads as a fresh one — hit rates measured after a clear start
        from zero). In-flight single-flight events are SIGNALLED before
        being dropped: a waiter must fail open to an uncached plan now,
        not sit out the full wait timeout."""
        with self._lock:
            freed = self._bytes
            inflight = list(self._inflight.values())
            self._entries.clear()
            self._inflight.clear()
            self._bytes = 0
            self.hits = self.misses = self.inserts = 0
            self.evictions = self.demotions = self.errors = 0
        for ev in inflight:
            ev.set()
        self._charge(-freed)

    # ------------------------------------------------------ single flight
    def begin_build(self, full_key) -> Optional[threading.Event]:
        """Returns None when THIS caller owns the build; otherwise the
        event to wait on (another thread is already planning this key)."""
        with self._lock:
            ev = self._inflight.get(full_key)
            if ev is not None:
                return ev
            self._inflight[full_key] = threading.Event()
            return None

    def end_build(self, full_key) -> None:
        with self._lock:
            ev = self._inflight.pop(full_key, None)
        if ev is not None:
            ev.set()

    # -------------------------------------------------- persist artifacts
    def export_artifact(self) -> list:
        """The persist/ serialization view:
        ``[(canonical_fp, cfg_key, [(binding, pickled-CompiledPlan)])]``.
        Per-binding blobs, so one unpicklable plan (exotic closures)
        skips alone; ``mem#`` bindings (process-local in-memory source
        tokens) never persist — a fresh process can't hold their data."""
        import pickle as _pickle

        with self._lock:
            items = [(key, list(e.bindings.items()))
                     for key, e in self._entries.items()]
        out = []
        for (fp, cfg_key), bindings in items:
            blobs = []
            for bk, cp in bindings:
                if "mem#" in bk:
                    continue
                try:
                    blobs.append((bk, _pickle.dumps(
                        cp, protocol=_pickle.HIGHEST_PROTOCOL)))
                except Exception:
                    continue  # fail open: this binding stays process-only
            if blobs:
                out.append((fp, cfg_key, blobs))
        return out

    def import_artifact(self, entries, cap_bytes: int) -> int:
        """Merge an artifact's entries; LIVE bindings win (the running
        process's plans are newer than any file). Lookup counters are NOT
        touched — hit rates must reflect real query traffic, not the
        load. Returns bindings merged."""
        import pickle as _pickle

        n = 0
        for fp, cfg_key, blobs in entries:
            for bk, blob in blobs:
                with self._lock:
                    e = self._entries.get((fp, cfg_key))
                    if e is not None and bk in e.bindings:
                        continue
                try:
                    cp = _pickle.loads(blob)
                except Exception:
                    continue  # one bad blob is one cold binding
                self.store(fp, cfg_key, bk, cp, cap_bytes)
                n += 1
        return n

    # ------------------------------------------------------------- admin
    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bindings": sum(len(e.bindings)
                                for e in self._entries.values()),
                "bytes": self._bytes,
                "hits": self.hits,
                "misses": self.misses,
                "inserts": self.inserts,
                "evictions": self.evictions,
                "demotions": self.demotions,
                "errors": self.errors,
            }


PLAN_CACHE = PlanCache()


def _event(stats, kind: str, **fields) -> None:
    p = stats.profiler
    if p.armed:
        p.event("plancache", kind=kind, **fields)


def _has_write(plan) -> bool:
    from ..logical import Write

    if isinstance(plan, Write):
        return True
    return any(_has_write(c) for c in plan.children())


def plan_query(plan, cfg, stats=None, optimized: bool = False,
               runner: str = "native"):
    """The runners' one planning entry point: FDO-informed optimize +
    translate + fuse, served from the plan cache when possible.

    Returns ``(optimized_plan, physical_plan, run_cfg)`` — ``run_cfg`` is
    ``cfg`` unless a history-driven per-query hint (e.g. streaming-off)
    replaced a knob for this execution only.

    Timing lands in ``stats``: ``planning_wall_ns`` covers this whole
    call (cold planning or warm lookup+rehydrate), ``compile_wall_ns``
    the fuse-compile share inside ``translate`` — the very costs the
    cache removes stay measurable either way."""
    import time as _time

    from . import fdo
    from .fingerprint import canonical_fingerprint

    t0 = _time.perf_counter_ns()
    canonical = ""
    try:
        canonical = canonical_fingerprint(plan)
    except Exception as e:
        # an unfingerprintable plan only loses cache/FDO eligibility
        logger.warning("canonical_fingerprint_failed", error=repr(e))

    def _finish(opt, phys, run_cfg, from_cache: bool):
        if canonical:
            phys._canonical_fp = canonical
        if stats is not None:
            stats.bump("planning_wall_ns",
                       _time.perf_counter_ns() - t0)
        run_cfg = fdo.apply_query_hints(canonical, run_cfg, stats)
        return opt, phys, run_cfg

    def _cold(record_fdo: bool):
        from ..optimizer import optimize
        from ..physical import fuse_for_device, translate

        # fan-out resizes decline for: mesh plans (the device collective
        # yields its own partition count — a reduce-side fan-in would
        # desynchronize translate's counts) and Write-bearing plans (one
        # output file per partition: an identical write query must not
        # change its file count/layout with process history)
        fanout_ok = runner != "mesh" and not _has_write(plan)
        with fdo.collecting(cfg, stats, enabled=record_fdo,
                            fanout_ok=fanout_ok) as coll:
            opt = plan if optimized else optimize(plan)
            phys = translate(opt, cfg, stats=stats)
            phys = fuse_for_device(phys, cfg)
        return opt, phys, coll

    use_cache = (getattr(cfg, "plan_cache", True) and not optimized
                 and canonical)
    binding = cfg_key = None
    if use_cache:
        try:
            from .. import faults
            from ..runners import plan_cache_key

            faults.check("plancache.lookup", stats)
            # warm-start: merge any on-disk artifacts before the first
            # lookup (latched per process; inert without cfg.cache_dir).
            # Sits BEFORE the any_armed stand-down so an armed
            # persist.load plan reaches its site and cold-misses there.
            if getattr(cfg, "cache_dir", None) is not None:
                from .. import persist

                persist.ensure_loaded(cfg, stats)
            # an armed fault registry stands the cache down entirely: a
            # cached plan would let an armed site (fuse.compile, ...)
            # silently never fire — chaos runs must plan for real
            binding = None if faults.any_armed() else plan_cache_key(plan)
            # the runner is part of the key: mesh plans decline FDO
            # fan-out decisions, so a native-planned tree must never
            # serve a mesh execution (and vice versa)
            cfg_key = _cfg_key(cfg) + f"|v{CACHE_VERSION}" \
                + f"|g{PLAN_CACHE.generation}|r{runner}"
        except Exception as e:
            PLAN_CACHE.errors += 1
            if stats is not None:
                stats.bump("plan_cache_errors")
            logger.warning("plan_cache_lookup_failed", error=repr(e))
            binding = None
    if not use_cache or binding is None:
        opt, phys, _ = _cold(record_fdo=not optimized)
        return _finish(opt, phys, cfg, from_cache=False)

    full_key = (canonical, cfg_key, binding)
    waited = False
    while True:
        try:
            cp = PLAN_CACHE.lookup(canonical, cfg_key, binding)
        except Exception:
            PLAN_CACHE.errors += 1
            cp = None
        if cp is not None:
            if stats is not None:
                stats.bump("plan_cache_hits")
                _event(stats, "hit", fingerprint=canonical)
            try:
                phys = clone_plan(cp.physical)
            except Exception as e:
                # rehydration defect: fail open to a fresh plan
                PLAN_CACHE.errors += 1
                if stats is not None:
                    stats.bump("plan_cache_errors")
                logger.warning("plan_cache_rehydrate_failed",
                               error=repr(e))
                break
            return _finish(cp.optimized, phys, cfg, from_cache=True)
        if waited:
            break  # builder failed or evicted underneath us: plan uncached
        ev = PLAN_CACHE.begin_build(full_key)
        if ev is not None:
            # someone else is planning this exact binding: wait, re-check
            waited = True
            if not ev.wait(_SINGLE_FLIGHT_WAIT_S):
                break
            continue
        # we own the build
        try:
            opt, phys, coll = _cold(record_fdo=True)
            if stats is not None:
                stats.bump("plan_cache_misses")
                _event(stats, "miss", fingerprint=canonical)
            try:
                cp = CompiledPlan(opt, phys,
                                  _estimate_plan_bytes(opt, phys),
                                  fdo_expect=coll.expects)
                PLAN_CACHE.store(canonical, cfg_key, binding, cp,
                                 getattr(cfg, "plan_cache_bytes",
                                         64 * 1024 * 1024))
            except Exception as e:
                PLAN_CACHE.errors += 1
                if stats is not None:
                    stats.bump("plan_cache_errors")
                logger.warning("plan_cache_store_failed", error=repr(e))
            return _finish(opt, phys, cfg, from_cache=False)
        finally:
            PLAN_CACHE.end_build(full_key)
    # fail-open tail: plan uncached (still FDO-informed)
    opt, phys, _ = _cold(record_fdo=True)
    if stats is not None:
        stats.bump("plan_cache_misses")
    return _finish(opt, phys, cfg, from_cache=False)
