# daftlint: migrated
"""FDO history: what repeated plan shapes actually did at runtime.

A bounded, thread-safe, process-level registry with two views:

- **site observations** (``observe``/``size``): per canonical *subtree*
  fingerprint (``fingerprint.canonical_site_fp``), the rows/bytes that
  actually flowed through that subtree — join sides observed at their
  exchanges, aggregate map-side output observed at its shuffle. This is
  what seeds broadcast-vs-hash flips and shuffle fan-out resizes on the
  FIRST run of a repeated shape (upstream's AdaptivePlanner needs a
  materialization barrier to learn the same fact).
- **query profiles** (``fold``): per canonical *query* fingerprint, the
  wall/ttfr/streaming aggregates of past runs — the streaming-vs-
  partition segment hint's input.

``fold`` runs from ``execution.execute_plan``'s completion hook (fail-open:
a history defect degrades to an error log, never a query failure) and
afterwards asks the plan cache to revalidate entries whose FDO decisions
consulted the just-updated sites — so a shape cached with a hash join is
re-planned (and flips to broadcast) as soon as history says its build
side is small, and a runtime mispredict (``note_mispredict``) demotes the
entry the same way.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

__all__ = ["QueryHistory", "HISTORY"]

# EWMA weight for new observations (repeat-shaped traffic drifts slowly;
# a single outlier run must not whipsaw the planner)
_ALPHA = 0.5


class _SiteStats:
    __slots__ = ("rows", "bytes", "count", "last_rows", "last_bytes",
                 "mispredicts")

    def __init__(self):
        self.rows = 0.0
        self.bytes = 0.0
        self.count = 0
        self.last_rows = 0
        self.last_bytes = 0
        self.mispredicts = 0


class QueryHistory:
    """Bounded history registry (see module docstring)."""

    def __init__(self, max_sites: int = 4096, max_queries: int = 1024):
        self._lock = threading.Lock()
        self._sites: "OrderedDict[str, _SiteStats]" = OrderedDict()
        self._queries: "OrderedDict[str, dict]" = OrderedDict()
        self._max_sites = max_sites
        self._max_queries = max_queries
        # monotone write counter: the persist/ artifact leg's dirty
        # marker (a save is skipped while history did not move)
        self._mutations = 0

    # ----------------------------------------------------------- sites
    def observe(self, site_fp: str, rows: int, nbytes: int) -> None:
        with self._lock:
            st = self._sites.get(site_fp)
            if st is None:
                st = self._sites[site_fp] = _SiteStats()
                st.rows = float(rows)
                st.bytes = float(nbytes)
            else:
                st.rows = (1 - _ALPHA) * st.rows + _ALPHA * rows
                st.bytes = (1 - _ALPHA) * st.bytes + _ALPHA * nbytes
            st.count += 1
            st.last_rows = rows
            st.last_bytes = nbytes
            self._mutations += 1
            self._sites.move_to_end(site_fp)
            while len(self._sites) > self._max_sites:
                self._sites.popitem(last=False)

    def size(self, site_fp: str) -> Optional[Tuple[int, int, int]]:
        """(ewma rows, ewma bytes, observation count) or None."""
        with self._lock:
            st = self._sites.get(site_fp)
            if st is None:
                return None
            return int(st.rows), int(st.bytes), st.count

    def note_mispredict(self, site_fp: str) -> None:
        """A decision seeded from this site was wrong at runtime (e.g. a
        history-says-broadcast side grew past the threshold). The caller
        also observes the TRUE size, so the next plan degrades to the
        uncached decision on its own; this just keeps the event countable."""
        with self._lock:
            st = self._sites.get(site_fp)
            if st is not None:
                st.mispredicts += 1

    # --------------------------------------------------------- queries
    def query_profile(self, canonical_fp: str) -> Optional[dict]:
        with self._lock:
            p = self._queries.get(canonical_fp)
            return dict(p) if p is not None else None

    def fold(self, canonical_fp: str, stats, rec: dict) -> None:
        """Fold one finished execution into the history: site observations
        accumulated by the tagged exchanges/joins (``stats.fdo_obs``) and
        the per-query aggregates, then revalidate dependent plan-cache
        entries.

        Only CLEAN completions contribute site observations. The
        observation points already record only after fully draining their
        input (a mid-fanout teardown never reaches ``fdo_observe``), but
        an errored/abandoned/deadline-killed run is drained here and
        discarded anyway — biased-low sizes from any partially-consumed
        path must never seed a broadcast flip."""
        obs = stats.take_fdo_obs()
        if rec.get("outcome") != "ok":
            obs = {}
        for site_fp, (rows, nbytes) in obs.items():
            self.observe(site_fp, rows, nbytes)
        if canonical_fp and rec.get("outcome") == "ok":
            counters = rec.get("counters", {})
            prof = {
                "wall_s": rec.get("wall_s", 0.0),
                "ttfr_ms": counters.get("time_to_first_row_ns", 0) / 1e6,
                "stream_morsels": counters.get("stream_morsels", 0),
                "backpressure_ms":
                    counters.get("stream_backpressure_ns", 0) / 1e6,
                "runs": 1,
            }
            with self._lock:
                prev = self._queries.get(canonical_fp)
                if prev is not None:
                    for k in ("wall_s", "ttfr_ms", "backpressure_ms"):
                        prof[k] = (1 - _ALPHA) * prev[k] + _ALPHA * prof[k]
                    prof["stream_morsels"] = max(prev["stream_morsels"],
                                                 prof["stream_morsels"])
                    prof["runs"] = prev["runs"] + 1
                self._queries[canonical_fp] = prof
                self._mutations += 1
                self._queries.move_to_end(canonical_fp)
                while len(self._queries) > self._max_queries:
                    self._queries.popitem(last=False)
        if obs:
            # new facts may flip a decision a cached plan baked in: drop
            # entries whose recorded FDO expectations no longer hold
            from .plancache import PLAN_CACHE

            PLAN_CACHE.revalidate(set(obs))

    # ------------------------------------------------- persist artifacts
    @property
    def mutations(self) -> int:
        with self._lock:
            return self._mutations

    def export(self) -> dict:
        """Plain-data serialization for the persist/ artifact leg: site
        EWMA rows (_SiteStats slots as tuples) + query profiles."""
        with self._lock:
            return {
                "sites": {fp: (st.rows, st.bytes, st.count, st.last_rows,
                               st.last_bytes, st.mispredicts)
                          for fp, st in self._sites.items()},
                "queries": {fp: dict(p)
                            for fp, p in self._queries.items()},
            }

    def merge(self, data: dict) -> int:
        """Merge an artifact's export; LIVE keys win (this process's own
        observations are fresher than any file). Returns keys merged."""
        n = 0
        with self._lock:
            for fp, row in (data.get("sites") or {}).items():
                if fp in self._sites or len(self._sites) >= self._max_sites:
                    continue
                st = _SiteStats()
                (st.rows, st.bytes, st.count, st.last_rows,
                 st.last_bytes, st.mispredicts) = row
                self._sites[fp] = st
                self._sites.move_to_end(fp, last=False)
                n += 1
            for fp, p in (data.get("queries") or {}).items():
                if fp in self._queries \
                        or len(self._queries) >= self._max_queries:
                    continue
                self._queries[fp] = dict(p)
                self._queries.move_to_end(fp, last=False)
                n += 1
        return n

    # ------------------------------------------------------------ admin
    def snapshot(self) -> dict:
        with self._lock:
            return {"sites": len(self._sites),
                    "queries": len(self._queries),
                    "mispredicts": sum(s.mispredicts
                                       for s in self._sites.values())}

    def clear(self) -> None:
        """Tests only."""
        with self._lock:
            self._sites.clear()
            self._queries.clear()
            self._mutations += 1  # a clear IS a state change


HISTORY = QueryHistory()
