# daftlint: migrated
"""Canonical plan fingerprints: structure + schema, literals masked out.

Two queries that differ only in literal values — ``WHERE x > 5`` vs
``WHERE x > 9`` — share a canonical fingerprint, so the plan cache and
the FDO history treat them as one *shape* while the exact, literal-bearing
fingerprint (``obs.querylog.plan_signature``) keeps per-query identity in
the QueryRecord.

Two scopes:

- ``identity`` (``canonical_fingerprint``): the cross-process-stable
  shape label the QueryRecord carries as ``plan_fingerprint_canonical``.
  In-memory sources contribute only schema + partition count (a process-
  local object token would break cross-interpreter stability); scan
  sources contribute paths/format/pushdown structure but NOT mtimes (a
  rewritten file keeps its shape).
- ``site`` (``canonical_site_fp``): the process-local key the FDO history
  observes plan subtrees under. In-memory sources additionally contribute
  their data-identity token so observations from one test frame can never
  seed decisions for a different frame that merely shares a schema.

The serialization is deterministic: no ``id()``, no ``hash()``, no
default object reprs (their embedded addresses are scrubbed defensively),
callables by ``__qualname__`` — pinned by the two-interpreter stability
test in tests/test_adapt.py.
"""

from __future__ import annotations

import hashlib
import re
from typing import Any, List, Optional

__all__ = ["canonical_fingerprint", "canonical_site_fp",
           "canonical_expr_key", "literal_values"]

_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")

# attributes that are derived/cache state, never identity
_SKIP_ATTRS = ("schema", "file_schema", "_memoizable_cache", "_cache_token",
               "_obs_signature")


def _scrub(s: str) -> str:
    """Strip memory addresses from default reprs — identity must be
    process-independent."""
    return _ADDR_RE.sub("0x", s)


def _scalar(v: Any) -> str:
    if callable(v):
        return f"fn:{getattr(v, '__qualname__', getattr(v, '__name__', 'fn'))}"
    return _scrub(repr(v))


def _expr_canon(node, out: List[str],
                params: Optional[List[Any]]) -> None:
    from ..expressions import Expression, ExprNode, Literal

    if isinstance(node, Expression):
        node = node._node
    if isinstance(node, Literal):
        # the value is parameterized OUT; dtype + weakness stay (they are
        # typing-relevant — a weak lit(2) and a strong lit(2, int64)
        # resolve differently in binary contexts)
        if params is not None:
            params.append(node.value)
        out.append(f"lit?:{node.dtype!r}:w{int(node.weak)}")
        return
    out.append(type(node).__name__)
    kids = node.children()
    kid_ids = {id(k) for k in kids}
    for k in sorted(vars(node)):
        if k in _SKIP_ATTRS:
            continue
        v = getattr(node, k)
        if isinstance(v, ExprNode):
            if id(v) in kid_ids:
                continue  # serialized via children() below
            out.append(f"{k}=(")
            _expr_canon(v, out, params)
            out.append(")")
        elif isinstance(v, (list, tuple)) and any(
                isinstance(e, (ExprNode, Expression)) for e in v):
            if all(id(getattr(e, "_node", e)) in kid_ids for e in v):
                continue
            out.append(f"{k}=[")
            for e in v:
                _expr_canon(e, out, params)
            out.append("]")
        else:
            out.append(f"{k}={_scalar(v)}")
    out.append("(")
    for c in kids:
        _expr_canon(c, out, params)
    out.append(")")


def canonical_expr_key(expr) -> str:
    """Canonical (literal-masked) serialization of one expression."""
    out: List[str] = []
    _expr_canon(expr, out, None)
    return "|".join(out)


def _schema_canon(schema) -> str:
    return ",".join(f"{f.name}:{f.dtype!r}" for f in schema)


def _scan_task_canon(t, out: List[str], params) -> None:
    """Shape identity of one scan task: path/format/options/pushdowns —
    NOT mtime or size (a rewritten file keeps its shape; exactness is the
    binding key's job)."""
    out.append(f"scan:{getattr(t, 'path', '?')}"
               f"|{getattr(t, 'format', '?')}")
    # MergedScanTask and friends expose children; fold them in
    for c in getattr(t, "children", ()) or ():
        _scan_task_canon(c, out, params)
    opts = getattr(t, "storage_options", None)
    if opts:
        out.append(";".join(f"{k}={_scalar(v)}" for k, v in sorted(
            opts.items(), key=lambda kv: kv[0])))
    out.append(f"rg={getattr(t, 'row_group_ids', None)!r}"
               f"|pv={_scrub(repr(getattr(t, 'partition_values', None)))}")
    sch = getattr(t, "schema", None)
    if sch is not None:
        out.append(_schema_canon(sch))
    pd = getattr(t, "pushdowns", None)
    if pd is not None:
        out.append(f"cols={getattr(pd, 'columns', None)!r}"
                   f"|limit={getattr(pd, 'limit', None)!r}")
        filt = getattr(pd, "filters", None)
        if filt is not None:
            out.append("filt=(")
            _expr_canon(filt, out, params)
            out.append(")")


def _plan_canon(p, out: List[str], params, scope: str) -> None:
    from ..expressions import Expression
    from ..logical import InMemorySource, ScanSource

    out.append(type(p).__name__)
    if isinstance(p, InMemorySource):
        out.append(f"mem[{len(p.partitions)}]:{_schema_canon(p.schema)}")
        if scope == "site":
            # data identity: observations must never cross frames that
            # merely share a schema (process-local by design)
            out.append(f"tok={p._cache_token}")
        return
    if isinstance(p, ScanSource):
        for t in p.tasks:
            _scan_task_canon(t, out, params)
        return
    kids = p.children()
    kid_ids = {id(k) for k in kids}
    for k in sorted(vars(p)):
        if k in _SKIP_ATTRS or k.startswith("_fdo"):
            continue
        v = getattr(p, k)
        if id(v) in kid_ids:
            continue
        if isinstance(v, Expression):
            out.append(f"{k}=(")
            _expr_canon(v, out, params)
            out.append(")")
        elif isinstance(v, (list, tuple)) and any(
                isinstance(e, Expression) for e in v):
            out.append(f"{k}=[")
            for e in v:
                _expr_canon(e, out, params)
            out.append("]")
        else:
            out.append(f"{k}={_scalar(v)}")
    out.append("(")
    for c in kids:
        _plan_canon(c, out, params, scope)
    out.append(")")


def _digest(parts: List[str]) -> str:
    return hashlib.sha256("|".join(parts).encode()).hexdigest()[:16]


def canonical_fingerprint(plan) -> str:
    """Cross-process-stable shape fingerprint of a logical plan, literals
    parameterized out (the QueryRecord's ``plan_fingerprint_canonical``)."""
    out: List[str] = []
    _plan_canon(plan, out, None, "identity")
    return _digest(out)


def canonical_site_fp(plan) -> str:
    """Process-local observation key for one plan subtree (FDO history):
    canonical shape PLUS in-memory data-identity tokens."""
    out: List[str] = []
    _plan_canon(plan, out, None, "site")
    return _digest(out)


def literal_values(plan) -> List[Any]:
    """The literal values a canonical fingerprint masked out, in
    deterministic walk order (diagnostic surface; the plan cache keys
    bindings by the exact structural key instead)."""
    params: List[Any] = []
    out: List[str] = []
    _plan_canon(plan, out, params, "identity")
    return params
