"""Peer allgather transport for multi-process (jax distributed) clusters.

The multihost mesh exchange normally rides XLA collectives (ICI+DCN). On
toolchains whose backend has no cross-process collective transport (the
jaxlib CPU gap the multihost tests pin), the engine still needs a data
plane: this module gives the N peer processes of one jax distributed
cluster a host-side allgather over TCP, so the shuffle exchange can move
rows between processes without the collective backend
(mesh_exec._transport_shuffle routes through it when the collective path
fails).

Topology: a star. Process 0 hosts the hub (bound next to the jax
coordinator port, override with DAFT_TPU_PEER_PORT); every other process
dials in once and holds the connection. One ``allgather(payload)`` round:
each peer sends its bytes, the hub collects all N contributions (its own
included) and broadcasts the full pid-ordered list. SPMD discipline —
every process issues the same rounds in the same order — is the same
contract the collective exchange already requires, and round ids are
checked so a desync fails loudly instead of mispairing payloads.
"""

from __future__ import annotations

import os
import queue
import socket
import threading
import time
from typing import List, Optional

from ..errors import DaftTransientError
from ..obs.log import get_logger
from .transport import TransportClosed, recv_msg, send_msg

logger = get_logger("dist.peer")

# how long one allgather round may wait on the slowest peer before the
# caller's breaker/fallback machinery takes over
ROUND_TIMEOUT_S = 300.0


class PeerGroup:
    """One process's handle on the cluster-wide allgather plane."""

    def __init__(self, host: str, port: int, nproc: int, pid: int):
        self.host = host
        self.port = port
        self.nproc = nproc
        self.pid = pid
        self._round = 0
        # serializes collective rounds end-to-end: held across the
        # round's socket traffic by design (rounds must not interleave)
        self._lock = threading.Lock()  # daftlint: io-lock
        self._sock: Optional[socket.socket] = None
        self._hub: Optional["_Hub"] = None
        self._local_q: Optional[queue.Queue] = None
        if pid == 0:
            self._hub = _Hub(host, port, nproc)
            self._local_q = self._hub.local_q

    def allgather(self, payload: bytes,
                  timeout_s: float = ROUND_TIMEOUT_S) -> List[bytes]:
        """All processes' payloads for this round, pid-ordered. Raises
        DaftTransientError when a peer goes away / times out — callers
        degrade exactly like a failed collective."""
        with self._lock:
            rnd = self._round
            self._round += 1
            if self.pid == 0:
                self._hub.ensure_started(timeout_s)
                reply: "queue.Queue" = queue.Queue()
                self._local_q.put((rnd, payload, reply))
                try:
                    out = reply.get(timeout=timeout_s)
                except queue.Empty:
                    raise DaftTransientError(
                        f"peer allgather round {rnd} timed out on the hub")
                if isinstance(out, BaseException):
                    raise out
                return out
            sock = self._connect(timeout_s)
            try:
                send_msg(sock, {"type": "ag", "round": rnd, "pid": self.pid,
                                "data": payload})
                msg = recv_msg(sock)
            except (TransportClosed, OSError) as e:
                self._drop_socket()
                raise DaftTransientError(
                    f"peer allgather failed: {e!r}") from e
            if msg.get("type") != "agr" or msg.get("round") != rnd:
                self._drop_socket()
                raise DaftTransientError(
                    f"peer allgather desync: expected round {rnd}, got "
                    f"{msg.get('type')}/{msg.get('round')}")
            return msg["datas"]

    def _connect(self, timeout_s: float) -> socket.socket:
        if self._sock is not None:
            return self._sock
        deadline = time.monotonic() + min(timeout_s, 60.0)
        last: Optional[Exception] = None
        while time.monotonic() < deadline:
            try:
                s = socket.create_connection((self.host, self.port),
                                             timeout=5.0)
                s.settimeout(timeout_s)
                send_msg(s, {"type": "join", "pid": self.pid})
                self._sock = s
                return s
            except OSError as e:
                last = e
                time.sleep(0.2)
        raise DaftTransientError(
            f"could not reach peer hub {self.host}:{self.port}: {last!r}")

    def _drop_socket(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


class _Hub:
    """Process 0's collector/broadcaster (lazy: binds on first round)."""

    def __init__(self, host: str, port: int, nproc: int):
        self.host = host
        self.port = port
        self.nproc = nproc
        self.local_q: "queue.Queue" = queue.Queue()
        self._started = False
        self._start_lock = threading.Lock()
        self._peers: dict = {}
        self._error: Optional[Exception] = None

    def ensure_started(self, timeout_s: float) -> None:
        with self._start_lock:
            if self._started:
                if self._error is not None:
                    raise DaftTransientError(
                        f"peer hub failed: {self._error!r}")
                return
            listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            listener.bind((self.host, self.port))
            listener.listen(self.nproc + 2)
            self._listener = listener
            t = threading.Thread(target=self._serve,
                                 name="daft-dist-peer-hub", daemon=True)
            t.start()
            self._started = True

    def _serve(self) -> None:
        try:
            self._listener.settimeout(ROUND_TIMEOUT_S)
            while len(self._peers) < self.nproc - 1:
                sock, _ = self._listener.accept()
                sock.settimeout(ROUND_TIMEOUT_S)
                join = recv_msg(sock)
                if join.get("type") != "join":
                    sock.close()
                    continue
                self._peers[join["pid"]] = sock
            while True:
                # one round: the local contribution names the round id;
                # every peer socket then delivers exactly one "ag" frame
                rnd, local_data, reply = self.local_q.get()
                try:
                    datas: List[Optional[bytes]] = [None] * self.nproc
                    datas[0] = local_data
                    for pid, sock in self._peers.items():
                        msg = recv_msg(sock)
                        if msg.get("type") != "ag" or msg.get("round") != rnd:
                            raise DaftTransientError(
                                f"hub desync from pid {pid}: "
                                f"{msg.get('type')}/{msg.get('round')} != "
                                f"ag/{rnd}")
                        datas[msg["pid"]] = msg["data"]
                    out = {"type": "agr", "round": rnd, "datas": datas}
                    for sock in self._peers.values():
                        send_msg(sock, out)
                    reply.put(datas)
                except BaseException as e:
                    reply.put(e if isinstance(e, Exception)
                              else DaftTransientError(repr(e)))
                    raise
        except BaseException as e:
            self._error = e if isinstance(e, Exception) else Exception(repr(e))
            logger.warning("peer_hub_failed", error=repr(e))
            for sock in self._peers.values():
                try:
                    sock.close()
                except OSError:
                    pass


_GROUP: Optional[PeerGroup] = None
_GROUP_LOCK = threading.Lock()


def get_peer_group() -> Optional[PeerGroup]:
    """This process's PeerGroup, derived from the jax distributed cluster
    info multihost.init_distributed recorded; None outside a multi-process
    cluster (or when no coordinator address is known)."""
    global _GROUP
    with _GROUP_LOCK:
        if _GROUP is not None:
            return _GROUP
        from ..parallel.multihost import cluster_info

        info = cluster_info()
        if info is None:
            return None
        coordinator, nproc, pid = info
        if nproc is None or pid is None or nproc <= 1:
            return None
        host = coordinator.rsplit(":", 1)[0] if coordinator else "127.0.0.1"
        env_port = os.environ.get("DAFT_TPU_PEER_PORT")
        if env_port is not None:
            port = int(env_port)
        elif coordinator and ":" in coordinator:
            # deterministic rendezvous next to the coordinator port: every
            # process derives the same address with zero extra coordination
            port = int(coordinator.rsplit(":", 1)[1]) + 1
        else:
            return None
        _GROUP = PeerGroup(host, port, nproc, pid)
        return _GROUP
