"""Distributed-worker process entrypoint: ``python -m daft_tpu.dist.worker``.

One worker = one OS process the supervisor spawned. It connects back to
the driver's listener, authenticates with the spawn token, receives its
ExecutionConfig (with a carved child memory budget), and then serves
tasks until told to stop:

- a **reader thread** drains the socket: ``ping`` is answered immediately
  (a busy worker still heartbeats), ``task`` messages queue for the
  executor loop, ``cancel`` marks a queued task skippable (the losing
  side of a speculative duplicate), ``shutdown`` (or EOF) ends the
  process;
- the **main loop** executes one task at a time — unpickle the map op
  (cached per op key), materialize/execute ``op.map_partition`` against a
  local ExecutionContext, and ship the result (or the error) back. The
  ``worker.task`` fault site fires per execution and is armable from the
  parent's environment (``faults.ENV_FAULT_SPEC``), which is how chaos
  tooling slows exactly one worker into a deterministic straggler.

Telemetry (daft_tpu/obs/cluster.py): when the driver's task envelope asks
for it, the task runs inside a :class:`TelemetryCollector` scope — a local
Profiler (armed only when the driver's query is profiled), a RuntimeStats
counter snapshot, and a log-record capture — and the bounded fragment it
builds piggybacks on the ``result``/``task_error`` reply. Fragments carry
an incremental sequence number (``tseq``) that pongs echo, so the
supervisor can count fragments lost in flight (a dead worker's un-shipped
telemetry) as ``telemetry_dropped``. Building a fragment is strictly
fail-open: any defect ships the reply WITHOUT telemetry, never an error.

The worker never decides policy: retries, re-dispatch, deadlines, and
poison detection all live driver-side in supervisor.py — a worker that
dies mid-task simply stops answering, and the supervision layer treats
the silence as the failure signal.
"""

from __future__ import annotations

import os
import pickle
import queue
import signal
import socket
import sys
import threading
import time


def _execute_task(op, part, exec_ctx, msg: dict):
    """Run one map task against the worker-local ExecutionContext, inside
    a task-scope span when the task's telemetry collector armed a local
    profiler — the span is the root the driver splices the worker subtree
    under (DTL006 pins this entry point opening it). The ``worker.task``
    fault site fires per execution (the chaos straggler/failure hook)."""
    from .. import faults
    from ..obs.log import get_logger

    prof = exec_ctx.stats.profiler
    sp = None
    if prof.armed:
        sp = prof.begin("worker.task", op=msg.get("op_name"),
                        part=msg.get("seq"), kind="bg")
    try:
        faults.check("worker.task")
        return op.map_partition(part, exec_ctx)
    except BaseException as e:
        # the worker's view of the failure, emitted INSIDE the telemetry
        # scope so the fragment's log tail relays it to the driver's ring
        get_logger("dist.worker").warning(
            "worker_task_failed", op=msg.get("op_name"),
            seq=msg.get("seq"), error=repr(e))
        raise
    finally:
        if sp is not None:
            prof.end(sp)


def _serve(sock: socket.socket, worker_id: int, token: str) -> int:
    # late imports: the module must be importable for argv parsing before
    # the (expensive) engine import decides the process's fate
    from .. import faults
    from ..context import get_context
    from ..obs.log import get_logger
    from .peerplane import PieceServer, execute_fanout, plane
    from .transport import _FLAG_CRC, PROTOCOL_VERSION, TransportClosed, \
        recv_msg, send_msg

    log = get_logger("dist.worker")
    # held across each framed reply by design: one socket, one frame
    # at a time (interleaved frames would desync the driver's reader)
    send_lock = threading.Lock()  # daftlint: io-lock
    # the peer-shuffle piece server binds BEFORE the hello carries its
    # port: no dispatched reduce task can ever hold an unbound address
    peer_server = PieceServer(token)
    peer_server.start()
    # frame checksums MIRROR the driver's: every received frame's flag
    # byte updates this, so a driver-side cfg.partition_integrity toggle
    # flips both directions of traffic without a respawn. The hello
    # itself is always checksummed (both sides speak v2 or the handshake
    # rejects).
    checksum = [True]
    # fragments attached to replies, ever (the telemetry sequence number):
    # read and bumped ONLY under send_lock, so a pong echoing it can never
    # overtake the reply frame that carried the counted fragment — socket
    # FIFO then guarantees the driver sees the fragment before the seq
    tel_seq = [0]

    def reply(msg: dict, frag=None) -> None:
        with send_lock:
            if frag is not None:
                tel_seq[0] += 1
                msg["telemetry"] = frag
                msg["tseq"] = tel_seq[0]
            send_msg(sock, msg, checksum=checksum[0])

    reply({"type": "hello", "worker_id": worker_id, "pid": os.getpid(),
           "token": token, "proto": PROTOCOL_VERSION,
           "peer_port": peer_server.port})
    init = recv_msg(sock)
    if init.get("type") != "init":
        raise RuntimeError(f"expected init, got {init.get('type')!r}")
    cfg = init["cfg"]
    checksum[0] = bool(getattr(cfg, "partition_integrity", True))
    peer_server.checksum = checksum[0]
    ctx = get_context()
    ctx.execution_config = cfg
    # fault plans armed by the PARENT process via the environment (chaos
    # tooling's cross-process hook — e.g. a worker.task delay plan that
    # slows exactly this worker into a straggler)
    faults.arm_from_env(worker_id)

    from ..execution import ExecutionContext

    exec_ctx = ExecutionContext(cfg)
    # peer-plane identity + stats hook: fetch/refetch counters bumped
    # during piece pulls land on the worker's RuntimeStats and ride the
    # telemetry fragments back into the driver's per-query rollup
    plane().configure(worker_id, exec_ctx.stats)
    # persistent result tier (persist/resultstore): one store per worker
    # slot models one store per node — peer serving between slots on one
    # host exercises the real fleet-warming path. Fail-open: a persist
    # defect leaves the worker serving plain tasks.
    rs_store = None
    try:
        if getattr(cfg, "cache_dir", None) is not None \
                and getattr(cfg, "persist_result_store", True):
            from ..persist.resultstore import RESULT_STORE as rs_store

            rs_store.configure(os.path.join(
                os.path.abspath(cfg.cache_dir), f"w{worker_id}"))
    except Exception as e:
        rs_store = None
        log.warning("worker_persist_configure_failed", error=repr(e))
    tasks: "queue.Queue" = queue.Queue()
    inflight = [0]
    op_cache: dict = {}
    # task ids cancelled by the driver (the losing side of a speculative
    # duplicate): queued-but-unstarted tasks are skipped with an explicit
    # task_skipped ack; a task already executing cannot be preempted —
    # the driver discards its late result through the exactly-once ledger
    cancelled: set = set()

    def ledger_report() -> dict:
        try:
            from ..spill import MEMORY_LEDGER

            snap = MEMORY_LEDGER.snapshot()
            return {"current": snap["current"],
                    "high_water": snap["high_water"]}
        except Exception:
            return {"current": 0, "high_water": 0}

    def read_loop() -> None:
        try:
            while True:
                msg, flags = recv_msg(sock, with_flags=True)
                checksum[0] = bool(flags & _FLAG_CRC)
                kind = msg.get("type")
                if kind == "ping":
                    with send_lock:
                        seq = tel_seq[0]
                    pong = {"type": "pong", "worker_id": worker_id,
                            "inflight": inflight[0],
                            "tseq": seq,
                            "ledger": ledger_report(),
                            "peer": plane().snapshot()}
                    if rs_store is not None:
                        # hosted result-tier digests + counters piggyback
                        # the heartbeat: the driver's location map for
                        # peer-serving cached prefixes
                        try:
                            pong["rs"] = rs_store.pong_report()
                        except Exception:
                            pass
                    reply(pong)
                elif kind == "task":
                    inflight[0] += 1
                    tasks.put(msg)
                elif kind == "cancel":
                    # ids never reuse, so stale entries are harmless —
                    # but bound the set anyway (a cleared stale id at
                    # worst skips a skip: the task runs and the driver
                    # drops its result through the exactly-once ledger)
                    if len(cancelled) > 4096:
                        cancelled.clear()
                    cancelled.add(msg.get("task_id"))
                elif kind == "drop_shuffles":
                    # end-of-life broadcast for a query's shuffle pieces
                    plane().drop_shuffles(msg.get("ids", []))
                elif kind == "drain":
                    # graceful quiesce: queued AFTER any in-flight task,
                    # so current work finishes and replies first
                    tasks.put({"_drain": True})
                elif kind == "shutdown":
                    tasks.put(None)
                    return
        except TransportClosed:
            tasks.put(None)  # driver went away: exit cleanly
        except Exception as e:
            log.error("worker_reader_failed", error=repr(e))
            tasks.put(None)

    reader = threading.Thread(target=read_loop, name="daft-dist-reader",
                              daemon=True)
    reader.start()

    # SIGTERM = spot preemption notice: tell the driver we are draining
    # (it stops routing tasks here), finish the current task, keep
    # serving hosted pieces through the grace window, then exit 0. The
    # handler only spawns a thread — the main thread may hold send_lock
    # when the signal lands, and a direct reply() would self-deadlock.
    def _on_sigterm(signum, frame):
        def _announce():
            try:
                reply({"type": "draining", "worker_id": worker_id})
            except Exception:
                pass
            tasks.put({"_drain": True})

        threading.Thread(target=_announce, name="daft-dist-announce",
                         daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):
        pass  # non-main thread / exotic platform: drain stays driver-led

    while True:
        msg = tasks.get()
        if msg is None:
            break
        if msg.get("_drain"):
            # quiesce: no new tasks will arrive (the driver marked this
            # slot draining); hold the piece server open for the grace
            # window so peers finish their fetches, then leave — pieces
            # lost with us re-source from lineage at the read site
            log.info("worker_draining", worker=worker_id,
                     pieces=plane().snapshot()["pieces_hosted"])
            time.sleep(float(getattr(cfg, "worker_drain_grace_s", 2.0)))
            peer_server.close()
            break
        task_id = msg["task_id"]
        if task_id in cancelled:
            # speculative loser cancelled before this task ever started:
            # ack the skip so the driver frees the slot deterministically
            cancelled.discard(task_id)
            inflight[0] -= 1
            reply({"type": "task_skipped", "task_id": task_id})
            continue
        collector = None
        try:
            spec = msg.get("shuffle")
            op = None
            if spec is None:
                op_key = msg["op_key"]
                if "op" in msg:
                    # (re-)insert at the end so eviction order tracks the
                    # driver's send order (its ops_sent window is smaller
                    # than this cache, so a key it omits is always still
                    # here)
                    op_cache.pop(op_key, None)
                    op_cache[op_key] = pickle.loads(msg["op"])
                    while len(op_cache) > 128:  # bounded across queries
                        op_cache.pop(next(iter(op_cache)))
                op = op_cache[op_key]
            part = msg["part"]
            if isinstance(part, (bytes, bytearray)):
                # the driver pre-serializes partitions once (re-dispatches
                # reuse the bytes); decode here
                part = pickle.loads(part)
            if msg.get("telemetry"):
                # per-task telemetry scope (obs/cluster.py): counter
                # snapshot + log capture always, a bounded local profiler
                # when the driver's query is profiled. Failing to BUILD
                # the scope must not fail the task (fail-open).
                try:
                    from ..obs.cluster import TelemetryCollector

                    collector = TelemetryCollector(
                        msg.get("query_id"), msg.get("op_name", "task"),
                        msg.get("seq", 0), exec_ctx.stats,
                        profile=bool(msg.get("profile")))
                except Exception:
                    collector = None
            def _run_map(op=op, part=part, msg=msg):
                # result-tier hook: serve the task's output from the
                # local/peer store when the driver attached an rs
                # address; a miss (or any defect) executes the task for
                # real and write-throughs — the task IS the recipe
                rs = msg.get("rs")
                if rs is not None and rs_store is not None:
                    from ..persist import resultstore

                    cached = resultstore.worker_lookup(
                        rs, exec_ctx, token, checksum[0])
                    if cached is not None:
                        return cached
                    res = _execute_task(op, part, exec_ctx, msg)
                    resultstore.worker_store(rs, res, exec_ctx)
                    return res
                return _execute_task(op, part, exec_ctx, msg)

            t0 = time.perf_counter_ns()
            # _execute_task fires the worker.task chaos hook: an armed
            # delay plan slows this worker (counted into the reported
            # wall), a failure plan becomes a task_error the driver's
            # retry machinery owns
            if collector is not None:
                with collector:
                    out = (execute_fanout(part, spec, exec_ctx)
                           if spec is not None else _run_map())
            else:
                out = (execute_fanout(part, spec, exec_ctx)
                       if spec is not None else _run_map())
            wall_ns = time.perf_counter_ns() - t0
            if spec is not None:
                # a fanout's reply is piece METADATA only — the payload
                # bytes stay parked in this process's piece store
                n = sum(m[1] for m in out)
            else:
                n = out.num_rows_or_none()
            reply({"type": "result", "task_id": task_id, "part": out,
                   "rows": n if n is not None else 0, "wall_ns": wall_ns},
                  frag=collector.fragment() if collector else None)
        except BaseException as e:  # a task failure must not kill the worker
            try:
                err_pickle = pickle.dumps(e)
            except Exception:
                err_pickle = None
            try:
                frag = collector.fragment() if collector else None
            except Exception:
                frag = None
            reply({"type": "task_error", "task_id": task_id,
                   "error": err_pickle, "error_type": type(e).__name__,
                   "error_message": str(e)[:2000]}, frag=frag)
        finally:
            inflight[0] -= 1
            # a cancel that raced an already-executing task left its id
            # parked in the set; the id is spent now — drop it
            cancelled.discard(task_id)
    return 0


def main(argv) -> int:
    host, port, worker_id, token = (
        argv[0], int(argv[1]), int(argv[2]), argv[3])
    sock = socket.create_connection((host, port), timeout=30)
    sock.settimeout(None)
    try:
        return _serve(sock, worker_id, token)
    finally:
        try:
            sock.close()
        except OSError:
            pass


if __name__ == "__main__":
    # workers compute on the host path by default: a spawned worker must
    # never race the driver for the accelerator (override to opt in)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main(sys.argv[1:]))
