"""Distributed-worker process entrypoint: ``python -m daft_tpu.dist.worker``.

One worker = one OS process the supervisor spawned. It connects back to
the driver's listener, authenticates with the spawn token, receives its
ExecutionConfig (with a carved child memory budget), and then serves
tasks until told to stop:

- a **reader thread** drains the socket: ``ping`` is answered immediately
  (a busy worker still heartbeats), ``task`` messages queue for the
  executor loop, ``shutdown`` (or EOF) ends the process;
- the **main loop** executes one task at a time — unpickle the map op
  (cached per op key), materialize/execute ``op.map_partition`` against a
  local ExecutionContext, and ship the result (or the error) back.

The worker never decides policy: retries, re-dispatch, deadlines, and
poison detection all live driver-side in supervisor.py — a worker that
dies mid-task simply stops answering, and the supervision layer treats
the silence as the failure signal.
"""

from __future__ import annotations

import os
import pickle
import queue
import socket
import sys
import threading
import time


def _serve(sock: socket.socket, worker_id: int, token: str) -> int:
    # late imports: the module must be importable for argv parsing before
    # the (expensive) engine import decides the process's fate
    from ..context import get_context
    from ..obs.log import get_logger
    from .transport import TransportClosed, recv_msg, send_msg

    log = get_logger("dist.worker")
    send_lock = threading.Lock()

    def reply(msg: dict) -> None:
        with send_lock:
            send_msg(sock, msg)

    reply({"type": "hello", "worker_id": worker_id, "pid": os.getpid(),
           "token": token})
    init = recv_msg(sock)
    if init.get("type") != "init":
        raise RuntimeError(f"expected init, got {init.get('type')!r}")
    cfg = init["cfg"]
    ctx = get_context()
    ctx.execution_config = cfg

    from ..execution import ExecutionContext

    exec_ctx = ExecutionContext(cfg)
    tasks: "queue.Queue" = queue.Queue()
    inflight = [0]
    op_cache: dict = {}

    def ledger_report() -> dict:
        try:
            from ..spill import MEMORY_LEDGER

            snap = MEMORY_LEDGER.snapshot()
            return {"current": snap["current"],
                    "high_water": snap["high_water"]}
        except Exception:
            return {"current": 0, "high_water": 0}

    def read_loop() -> None:
        try:
            while True:
                msg = recv_msg(sock)
                kind = msg.get("type")
                if kind == "ping":
                    reply({"type": "pong", "worker_id": worker_id,
                           "inflight": inflight[0],
                           "ledger": ledger_report()})
                elif kind == "task":
                    inflight[0] += 1
                    tasks.put(msg)
                elif kind == "shutdown":
                    tasks.put(None)
                    return
        except TransportClosed:
            tasks.put(None)  # driver went away: exit cleanly
        except Exception as e:
            log.error("worker_reader_failed", error=repr(e))
            tasks.put(None)

    reader = threading.Thread(target=read_loop, name="daft-dist-reader",
                              daemon=True)
    reader.start()

    while True:
        msg = tasks.get()
        if msg is None:
            break
        task_id = msg["task_id"]
        try:
            op_key = msg["op_key"]
            if "op" in msg:
                # (re-)insert at the end so eviction order tracks the
                # driver's send order (its ops_sent window is smaller than
                # this cache, so a key it omits is always still here)
                op_cache.pop(op_key, None)
                op_cache[op_key] = pickle.loads(msg["op"])
                while len(op_cache) > 128:  # bounded across queries
                    op_cache.pop(next(iter(op_cache)))
            op = op_cache[op_key]
            part = msg["part"]
            if isinstance(part, (bytes, bytearray)):
                # the driver pre-serializes partitions once (re-dispatches
                # reuse the bytes); decode here
                part = pickle.loads(part)
            t0 = time.perf_counter_ns()
            out = op.map_partition(part, exec_ctx)
            wall_ns = time.perf_counter_ns() - t0
            n = out.num_rows_or_none()
            reply({"type": "result", "task_id": task_id, "part": out,
                   "rows": n if n is not None else 0, "wall_ns": wall_ns})
        except BaseException as e:  # a task failure must not kill the worker
            try:
                err_pickle = pickle.dumps(e)
            except Exception:
                err_pickle = None
            reply({"type": "task_error", "task_id": task_id,
                   "error": err_pickle, "error_type": type(e).__name__,
                   "error_message": str(e)[:2000]})
        finally:
            inflight[0] -= 1
    return 0


def main(argv) -> int:
    host, port, worker_id, token = (
        argv[0], int(argv[1]), int(argv[2]), argv[3])
    sock = socket.create_connection((host, port), timeout=30)
    sock.settimeout(None)
    try:
        return _serve(sock, worker_id, token)
    finally:
        try:
            sock.close()
        except OSError:
            pass


if __name__ == "__main__":
    # workers compute on the host path by default: a spawned worker must
    # never race the driver for the accelerator (override to opt in)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main(sys.argv[1:]))
