"""DistributedRunner: the multi-process backend behind the Runner ABC.

Role-equivalent to the reference's RayRunner (daft/runners/ray_runner.py):
the same optimized physical plan the NativeRunner executes, but with the
scheduler's dispatch backend pointed at the supervised WorkerPool — every
eligible map-class partition task ships to a worker process over the
socket transport; everything else (sources, exchanges, pipeline breakers,
UDF closures) stays on the driver. ``cfg.distributed_workers`` selects the
pool size; 0 degrades to exactly the NativeRunner (no pool, no backend),
and results are byte-identical at every worker count.
"""

from __future__ import annotations

from typing import Iterator

from ..context import get_context
from ..execution import ExecutionContext, execute_plan
from ..logical import LogicalPlan
from ..micropartition import MicroPartition
from ..runners import Runner


class DistributedRunner(Runner):
    name = "distributed"

    def _run_plain(self, plan: LogicalPlan, qctx,
                   optimized: bool = False) -> Iterator[MicroPartition]:
        ctx = get_context()
        cfg = ctx.execution_config
        _, phys, run_cfg = self.plan_query(plan, optimized,
                                           stats=qctx.stats)
        exec_ctx = ExecutionContext(run_cfg, qctx=qctx)
        if cfg.distributed_workers > 0:
            from .supervisor import get_worker_pool

            exec_ctx.dist_backend = get_worker_pool(cfg)
        return execute_plan(phys, exec_ctx)
