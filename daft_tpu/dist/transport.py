"""Length-prefixed message transport between the driver and its workers.

One frame = an 8-byte big-endian payload length + a pickled message dict.
Pickle is the wire format because the payloads ARE engine objects — Tables
(arrow-backed columns), scan tasks, physical map ops — and the endpoints
are trusted same-host processes the driver itself spawned (the token
handshake in worker.py keeps strangers off the socket; this is an IPC
plane, not a network service).

Failure contract: any partial read/EOF raises :class:`TransportClosed`
(a DaftTransientError — the supervision layer treats it as a dead
connection and re-dispatches), and every send passes the
``transport.send`` fault site so CI can sever a link deterministically.
"""

from __future__ import annotations

import pickle
import socket
import struct

from ..errors import DaftTransientError

# one frame's length prefix: 8-byte big-endian unsigned
_LEN = struct.Struct(">Q")
# a frame bigger than this is a protocol desync/corruption, not a payload
# (partitions are bounded by the memory budget, far below 1 TiB)
MAX_FRAME_BYTES = 1 << 40


class TransportClosed(DaftTransientError):
    """The peer went away mid-frame (EOF, reset, severed link)."""


def send_msg(sock: socket.socket, msg: dict) -> None:
    """Serialize + frame + send one message. Raises TransportClosed on a
    dead connection; the ``transport.send`` fault site fires here."""
    from .. import faults

    data = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
    try:
        faults.check("transport.send")
        sock.sendall(_LEN.pack(len(data)) + data)
    except DaftTransientError:
        raise
    except OSError as e:
        raise TransportClosed(f"transport send failed: {e!r}") from e


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        try:
            chunk = sock.recv(min(n - got, 1 << 20))
        except OSError as e:
            raise TransportClosed(f"transport recv failed: {e!r}") from e
        if not chunk:
            raise TransportClosed(
                f"peer closed mid-frame ({got}/{n} bytes)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket) -> dict:
    """Receive one framed message (blocking). Raises TransportClosed on
    EOF/reset and DaftTransientError on a corrupt frame."""
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if length > MAX_FRAME_BYTES:
        raise DaftTransientError(
            f"transport frame length {length} exceeds {MAX_FRAME_BYTES} "
            "(protocol desync)")
    return pickle.loads(_recv_exact(sock, length))
