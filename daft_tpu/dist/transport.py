"""Length-prefixed message transport between the driver and its workers.

One frame = a 13-byte header (8-byte big-endian payload length, 1 flag
byte, 4-byte crc32 of the payload) + a pickled message dict. Pickle is
the wire format because the payloads ARE engine objects — Tables
(arrow-backed columns), scan tasks, physical map ops — and the endpoints
are trusted same-host processes the driver itself spawned (the token
handshake in worker.py keeps strangers off the socket; this is an IPC
plane, not a network service).

Integrity (protocol v2): the sender records the payload's crc32 in the
frame header (flag bit 0 set) and the receiver verifies it before
unpickling, so a frame damaged in flight raises
:class:`~..errors.DaftCorruptionError` instead of feeding pickle garbage
— the supervision layer treats the connection as dead and re-dispatches.
Control-plane frames (up to ``_FULL_CRC_MAX``) are covered in full; BULK
payload frames (shipped partitions — tens of MB per query on the q1
bench leg) use STRIPED coverage (flag bit 1): first + last + every Nth
64 KiB block. A full-payload pass on every hop would cost ~20% of the
transport-bound q1 wall (measured: ~83 MB of frames per query at
~1.5 GB/s crc, twice per direction) — striping keeps the bench
``integrity_overhead_pct`` gate under 3% while still catching the
realistic frame failure modes (truncation, torn writes, desync, header/
metadata damage) on every frame; SILENT at-rest corruption is owned by
the spill/encode checksums, which stay full-coverage. ``checksum=False``
(cfg.partition_integrity off) sends flag 0 frames the receiver passes
through unverified. Peers speaking the old 8-byte-header protocol are
rejected at the handshake: the worker's hello carries
``PROTOCOL_VERSION`` and the supervisor drops mismatched candidates.

Failure contract: any partial read/EOF raises :class:`TransportClosed`
(a DaftTransientError — the supervision layer treats it as a dead
connection and re-dispatches), every send passes the ``transport.send``
fault site so CI can sever a link deterministically, and
``transport.corrupt`` flips a real payload bit AFTER the crc was
computed — the deterministic wire-corruption hook.
"""

from __future__ import annotations

import pickle
import socket
import struct
import zlib

from ..errors import DaftCorruptionError, DaftTransientError

# wire protocol version, carried in the worker hello: bumped to 2 when
# frames grew the flags+crc header fields (old-frame peers desync, so the
# handshake rejects them by version before any framed traffic matters)
PROTOCOL_VERSION = 2
# one frame's header: 8-byte big-endian payload length, 1 flag byte
# (bit 0 = payload crc present, bit 1 = striped coverage), 4-byte crc32
_HDR = struct.Struct(">QBI")
_FLAG_CRC = 1
_FLAG_STRIPED = 2
# frames up to this size crc in full (control plane: pings, acks, task
# envelopes, small results); larger frames stripe
_FULL_CRC_MAX = 256 * 1024
# striped coverage: first + last + every _STRIPE_EVERY'th 64 KiB block
# (~1.6% of bulk-frame bytes — the q1-leg overhead gate's budget)
_STRIPE = 64 * 1024
_STRIPE_EVERY = 64
# a frame bigger than this is a protocol desync/corruption, not a payload
# (partitions are bounded by the memory budget, far below 1 TiB)
MAX_FRAME_BYTES = 1 << 40


def _payload_crc(data: bytes) -> "tuple[int, int]":
    """(crc, flags) for a frame payload: full crc32 for control-plane
    sizes, striped for bulk payloads (both sides derive the same stripes
    from the payload length)."""
    n = len(data)
    if n <= _FULL_CRC_MAX:
        return zlib.crc32(data) & 0xFFFFFFFF, _FLAG_CRC
    m = memoryview(data)
    crc = zlib.crc32(n.to_bytes(8, "big"))
    for off in range(0, n, _STRIPE * _STRIPE_EVERY):
        crc = zlib.crc32(m[off:off + _STRIPE], crc)
    crc = zlib.crc32(m[n - _STRIPE:], crc)
    return crc & 0xFFFFFFFF, _FLAG_CRC | _FLAG_STRIPED


class TransportClosed(DaftTransientError):
    """The peer went away mid-frame (EOF, reset, severed link)."""


def dial(host: str, port: int, timeout: float = 30.0) -> socket.socket:
    """Open one framed-transport connection to a peer endpoint (the
    worker piece-servers of dist/peerplane.py dial each other with this).
    Connect is bounded by ``timeout`` and the socket keeps it for framed
    round-trips, so a dead peer reads as TransportClosed instead of a
    hang; the caller owns close()."""
    try:
        sock = socket.create_connection((host, port), timeout=timeout)
    except OSError as e:
        raise TransportClosed(
            f"transport dial {host}:{port} failed: {e!r}") from e
    sock.settimeout(timeout)
    return sock


def send_msg(sock: socket.socket, msg: dict, checksum: bool = True) -> None:
    """Serialize + frame + send one message. ``checksum`` stamps the
    payload's crc32 into the header for receiver-side verification
    (cfg.partition_integrity). Raises TransportClosed on a dead
    connection; the ``transport.send`` and ``transport.corrupt`` fault
    sites fire here."""
    from .. import faults

    data = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
    if checksum:
        crc, flags = _payload_crc(data)
    else:
        crc, flags = 0, 0
    try:
        faults.check("transport.send")
        try:
            faults.check("transport.corrupt")
        except DaftTransientError:
            # wire damage, deterministically: the crc above describes the
            # CLEAN payload, so the receiver's verify must catch this
            from ..integrity.checksum import flip_payload_bits

            data = flip_payload_bits(data)
        sock.sendall(_HDR.pack(len(data), flags, crc) + data)
    except DaftTransientError:
        raise
    except OSError as e:
        raise TransportClosed(f"transport send failed: {e!r}") from e


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        try:
            chunk = sock.recv(min(n - got, 1 << 20))
        except OSError as e:
            raise TransportClosed(f"transport recv failed: {e!r}") from e
        if not chunk:
            raise TransportClosed(
                f"peer closed mid-frame ({got}/{n} bytes)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket, with_flags: bool = False):
    """Receive one framed message (blocking). Raises TransportClosed on
    EOF/reset, DaftCorruptionError on a checksum-failed payload, and
    DaftTransientError on a desynced frame. ``with_flags`` additionally
    returns the frame's flag byte — the worker mirrors the driver's
    checksum setting from it, so toggling cfg.partition_integrity
    driver-side flips BOTH directions of frame traffic without a fleet
    respawn (the bench integrity A/B depends on that)."""
    (length, flags, crc) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    if length > MAX_FRAME_BYTES:
        raise DaftTransientError(
            f"transport frame length {length} exceeds {MAX_FRAME_BYTES} "
            "(protocol desync)")
    data = _recv_exact(sock, length)
    if flags & _FLAG_CRC:
        got, _ = _payload_crc(data)
        if got != crc:
            raise DaftCorruptionError(
                f"transport frame failed its integrity check "
                f"(crc {got:#010x} != {crc:#010x}, {length} bytes"
                f"{', striped' if flags & _FLAG_STRIPED else ''})")
    msg = pickle.loads(data)
    return (msg, flags) if with_flags else msg
