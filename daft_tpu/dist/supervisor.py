"""Worker supervision: spawn, heartbeat, re-dispatch, bounded respawn.

The WorkerPool owns N spawned worker processes and is the driver side of
the dispatch-backend abstraction (scheduler.DispatchBackend): map-class
partition tasks route here, execute on a worker, and return — while the
pool treats worker death as a first-class event:

- **heartbeats with a deadline**: the supervision thread pings every
  worker each ``worker_heartbeat_interval_s``; no pong within
  ``worker_heartbeat_timeout_s`` (or a dead process, a severed socket, an
  injected ``worker.heartbeat`` fault) declares the worker dead.
- **WorkerHealth breaker per worker** (the DeviceHealth trip/cooldown/
  probe shape from PR 1): a slot that keeps dying trips its breaker and
  stops being respawned until the cooldown probe lets one attempt through.
- **bounded respawn**: respawns (never the initial spawns) consume the
  pool-wide ``worker_restart_budget``; an exhausted budget degrades the
  pool to local in-process execution instead of cycling forever.
- **task re-dispatch with exactly-once results**: each task carries an
  attempt count and an excluded-worker set. A worker death re-dispatches
  only tasks still in flight — results already acked into the driver-side
  ledger are never re-run. A poison task that kills every worker it
  touches fails its QUERY with a DaftError naming the task once it
  exhausts ``dist_task_max_attempts`` or has excluded every slot.

Fault sites (CI chaos hooks, all DTL004-registered): ``worker.spawn``
fails a spawn attempt, ``worker.exec`` SIGKILLs the target worker at
dispatch (a REAL mid-query worker loss, deterministically placed),
``worker.heartbeat`` reads as a missed deadline, ``transport.send``
severs a link.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import pickle
import secrets
import signal
import socket
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..errors import DaftError, DaftTransientError
from ..execution import DeviceHealth
from ..obs.log import get_logger
from .transport import PROTOCOL_VERSION, TransportClosed, recv_msg, send_msg

logger = get_logger("dist")

# worker-side op-cache keys: process-wide monotonic, never reused (id()
# would alias across GC)
_OP_SEQ = itertools.count(1)

# speculative execution: completed-wall samples kept per op name (the
# running distribution the p75 straggler threshold is computed from), and
# the minimum sample count before speculation may trigger at all — with
# fewer completions the p75 is noise, and duplicating tasks on a cold
# pool would be pure added load
_WALL_HISTORY = 64
_SPECULATION_MIN_SAMPLES = 4


class WorkerHealth(DeviceHealth):
    """Per-worker circuit breaker: consecutive deaths trip it open (no
    respawn), the cooldown probe admits one respawn attempt, and a worker
    that comes back healthy re-closes it — the DeviceHealth contract
    applied to process supervision."""

    def __init__(self, threshold: int = 3, cooldown_s: float = 30.0):
        super().__init__(threshold, cooldown_s, kind="worker")


class _LocalFallback(Exception):
    """Internal: the pool cannot serve this task (degraded/closed) — the
    caller runs it in-process instead. Never escapes the backend."""


class _TaskEntry:
    """Driver-side ledger row for one dispatched task."""

    __slots__ = ("task_id", "op_name", "seq", "ctx", "attempts", "excluded",
                 "status", "result", "error", "event", "charged", "wid",
                 "active_wids", "spec_wid", "dispatched_at", "frag",
                 "frag_wid", "submit_pc", "sent_pc", "reply_pc", "extra",
                 "prefer", "result_wid")

    def __init__(self, task_id: int, op_name: str, seq: int, ctx):
        self.task_id = task_id
        self.op_name = op_name
        self.seq = seq
        self.ctx = ctx
        self.attempts = 0
        self.excluded: set = set()
        # inflight -> done | error | lost (lost = worker died; re-dispatch)
        self.status = "idle"
        self.result: Optional[Tuple] = None
        self.error: Optional[BaseException] = None
        self.event = threading.Event()
        self.charged = 0
        self.wid: Optional[int] = None
        # worker slots currently executing this entry (>1 while a
        # speculative duplicate is in flight); the entry only reads as
        # LOST when the set empties — one of two runners dying is not a
        # loss, it is exactly what speculation pays for
        self.active_wids: set = set()
        # the duplicate's worker slot while one is in flight (None
        # otherwise); invariant: the pool-wide _spec_inflight counter
        # counts entries whose spec_wid is set
        self.spec_wid: Optional[int] = None
        # when the current primary dispatch left the driver — the clock
        # the straggler threshold compares against
        self.dispatched_at = 0.0
        # telemetry fragment from the settling reply (obs/cluster.py) and
        # the worker slot it came from; merged by _execute on the query
        # thread, where the dist.remote span is open
        self.frag = None
        self.frag_wid: Optional[int] = None
        # driver-side perf_counter stamps for the dist.remote phase split:
        # submit (dispatch entered) -> sent (frame on the wire) -> reply
        # (reply frame processed) — visible even when the fragment is lost
        self.submit_pc = 0
        self.sent_pc = 0
        self.reply_pc = 0
        # envelope extras merged into the task message (a peer-shuffle
        # fanout carries its split spec here instead of a map op)
        self.extra: Optional[dict] = None
        # peer-locality preference: worker slots already hosting this
        # task's input pieces (dispatch picks among these when one is
        # free, turning remote piece fetches into local store reads)
        self.prefer: Optional[set] = None
        # the slot whose RESULT settled the entry (the piece-hosting
        # worker for a fanout — survives speculation; wid does not)
        self.result_wid: Optional[int] = None


class _WorkerHandle:
    """One supervised worker slot (the slot identity survives respawns)."""

    __slots__ = ("wid", "proc", "sock", "state", "last_pong", "inflight",
                 "restarts", "deaths", "breaker", "send_lock", "ops_sent",
                 "rx_thread", "ledger_report", "pid", "tasks_done",
                 "telemetry_rx", "telemetry_dropped", "peer_addr",
                 "peer_report", "rs_report", "draining", "drained")

    def __init__(self, wid: int, breaker: WorkerHealth):
        self.wid = wid
        self.proc: Optional[subprocess.Popen] = None
        self.sock: Optional[socket.socket] = None
        self.state = "dead"  # ready | dead | spawning (elastic growth)
        self.last_pong = 0.0
        self.inflight: Dict[int, _TaskEntry] = {}
        self.restarts = 0
        self.deaths = 0
        self.breaker = breaker
        # serializes frames onto this worker's socket — held across
        # the send by design (interleaved frames would desync rx)
        self.send_lock = threading.Lock()  # daftlint: io-lock
        self.ops_sent: dict = {}  # insertion-ordered op-key window
        self.rx_thread: Optional[threading.Thread] = None
        self.ledger_report = {"current": 0, "high_water": 0}
        self.pid: Optional[int] = None
        self.tasks_done = 0
        # telemetry accounting for THIS incarnation (reset on respawn):
        # fragments received on replies vs the worker's pong-echoed tseq —
        # a positive gap is a fragment lost in flight (telemetry_dropped)
        self.telemetry_rx = 0
        self.telemetry_dropped = 0
        # peer-shuffle piece-server endpoint from the hello, and the
        # worker's pong-piggybacked piece-store snapshot (peerplane.py)
        self.peer_addr: Optional[Tuple[str, int]] = None
        self.peer_report: dict = {}
        # the worker's pong-piggybacked persistent-result-store report
        # (persist/resultstore.pong_report): hosted stable digests — the
        # driver's peer location map — plus tier counters
        self.rs_report: dict = {}
        # draining: quiescing on request (no new tasks; pieces still
        # served through the grace window); drained: the quiesce finished
        # — this slot's exit is NOT a worker loss
        self.draining = False
        self.drained = False


def _repo_root() -> str:
    import daft_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(
        daft_tpu.__file__)))


class WorkerPool:
    """Supervised pool of worker processes behind the scheduler's dispatch
    backend protocol (``capacity`` / ``try_execute``)."""

    def __init__(self, cfg):
        self.cfg = cfg
        self.n = max(1, int(cfg.distributed_workers))
        # elastic bounds: with BOTH set the supervision loop scales the
        # live worker count inside [n_min, n_max] (admission-queue depth +
        # dispatch waiters push up, sustained idleness drains down);
        # unset keeps the fixed-size pool semantics exactly
        wmin = getattr(cfg, "distributed_workers_min", None)
        wmax = getattr(cfg, "distributed_workers_max", None)
        self._elastic = wmin is not None and wmax is not None
        self.n_min = max(1, int(wmin)) if self._elastic else self.n
        self.n_max = max(self.n_min, int(wmax)) if self._elastic else self.n
        if self._elastic:
            self.n = min(max(self.n, self.n_min), self.n_max)
        # the knob values this pool was built for (get_worker_pool's
        # rebuild predicate — self.n drifts under elasticity)
        self._cfg_key = (cfg.distributed_workers, wmin, wmax,
                         cfg.memory_budget_bytes)
        self._cond = threading.Condition()
        self._closed = False
        self._token = secrets.token_hex(16)
        self._task_seq = itertools.count(1)
        # handshakes are serialized: concurrent spawns would steal each
        # other's hello candidates off the shared listener. A stolen but
        # VALID hello for another slot is parked (wid -> (conn, hello))
        # for that slot's spawner rather than closed — closing it would
        # kill the sibling's worker mid-handshake
        self._spawn_lock = threading.Lock()
        self._parked: Dict[int, tuple] = {}
        # pool-wide counters (the cluster health / gauge surface)
        self.worker_losses_total = 0
        self.task_redispatches_total = 0
        self.tasks_dispatched_total = 0
        self.tasks_completed_total = 0
        self.local_fallbacks_total = 0
        self.restarts_used = 0
        self.restart_budget = max(0, int(cfg.worker_restart_budget))
        # telemetry fragments lost pool-wide: pong-gap detections, lost
        # in-flight replies at worker death (driver-side merge drops are
        # per-query RuntimeStats counters, not pool state)
        self.telemetry_dropped_total = 0
        # peer-shuffle plane: live shuffle ids (dropped at query finish),
        # and every payload byte the DRIVER shipped or received over the
        # task channel — the star-vs-p2p flatness gate's numerator
        self._shuffle_seq = itertools.count(1)
        self._live_shuffles: set = set()
        self.driver_payload_bytes_total = 0
        # elastic controller state: wids never reuse (a recycled wid
        # would alias a fresh worker into old tasks' excluded sets)
        self._next_wid = itertools.count(self.n)
        self.workers_drained_total = 0
        self.scale_ups_total = 0
        self.scale_downs_total = 0
        self.last_scale_decision = "init"
        self._last_scale_at = 0.0
        self._idle_since = time.monotonic()
        self._acquire_waiters = 0
        self._scaling = False
        # speculative straggler mitigation: completed-wall history per op
        # (feeds the p75 threshold), the bounded count of duplicates in
        # flight, and the speculated/won totals
        self._op_walls: Dict[str, deque] = {}
        self._spec_inflight = 0
        self.tasks_speculated_total = 0
        self.speculation_wins_total = 0
        # transport frame checksums follow the integrity knob
        self._checksum = bool(getattr(cfg, "partition_integrity", True))
        # the listener the spawned workers dial back into
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(self.n_max + 4)
        self._port = self._listener.getsockname()[1]
        self._bthresh = max(1, int(cfg.device_breaker_threshold))
        self._bcool = float(cfg.device_breaker_cooldown_s)
        self.workers: List[_WorkerHandle] = [
            _WorkerHandle(i, WorkerHealth(self._bthresh, self._bcool))
            for i in range(self.n)]
        for w in self.workers:
            try:
                self._spawn(w, initial=True)
            except Exception as e:
                logger.warning("worker_initial_spawn_failed", worker=w.wid,
                               error=repr(e))
        self._supervisor = threading.Thread(
            target=self._supervise_loop, name="daft-dist-supervisor",
            daemon=True)
        self._supervisor.start()
        from ..obs.health import register_cluster

        register_cluster(self)

    # ------------------------------------------------------------- spawning
    def _worker_cfg(self):
        """The cfg a worker runs under: never nested-distributed, one
        executor thread (one task at a time), and a carved CHILD share of
        the global memory budget — the driver keeps one share, so all
        workers plus the driver together can never exceed it."""
        share = None
        if self.cfg.memory_budget_bytes is not None:
            # carve by the elastic CEILING so the budget invariant holds
            # at any scale without respawning the fleet on a resize
            share = max(1, self.cfg.memory_budget_bytes // (self.n_max + 1))
        return dataclasses.replace(
            self.cfg, distributed_workers=0, memory_budget_bytes=share,
            executor_threads=1, enable_query_log=False,
            enable_profiling=False, diagnostics_dir=None,
            slow_query_threshold_s=None)

    def _spawn(self, w: _WorkerHandle, initial: bool = False) -> None:
        """Spawn slot ``w``'s process and complete the handshake. Raises on
        failure (caller accounts budget/breaker); the ``worker.spawn``
        fault site fires per attempt."""
        from .. import faults

        with self._cond:
            if self._closed:
                raise DaftTransientError("worker pool is shut down")
        faults.check("worker.spawn")
        env = dict(os.environ)
        root = _repo_root()
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "daft_tpu.dist.worker",
             "127.0.0.1", str(self._port), str(w.wid), self._token],
            env=env, cwd=root, stdout=subprocess.DEVNULL)
        deadline = time.monotonic() + float(self.cfg.worker_spawn_timeout_s)
        sock = None
        try:
            while True:
                # _spawn_lock guards ONLY the parked-handshake dict (held
                # for dict ops, never across IO): concurrent spawners may
                # all block in accept() on the shared listener — the OS
                # hands each connection to exactly one of them, and a
                # spawner that accepts a sibling's worker parks it below
                with self._spawn_lock:
                    parked = self._parked.pop(w.wid, None)
                if parked is not None:
                    # a sibling spawner already accepted and validated our
                    # worker's hello off the shared listener
                    cand, hello = parked
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise DaftTransientError(
                            f"worker {w.wid} spawn timed out")
                    # short accept timeout: a handshake parked for us by a
                    # sibling must be discovered within a second
                    self._listener.settimeout(min(remaining, 1.0))
                    try:
                        cand, _ = self._listener.accept()
                    except socket.timeout:
                        if proc.poll() is not None:
                            raise DaftTransientError(
                                f"worker {w.wid} exited rc={proc.returncode}"
                                " before handshake")
                        continue
                    except OSError:
                        # listener closed under us: shutdown raced in
                        raise DaftTransientError(
                            "worker pool shut down during spawn")
                    # the handshake read gets its own deadline: a client
                    # that connects and never speaks must time out instead
                    # of wedging every subsequent spawn
                    cand.settimeout(
                        min(max(deadline - time.monotonic(), 0.1), 5.0))
                    try:
                        hello = recv_msg(cand)
                    except Exception:
                        cand.close()
                        continue
                    if (hello.get("type") == "hello"
                            and hello.get("proto") != PROTOCOL_VERSION):
                        # old-frame peer (pre-checksum protocol) or a
                        # version skew: reject at the handshake — mixed-
                        # version frames would desync, and unverified
                        # payloads defeat the end-to-end integrity contract
                        logger.warning("worker_proto_rejected", worker=w.wid,
                                       got=hello.get("proto"),
                                       want=PROTOCOL_VERSION)
                        cand.close()
                        continue
                if (hello.get("type") == "hello"
                        and hello.get("token") == self._token
                        and hello.get("worker_id") == w.wid):
                    sock = cand
                    break
                other = hello.get("worker_id") if (
                    hello.get("type") == "hello"
                    and hello.get("token") == self._token) else None
                if isinstance(other, int) and other != w.wid:
                    # a concurrent spawn's worker dialed in while we held
                    # the listener: park its handshake for that spawner
                    with self._spawn_lock:
                        stale = self._parked.pop(other, None)
                        self._parked[other] = (cand, hello)
                    if stale is not None:
                        try:
                            stale[0].close()
                        except OSError:
                            pass
                    continue
                cand.close()  # stale/foreign connection: not ours
            # back to a blocking socket before init/rx handoff: the
            # handshake deadline must not apply to task traffic
            sock.settimeout(None)
            send_msg(sock, {"type": "init", "cfg": self._worker_cfg()},
                     checksum=self._checksum)
        except BaseException:
            if sock is not None:
                sock.close()
            try:
                proc.kill()
                proc.wait(timeout=5)
            except Exception:
                pass
            raise
        with self._cond:
            if self._closed:
                # shutdown raced this spawn: shutdown() iterated the slots
                # before this worker existed, so nothing else will ever
                # reap it — kill it HERE or the zero-leak guarantee breaks
                closed = True
            else:
                closed = False
                w.proc = proc
                w.sock = sock
                w.pid = hello.get("pid")
                w.state = "ready"
                w.last_pong = time.monotonic()
                w.ops_sent = {}
                # a fresh incarnation's tseq starts at 0: reset the
                # per-incarnation telemetry accounting with it
                w.telemetry_rx = 0
                w.telemetry_dropped = 0
                peer_port = hello.get("peer_port")
                w.peer_addr = (("127.0.0.1", int(peer_port))
                               if peer_port else None)
                w.peer_report = {}
                w.rs_report = {}
                w.draining = False
                w.drained = False
                if not initial:
                    w.restarts += 1
                w.rx_thread = threading.Thread(
                    target=self._rx_loop, args=(w, sock),
                    name=f"daft-dist-rx-{w.wid}", daemon=True)
                w.rx_thread.start()
                self._cond.notify_all()
        if closed:
            try:
                sock.close()
            except OSError:
                pass
            try:
                proc.kill()
                proc.wait(timeout=5)
            except Exception:
                pass
            raise DaftTransientError("worker pool shut down during spawn")
        w.breaker.record_success()
        logger.info("worker_ready", worker=w.wid, pid=w.pid,
                    respawn=not initial)

    # ------------------------------------------------------------- receive
    def _rx_loop(self, w: _WorkerHandle, sock: socket.socket) -> None:
        try:
            while True:
                msg = recv_msg(sock)
                kind = msg.get("type")
                if kind == "pong":
                    with self._cond:
                        if w.sock is sock:
                            w.last_pong = time.monotonic()
                            w.ledger_report = msg.get("ledger",
                                                      w.ledger_report)
                            peer = msg.get("peer")
                            if isinstance(peer, dict):
                                w.peer_report = peer
                            rs = msg.get("rs")
                            if isinstance(rs, dict):
                                w.rs_report = rs
                            tseq = msg.get("tseq")
                            if isinstance(tseq, int):
                                # the worker attached tseq fragments ever;
                                # any it sent that never arrived (and were
                                # not already counted) were dropped in
                                # flight — fail-open means we COUNT them,
                                # never chase them
                                gap = (tseq - w.telemetry_rx
                                       - w.telemetry_dropped)
                                if gap > 0:
                                    w.telemetry_dropped += gap
                                    self.telemetry_dropped_total += gap
                elif kind == "draining":
                    # SIGTERM landed on the worker itself (spot
                    # preemption): it finishes its current task, keeps
                    # serving pieces through the grace window, then
                    # exits — from here on it takes no new work and its
                    # exit reads as a drain, not a loss
                    with self._cond:
                        if w.sock is sock and w.state == "ready":
                            w.draining = True
                            self._cond.notify_all()
                    logger.info("worker_draining", worker=w.wid,
                                reason="sigterm")
                elif kind in ("result", "task_error", "task_skipped"):
                    self._on_task_reply(w, sock, msg)
        except TransportClosed:
            self._on_worker_death(w, sock, "connection closed")
        except Exception as e:
            # includes DaftCorruptionError from a checksum-failed frame:
            # a corrupt link is a dead link — re-dispatch owns recovery
            self._on_worker_death(w, sock, f"receiver failed: {e!r}")

    def _on_task_reply(self, w: _WorkerHandle, sock, msg: dict) -> None:
        cancel_targets: List[_WorkerHandle] = []
        reply_pc = time.perf_counter_ns()
        with self._cond:
            if w.sock is not sock:
                return  # a dead incarnation's straggler frame
            frag = msg.get("telemetry")
            if frag is not None:
                # counted on ARRIVAL (even a discarded speculative loser's
                # fragment arrived fine) so the pong-gap math only ever
                # flags frames that truly never made it
                w.telemetry_rx += 1
            entry = w.inflight.pop(msg["task_id"], None)
            if entry is None:
                return
            entry.active_wids.discard(w.wid)
            if msg["type"] == "task_skipped" or entry.status != "inflight":
                # a cancelled speculative loser (skipped before it started,
                # or its late result after the winner settled): the pop
                # above frees the slot; exactly-once — never re-applied
                self._cond.notify_all()
                return
            if msg["type"] == "result":
                entry.status = "done"
                entry.result = (msg["part"], msg["rows"], msg["wall_ns"])
                entry.result_wid = w.wid
                entry.frag = frag
                entry.frag_wid = w.wid
                entry.reply_pc = reply_pc
                w.tasks_done += 1
                self.tasks_completed_total += 1
                # feed the straggler threshold's running distribution
                self._op_walls.setdefault(
                    entry.op_name, deque(maxlen=_WALL_HISTORY)).append(
                    msg["wall_ns"] / 1e9)
            else:
                if entry.active_wids:
                    # another runner of this entry is still executing
                    # (speculation): DROP the failed runner instead of
                    # settling — "first result wins" means first RESULT,
                    # not first reply, and an erroring duplicate must
                    # never cancel healthy in-flight work (nor count as
                    # a speculation win)
                    if entry.spec_wid == w.wid:
                        entry.spec_wid = None
                        self._spec_inflight -= 1
                    elif entry.spec_wid is not None:
                        # the primary failed: the duplicate is now the
                        # worker of record
                        entry.wid = entry.spec_wid
                        entry.spec_wid = None
                        self._spec_inflight -= 1
                    self._cond.notify_all()
                    return
                err = None
                if msg.get("error") is not None:
                    try:
                        err = pickle.loads(msg["error"])
                    except Exception:
                        err = None
                if not isinstance(err, BaseException):
                    err = DaftError(
                        f"worker task failed: {msg.get('error_type')}: "
                        f"{msg.get('error_message')}")
                entry.status = "error"
                entry.error = err
                entry.frag = frag
                entry.frag_wid = w.wid
                entry.reply_pc = reply_pc
            spec_win = False
            if entry.spec_wid is not None:
                # a speculated entry settled: first result wins, the
                # still-running dispatch is the loser — cancel it (frees
                # its worker's queue slot if the task never started; a
                # mid-execution loser finishes and its result is dropped
                # by the exactly-once guard above)
                spec_win = (w.wid == entry.spec_wid)
                entry.spec_wid = None
                self._spec_inflight -= 1
                if spec_win:
                    self.speculation_wins_total += 1
                cancel_targets = [ow for ow in self.workers
                                  if ow.wid in entry.active_wids
                                  and ow.sock is not None]
            if entry.charged:
                entry.ctx.ledger.dist_done(entry.charged)
                entry.charged = 0
            self._cond.notify_all()
        if entry.status == "done":
            w.breaker.record_success()
        if spec_win:
            entry.ctx.stats.bump("speculation_wins")
            logger.warning("speculation_win", op=entry.op_name,
                           seq=entry.seq, worker=w.wid)
        for ow in cancel_targets:
            try:
                with ow.send_lock:
                    send_msg(ow.sock, {"type": "cancel",
                                       "task_id": entry.task_id},
                             checksum=self._checksum)
            except Exception:
                pass  # a dead loser settles through the death path
        entry.event.set()

    # ------------------------------------------------------------ death
    def _kill_worker(self, w: _WorkerHandle, reason: str) -> None:
        """SIGKILL the slot's process (the injected ``worker.exec`` chaos
        hook and the shutdown straggler path), then run the death flow."""
        with self._cond:
            proc, sock = w.proc, w.sock
        if proc is not None and proc.poll() is None:
            try:
                os.kill(proc.pid, signal.SIGKILL)
            except OSError:
                pass
        self._on_worker_death(w, sock, reason)

    def _on_worker_death(self, w: _WorkerHandle, sock, reason: str) -> None:
        """Declare slot ``w`` dead: reap the process, mark in-flight tasks
        lost (their waiters re-dispatch), inform the breaker and the
        per-query counters. Idempotent per incarnation."""
        with self._cond:
            if w.state != "ready" or (sock is not None and w.sock is not sock):
                # a stale incarnation's death (the slot already moved on):
                # still close ITS socket, or the rx thread that reported the
                # death stays blocked in recv() forever
                if sock is not None and sock is not w.sock:
                    try:
                        sock.close()
                    except OSError:
                        pass
                return
            if self._closed:
                # drain-mode shutdown: the worker exiting on request is not
                # a loss (no breaker failure, no counters, no warning)
                w.state = "dead"
                w.sock = None
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                return
            w.state = "dead"
            drained = w.draining
            if drained:
                # a graceful quiesce completing (drain_worker / SIGTERM):
                # no new tasks landed since the draining mark, peers had
                # the grace window to finish fetching, and its remaining
                # pieces re-source through lineage at the read site —
                # this exit is paid-for, not a failure (no breaker hit,
                # no worker_losses)
                w.draining = False
                w.drained = True
                self.workers_drained_total += 1
            else:
                w.deaths += 1
            dead_sock, proc = w.sock, w.proc
            w.sock = None
            entries = []
            for e in w.inflight.values():
                if e.status != "inflight":
                    continue  # a settled speculative loser parked here
                e.active_wids.discard(w.wid)
                if e.active_wids:
                    # a speculative duplicate (or the primary) of this
                    # entry is still running on another worker: the entry
                    # SURVIVES this death — exactly what the duplicate
                    # was dispatched to buy
                    if e.spec_wid == w.wid:
                        e.spec_wid = None
                        self._spec_inflight -= 1
                    elif e.spec_wid is not None:
                        # the primary died: the duplicate is now the
                        # worker of record (exclusion on a later loss)
                        e.wid = e.spec_wid
                        e.spec_wid = None
                        self._spec_inflight -= 1
                    continue
                if e.spec_wid is not None:
                    e.spec_wid = None
                    self._spec_inflight -= 1
                entries.append(e)
            w.inflight.clear()
            if not drained:
                self.worker_losses_total += 1
            affected = {}
            for e in entries:
                e.status = "lost"
                if e.charged:
                    e.ctx.ledger.dist_done(e.charged)
                    e.charged = 0
                affected[id(e.ctx)] = e.ctx
                if getattr(e.ctx.cfg, "cluster_telemetry", True):
                    # the in-flight task's would-be fragment died with the
                    # worker: counted, never chased — and the driver-side
                    # span around the remote wait still closes, so a lost
                    # fragment can never orphan a driver span
                    self.telemetry_dropped_total += 1
            self._cond.notify_all()
        if proc is not None and proc.poll() is None:
            try:
                proc.kill()
            except OSError:
                pass
        if proc is not None:
            try:
                proc.wait(timeout=5)
            except Exception:
                pass
        if dead_sock is not None:
            try:
                dead_sock.close()
            except OSError:
                pass
        if drained:
            # every query that lived through the drain records it (the
            # QueryRecord workers_drained event counter)
            from ..obs.cluster import active_query_stats

            for st in active_query_stats():
                st.bump("workers_drained")
            for e in entries:
                e.event.set()
            logger.info("worker_drained", worker=w.wid, reason=reason,
                        raced_inflight=len(entries))
            return
        w.breaker.record_failure()
        for ctx in affected.values():
            ctx.stats.bump("worker_losses")
        for e in entries:
            if getattr(e.ctx.cfg, "cluster_telemetry", True):
                e.ctx.stats.bump("telemetry_dropped")
        for e in entries:
            e.event.set()
        logger.warning("worker_lost", worker=w.wid, reason=reason,
                       inflight=len(entries))

    # ------------------------------------------------------- supervision
    def _supervise_loop(self) -> None:
        from .. import faults

        interval = max(0.05, float(self.cfg.worker_heartbeat_interval_s))
        timeout = max(float(self.cfg.worker_heartbeat_timeout_s),
                      2 * interval)
        while True:
            with self._cond:
                if self._closed:
                    return
            time.sleep(interval)
            self._elastic_step()
            with self._cond:
                fleet = list(self.workers)
            for w in fleet:
                with self._cond:
                    if self._closed:
                        return
                    state, sock, proc = w.state, w.sock, w.proc
                    stale = (state == "ready"
                             and time.monotonic() - w.last_pong > timeout)
                    if state == "dead" and w.drained:
                        continue  # a drained slot is retired, not sick
                if state == "ready":
                    if proc is not None and proc.poll() is not None:
                        self._on_worker_death(
                            w, sock, f"process exited rc={proc.returncode}")
                        continue
                    try:
                        faults.check("worker.heartbeat")
                    except DaftTransientError:
                        # injected missed-deadline: the supervision layer
                        # must behave exactly as if the worker went silent
                        self._kill_worker(w, "heartbeat fault injected")
                        continue
                    if stale:
                        self._kill_worker(w, "heartbeat deadline missed")
                        continue
                    try:
                        with w.send_lock:
                            send_msg(sock, {"type": "ping"},
                                     checksum=self._checksum)
                    except Exception as e:
                        self._on_worker_death(w, sock, f"ping failed: {e!r}")
                elif state == "dead":
                    self._maybe_respawn(w)

    def _maybe_respawn(self, w: _WorkerHandle) -> None:
        with self._cond:
            if self._closed or self.restarts_used >= self.restart_budget:
                return
            if not w.breaker.allow():
                return  # tripped: wait out the cooldown probe
            self.restarts_used += 1  # the attempt consumes budget, not success
        try:
            self._spawn(w)
        except Exception as e:
            w.breaker.record_failure()
            logger.warning("worker_respawn_failed", worker=w.wid,
                           error=repr(e),
                           budget_remaining=self.budget_remaining())
            if self.budget_remaining() <= 0:
                logger.error("worker_pool_degraded",
                             reason="restart budget exhausted",
                             losses=self.worker_losses_total)

    def budget_remaining(self) -> int:
        with self._cond:
            return max(0, self.restart_budget - self.restarts_used)

    # ----------------------------------------------------------- elastic
    def _elastic_step(self) -> None:
        """One scale decision per ``elastic_scale_interval_s``: demand =
        admission-queue depth + busy workers + dispatch waiters. Pressure
        grows the fleet toward ``n_max`` (a WARM FDO history — this
        process has completed queries before, so the traffic shape is
        known — jumps straight to max; a cold pool steps by one);
        fleet-wide idleness past ``elastic_idle_scale_down_s`` gracefully
        DRAINS one worker down toward ``n_min``. Drained/retired slots
        are pruned; fresh slots get never-reused wids."""
        if not self._elastic:
            return
        now = time.monotonic()
        interval = max(0.05, float(getattr(
            self.cfg, "elastic_scale_interval_s", 0.5)))
        if now - self._last_scale_at < interval:
            return
        self._last_scale_at = now
        try:
            from ..obs.health import admission_state

            queued = int((admission_state() or {}).get(
                "queued_queries", 0) or 0)
        except Exception:
            queued = 0
        with self._cond:
            if self._closed or self._scaling:
                return
            retired = [w for w in self.workers
                       if w.drained and w.state == "dead"]
            for w in retired:
                self.workers.remove(w)
            if retired:
                self.n = len(self.workers)
            live = [w for w in self.workers if not w.draining
                    and not w.drained]
            busy = sum(1 for w in live if w.inflight)
            demand = queued + busy + self._acquire_waiters
            n_live = len(live)
            grow = min(self.n_max - n_live,
                       max(demand - n_live, self.n_min - n_live))
            if grow > 0:
                if grow > 1 or demand > n_live:
                    # scaling UP under real pressure: with warm FDO
                    # history the traffic shape is a known repeat — jump;
                    # cold, step by one and let the next tick re-decide
                    try:
                        from ..adapt.history import HISTORY

                        warm = HISTORY.snapshot().get("queries", 0) > 0
                    except Exception:
                        warm = False
                    if not warm:
                        grow = min(grow, max(1, self.n_min - n_live))
                new = []
                for _ in range(grow):
                    w = _WorkerHandle(next(self._next_wid),
                                      WorkerHealth(self._bthresh,
                                                   self._bcool))
                    # "spawning", not the default "dead": the supervise
                    # loop would otherwise race a budgeted respawn of this
                    # slot against the scale-up thread's spawn — two
                    # processes for one wid, the loser's socket orphaned
                    w.state = "spawning"
                    self.workers.append(w)
                    new.append(w)
                self.n = len(self.workers)
                self.scale_ups_total += 1
                self.last_scale_decision = (
                    f"up+{len(new)} (queued={queued} busy={busy} "
                    f"waiters={self._acquire_waiters})")
                self._idle_since = now
                self._scaling = True
            elif (demand == 0 and n_live > self.n_min
                    and now - self._idle_since > float(getattr(
                        self.cfg, "elastic_idle_scale_down_s", 10.0))):
                # sustained idleness: gracefully retire ONE worker per
                # decision (prefer the emptiest piece store — its drain
                # strands the least to re-source)
                idle = [w for w in live if w.state == "ready"]
                if not idle:
                    return
                victim = min(idle, key=lambda h: (
                    h.peer_report.get("pieces_hosted", 0), h.tasks_done))
                self.scale_downs_total += 1
                self.last_scale_decision = f"down-1 (drain w{victim.wid})"
                self._idle_since = now
                self._scaling = True
                new = None
            else:
                if demand > 0:
                    self._idle_since = now
                return
        if new:
            def _grow_fleet(handles=new):
                try:
                    for w in handles:
                        try:
                            # fleet growth is capacity we asked for, not
                            # failure recovery: initial=True keeps it off
                            # the restart budget
                            self._spawn(w, initial=True)
                        except Exception as e:
                            with self._cond:
                                if w.state == "spawning":
                                    # hand the slot to the supervise
                                    # loop's budgeted respawn path
                                    w.state = "dead"
                            logger.warning("elastic_spawn_failed",
                                           worker=w.wid, error=repr(e))
                finally:
                    with self._cond:
                        self._scaling = False

            threading.Thread(target=_grow_fleet, daemon=True,
                             name="daft-dist-scale-up").start()
            logger.info("elastic_scale_up", count=len(new),
                        queued=queued, busy=busy)
        else:
            def _shrink_fleet(wid=victim.wid):
                try:
                    self.drain_worker(wid)
                finally:
                    with self._cond:
                        self._scaling = False

            threading.Thread(target=_shrink_fleet, daemon=True,
                             name="daft-dist-scale-down").start()
            logger.info("elastic_scale_down", worker=victim.wid)

    def drain_worker(self, wid: int) -> bool:
        """Gracefully quiesce one worker: stop routing tasks to it, wait
        out its in-flight work, then ask it to exit after the piece-serve
        grace window — a preemption that costs bounded recompute, never a
        failed query. The ``worker.drain`` fault site fires here; an
        injected fault (and a drain that times out) degrades to the
        SIGKILL/redispatch path, which the loss machinery already owns.
        Returns True when the worker exited as a drain."""
        from .. import faults

        with self._cond:
            w = next((x for x in self.workers if x.wid == wid), None)
            if w is None or w.state != "ready" or w.draining:
                return False
            w.draining = True
            self._cond.notify_all()
        logger.info("worker_drain_requested", worker=wid)
        try:
            faults.check("worker.drain")
        except DaftTransientError:
            with self._cond:
                w.draining = False
            self._kill_worker(w, "worker.drain fault injected")
            return False
        deadline = time.monotonic() + float(getattr(
            self.cfg, "worker_drain_timeout_s", 10.0))
        with self._cond:
            while (w.inflight and w.state == "ready"
                    and time.monotonic() < deadline):
                self._cond.wait(0.05)
            still_busy = bool(w.inflight) and w.state == "ready"
            sock, alive = w.sock, w.state == "ready"
        if still_busy:
            # its in-flight task outlived the drain window: this is the
            # bounded part of "bounded recompute" — kill and re-dispatch
            with self._cond:
                w.draining = False
            self._kill_worker(w, "drain timed out with task in flight")
            return False
        if not alive:
            return bool(w.drained)  # died mid-drain; death flow decided
        try:
            with w.send_lock:
                send_msg(sock, {"type": "drain"},
                         checksum=self._checksum)
        except Exception:
            pass  # a dead link settles through the death path
        grace = float(getattr(self.cfg, "worker_drain_grace_s", 2.0))
        exit_deadline = time.monotonic() + grace + max(
            5.0, float(getattr(self.cfg, "worker_drain_timeout_s", 10.0)))
        with self._cond:
            while w.state == "ready" and time.monotonic() < exit_deadline:
                self._cond.wait(0.1)
            alive = w.state == "ready"
        if alive:
            with self._cond:
                w.draining = False
            self._kill_worker(w, "drain grace expired without exit")
            return False
        return bool(w.drained)

    # --------------------------------------------------- dispatch backend
    def capacity(self) -> int:
        return self.n

    def _usable_locked(self) -> bool:
        if self._closed:
            return False
        if any(w.state == "ready" for w in self.workers):
            return True
        return self.restarts_used < self.restart_budget

    def _op_payload(self, op) -> Optional[Tuple[int, bytes]]:
        """(op_key, pickled map op with children stripped), cached on the
        op; None when the op cannot cross a process boundary (UDF closures
        and the like) — the task runs in-process instead. The key comes
        from a process-wide counter, NOT id(op): address reuse after GC
        would alias a new op to a dead op's worker-side cache entry."""
        cached = getattr(op, "_dist_payload", False)
        if cached is not False:
            return cached
        import copy

        try:
            clone = copy.copy(op)
            clone.children = []
            payload = (next(_OP_SEQ), pickle.dumps(
                clone, protocol=pickle.HIGHEST_PROTOCOL))
        except Exception:
            payload = None
        try:
            op._dist_payload = payload
        except Exception:
            pass
        return payload

    @staticmethod
    def _part_eligible(part) -> bool:
        # deferred op chains are driver-side closures; loaded tables and
        # plain scan tasks ship fine (the worker reads the file itself)
        return not getattr(part, "_pending", None)

    def try_execute(self, op, part, ctx, op_name: str, seq: int):
        """Execute one map task on a worker, blocking until a terminal
        result. Returns ``(out_partition, rows, wall_ns)`` or None when the
        task is ineligible / the pool is degraded (caller runs it
        in-process). Raises the task's real error, the poison-task
        DaftError, or the query's cancellation/timeout."""
        if getattr(op, "map_partition", None) is None:
            return None
        payload = self._op_payload(op)
        if payload is None or not self._part_eligible(part):
            return None
        with self._cond:
            if not self._usable_locked():
                self.local_fallbacks_total += 1
                ctx.stats.bump("dist_local_fallbacks")
                return None
        try:
            # serialize ONCE, up front: an unshippable partition (driver-
            # local prefetch state, exotic scan factories) is a decline,
            # never a worker death — and re-dispatches reuse the bytes
            part_bytes = pickle.dumps(part,
                                      protocol=pickle.HIGHEST_PROTOCOL)
        except Exception:
            return None
        from .peerplane import peer_preference

        # persistent result tier: address this task's output (stable
        # digest + exact task key) and name up to two peers whose pongs
        # report the digest — the worker serves locally, peer-fetches, or
        # executes + write-throughs. None = plain task (fail-open).
        extra = None
        try:
            from ..persist.resultstore import task_meta

            rs = task_meta(op, part, ctx.cfg)
            if rs is not None:
                rs["peers"] = self._rs_peers(rs["sd"])
                extra = {"rs": rs}
        except Exception:
            extra = None
        try:
            return self._execute(payload, part_bytes, ctx, op_name, seq,
                                 extra=extra,
                                 prefer=peer_preference(part))
        except _LocalFallback:
            with self._cond:
                self.local_fallbacks_total += 1
            ctx.stats.bump("dist_local_fallbacks")
            return None

    def _rs_peers(self, sd: str) -> list:
        """Worker slots whose last pong reported hosting this stable
        digest: ``(wid, host, port)`` rows for the task envelope (top
        two — one fetch normally suffices; the second is the dead-peer
        fallback)."""
        out = []
        with self._cond:
            for w in self.workers:
                if w.state != "ready" or w.peer_addr is None:
                    continue
                if sd in (w.rs_report.get("digests") or ()):
                    out.append((w.wid, w.peer_addr[0], w.peer_addr[1]))
                if len(out) >= 2:
                    break
        return out

    def execute_fanout(self, part, spec: dict, ctx, op_name: str,
                       seq: int):
        """Dispatch one peer-shuffle FANOUT task: the worker splits the
        source partition and parks the pieces in its local store
        (peerplane.execute_fanout); only piece metadata comes back.
        Returns ``(wid, (host, port), metas)`` naming the hosting slot,
        or None when the pool declines (the caller splits driver-side).
        Rides the whole _execute machinery, so re-dispatch, speculation,
        and exactly-once settle compose: a worker dying mid-fanout just
        re-stores the same deterministic pieces elsewhere."""
        if not self._part_eligible(part):
            return None
        with self._cond:
            if not self._usable_locked():
                self.local_fallbacks_total += 1
                ctx.stats.bump("dist_local_fallbacks")
                return None
        try:
            part_bytes = pickle.dumps(part,
                                      protocol=pickle.HIGHEST_PROTOCOL)
        except Exception:
            return None
        from .peerplane import peer_preference

        try:
            metas, _rows, _wall = self._execute(
                None, part_bytes, ctx, op_name, seq,
                extra={"shuffle": spec}, prefer=peer_preference(part))
        except _LocalFallback:
            with self._cond:
                self.local_fallbacks_total += 1
            ctx.stats.bump("dist_local_fallbacks")
            return None
        return metas

    def _execute(self, payload, part_bytes, ctx, op_name: str, seq: int,
                 extra: Optional[dict] = None,
                 prefer: Optional[set] = None):
        entry = _TaskEntry(next(self._task_seq), op_name, seq, ctx)
        entry.extra = extra
        entry.prefer = prefer
        max_attempts = max(1, int(self.cfg.dist_task_max_attempts))
        while True:
            self._check_query(ctx)
            w = self._acquire_worker(entry, ctx)
            self._dispatch(entry, w, payload, part_bytes)
            self._wait(entry, ctx, payload, part_bytes)
            if entry.status == "done":
                out, rows, wall_ns = entry.result
                self._finish_telemetry(entry, ctx)
                ctx.stats.bump("dist_tasks")
                if extra is not None and "shuffle" in extra:
                    # resolve the hosting slot's piece-server endpoint:
                    # the pieces live on whichever worker's result
                    # settled the entry (speculation-proof)
                    with self._cond:
                        host = next((h for h in self.workers
                                     if h.wid == entry.result_wid), None)
                        addr = host.peer_addr if host is not None else None
                    if addr is None:
                        return None, rows, wall_ns
                    return (entry.result_wid, addr, out), rows, wall_ns
                rbytes = 0
                try:
                    rbytes = out.size_bytes() or 0
                except Exception:
                    rbytes = 0
                if rbytes:
                    # the reply payload transited the driver too: the
                    # other half of the star topology's O(cluster) bill
                    with self._cond:
                        self.driver_payload_bytes_total += rbytes
                    ctx.stats.bump("dist_driver_bytes", rbytes)
                return out, rows, wall_ns
            if entry.status == "error":
                # task_error replies piggyback telemetry too — the failing
                # task's counters/spans/logs are exactly the ones worth
                # having when queries get hard to debug
                self._finish_telemetry(entry, ctx)
                raise entry.error
            # lost: the worker died with this task in flight
            if entry.wid is not None:
                entry.excluded.add(entry.wid)
            with self._cond:
                live = {w.wid for w in self.workers if not w.drained}
            if ((live and entry.excluded >= live)
                    or entry.attempts >= max_attempts):
                # terminal: no further dispatch happens, so this loss is
                # NOT a re-dispatch — counting it here would over-report
                raise DaftError(
                    f"poison task {op_name}#{seq}: lost "
                    f"{entry.attempts} worker(s) "
                    f"(excluded slots {sorted(entry.excluded)}) — "
                    "refusing further re-dispatch")
            ctx.stats.bump("task_redispatches")
            with self._cond:
                self.task_redispatches_total += 1
            logger.warning("task_redispatch", op=op_name, seq=seq,
                           attempts=entry.attempts,
                           excluded=sorted(entry.excluded))

    def _finish_telemetry(self, entry: _TaskEntry, ctx) -> None:
        """Terminal-reply observability, on the query thread while the
        ``dist.remote`` span run_map_task opened is still this thread's
        innermost: stamp the driver-side phase split (submit -> sent ->
        reply — visible even when the worker's fragment was lost) and
        merge the piggybacked telemetry fragment (obs/cluster.py;
        strictly fail-open)."""
        prof = ctx.stats.profiler
        if prof.armed:
            sp = prof.current()
            if sp is not None:
                if entry.sent_pc and entry.submit_pc:
                    sp.add_phase("submit",
                                 max(0, entry.sent_pc - entry.submit_pc))
                if entry.reply_pc and entry.sent_pc:
                    sp.add_phase("remote_wait",
                                 max(0, entry.reply_pc - entry.sent_pc))
                sp.set_attr("worker", entry.frag_wid
                            if entry.frag_wid is not None else entry.wid)
                sp.set_attr("attempts", entry.attempts)
        if entry.frag is not None:
            from ..obs.cluster import merge_fragment

            frag, entry.frag = entry.frag, None
            merge_fragment(ctx, frag, entry.frag_wid
                           if entry.frag_wid is not None else -1)

    def _check_query(self, ctx) -> None:
        from ..execution import QueryCancelledError

        if ctx.stats.is_cancelled():
            raise QueryCancelledError(
                "query cancelled (distributed task)")
        ctx.check_deadline()

    def _acquire_worker(self, entry: _TaskEntry, ctx) -> _WorkerHandle:
        """Reserve a ready worker slot outside the task's excluded set
        (capacity one task per worker). Blocks until one frees up; raises
        _LocalFallback when the pool can no longer serve, and detects
        poison-by-exclusion without waiting."""
        while True:
            with self._cond:
                live = {w.wid for w in self.workers if not w.drained}
                if live and entry.excluded >= live:
                    raise DaftError(
                        f"poison task {entry.op_name}#{entry.seq}: lost "
                        f"{entry.attempts} worker(s) (every slot excluded)"
                        " — refusing further re-dispatch")
                if not self._usable_locked():
                    raise _LocalFallback
                ready = [w for w in self.workers
                         if w.state == "ready"
                         and not w.draining
                         and w.wid not in entry.excluded
                         and not w.inflight]
                if ready:
                    if entry.prefer:
                        # peer locality: a free slot already hosting this
                        # task's input pieces wins (fetches become local
                        # store reads); otherwise any free slot serves
                        hosts = [w for w in ready
                                 if w.wid in entry.prefer]
                        if hosts:
                            ready = hosts
                    w = min(ready, key=lambda h: h.tasks_done)
                    entry.status = "inflight"
                    entry.event.clear()
                    entry.wid = w.wid
                    entry.active_wids = {w.wid}
                    entry.spec_wid = None
                    w.inflight[entry.task_id] = entry
                    return w
                # nothing to wait FOR: no candidate slot is serving (ready
                # or finishing a task) and none can come back soon — every
                # dead candidate is budget-blocked or breaker-tripped
                # (waiting out a 30s cooldown would stall the query while
                # in-process execution is available). An elastic pool
                # below its ceiling is worth waiting on: the waiter count
                # below IS the scale-up controller's demand signal.
                candidates = [w for w in self.workers
                              if w.wid not in entry.excluded
                              and not w.draining and not w.drained]
                revivable = (self.restarts_used < self.restart_budget)
                respawn_pending = revivable and any(
                    w.state == "dead" and w.breaker.state != "open"
                    for w in candidates)
                headroom = self._elastic and len(
                    [w for w in self.workers
                     if not w.draining and not w.drained]) < self.n_max
                if (not any(w.state == "ready" or w.inflight
                            for w in candidates)
                        and not respawn_pending and not headroom):
                    raise _LocalFallback
                self._acquire_waiters += 1
                try:
                    self._cond.wait(0.05)
                finally:
                    self._acquire_waiters -= 1
            self._check_query(ctx)

    def _dispatch(self, entry: _TaskEntry, w: _WorkerHandle, payload,
                  part_bytes: bytes, speculative: bool = False) -> None:
        from .. import faults

        # payload None = a peer-shuffle fanout (no map op crosses the
        # wire; entry.extra carries the split spec instead)
        op_key, op_bytes = payload if payload is not None else (None, b"")
        if not speculative:
            # a speculative duplicate is added capacity for the SAME
            # attempt: it must not consume the poison-task budget, and the
            # straggler clock keeps timing the original dispatch
            entry.attempts += 1
            entry.dispatched_at = time.monotonic()
            entry.submit_pc = time.perf_counter_ns()
        with self._cond:
            self.tasks_dispatched_total += 1
        try:
            faults.check("worker.exec", entry.ctx.stats)
        except DaftTransientError:
            # the chaos contract: an injected worker.exec fault IS a worker
            # loss — SIGKILL the process for real and let the re-dispatch
            # machinery (the thing under test) pick up the pieces
            self._kill_worker(w, "worker.exec fault injected")
            return
        with self._cond:
            # the worker may have died between acquire and here: its death
            # handler already marked the entry lost and settled any charge
            # — charging after that point would leak ledger bytes
            if entry.status != "inflight" or w.sock is None:
                if speculative:
                    # the entry settled (or this worker died) before the
                    # duplicate's frame ever left: unwind the reservation,
                    # or the slot would wait forever for a reply that can
                    # never come
                    w.inflight.pop(entry.task_id, None)
                    entry.active_wids.discard(w.wid)
                    if entry.spec_wid == w.wid:
                        entry.spec_wid = None
                        self._spec_inflight -= 1
                return
            sock = w.sock
            size = len(part_bytes)
            if size and not entry.charged:
                # charged once per entry, not per duplicate: the driver
                # ships the same payload twice but holds it once
                entry.charged = size
                # daftlint: ledger-escape settled-by=_on_task_reply,_on_worker_death,shutdown
                entry.ctx.ledger.dist_started(size)
        msg = {"type": "task", "task_id": entry.task_id,
               "part": part_bytes}
        if payload is not None:
            msg["op_key"] = op_key
        if entry.extra:
            msg.update(entry.extra)
        if getattr(entry.ctx.cfg, "cluster_telemetry", True):
            # the span-context propagation half of the telemetry plane:
            # the task envelope carries the query id (log attribution),
            # the dispatching op's identity (the splice anchor names it),
            # and whether the driver's query is profiled (the worker arms
            # a local profiler only then — unprofiled queries piggyback
            # counters + log tail only)
            from ..obs.log import current_query_id

            msg["telemetry"] = True
            msg["query_id"] = current_query_id()
            msg["op_name"] = entry.op_name
            msg["seq"] = entry.seq
            msg["profile"] = bool(entry.ctx.stats.profiler.armed)
        wire = len(part_bytes)
        if payload is not None and op_key not in w.ops_sent:
            msg["op"] = op_bytes
            wire += len(op_bytes)
        try:
            with w.send_lock:
                send_msg(sock, msg, checksum=self._checksum)
            if not speculative:
                entry.sent_pc = time.perf_counter_ns()
            with self._cond:
                self.driver_payload_bytes_total += wire
            entry.ctx.stats.bump("dist_driver_bytes", wire)
            if payload is not None:
                # insertion-ordered window, capped BELOW the worker's op
                # cache so a key we omit op bytes for is always still
                # cached there
                w.ops_sent[op_key] = True
                while len(w.ops_sent) > 96:
                    w.ops_sent.pop(next(iter(w.ops_sent)))
        except Exception as e:
            self._on_worker_death(w, sock, f"task send failed: {e!r}")

    def _wait(self, entry: _TaskEntry, ctx, payload,
              part_bytes: bytes) -> None:
        """Block until the entry is terminal, keeping the query's
        cancellation/deadline semantics live while the work is remote —
        and watching for straggling: an entry past the speculation
        threshold gets a duplicate dispatched to a different worker. A
        query that dies here disowns the entry; a late result (or the
        worker's death) settles it without a waiter, exactly once."""
        while not entry.event.wait(0.05):
            self._check_query(ctx)
            self._maybe_speculate(entry, ctx, payload, part_bytes)

    def _maybe_speculate(self, entry: _TaskEntry, ctx, payload,
                         part_bytes: bytes) -> None:
        """Speculative straggler mitigation: when this entry has been
        running longer than ``speculation_quantile_factor`` x the op's
        running p75 completed wall (floor ``speculation_min_s``), dispatch
        a duplicate to a different idle worker. First result wins through
        the exactly-once ack ledger, the loser is cancelled, and
        pool-wide duplicates are bounded by ``speculation_max_inflight``
        so a sick fleet cannot double its own load."""
        # speculation knobs are PER-QUERY semantics: read the query's own
        # config, not the pool's spawn-time snapshot
        cfg = ctx.cfg
        if not getattr(cfg, "speculative_execution", True):
            return
        with self._cond:
            if (self._closed or entry.status != "inflight"
                    or entry.spec_wid is not None):
                return
            hist = self._op_walls.get(entry.op_name)
            if hist is None or len(hist) < _SPECULATION_MIN_SAMPLES:
                return
            walls = sorted(hist)
            p75 = walls[min(len(walls) - 1, (3 * len(walls)) // 4)]
            threshold = max(
                float(getattr(cfg, "speculation_min_s", 1.0)),
                float(getattr(cfg, "speculation_quantile_factor", 3.0))
                * p75)
            if time.monotonic() - entry.dispatched_at < threshold:
                return
            if self._spec_inflight >= max(
                    0, int(getattr(cfg, "speculation_max_inflight", 2))):
                return
            cands = [w for w in self.workers
                     if w.state == "ready" and not w.inflight
                     and w.wid not in entry.active_wids
                     and w.wid not in entry.excluded]
            if not cands:
                return
            w = min(cands, key=lambda h: h.tasks_done)
            entry.spec_wid = w.wid
            entry.active_wids.add(w.wid)
            w.inflight[entry.task_id] = entry
            self._spec_inflight += 1
            self.tasks_speculated_total += 1
        ctx.stats.bump("tasks_speculated")
        logger.warning("task_speculated", op=entry.op_name, seq=entry.seq,
                       worker=w.wid, threshold_s=round(threshold, 3))
        self._dispatch(entry, w, payload, part_bytes, speculative=True)

    # ------------------------------------------------------- peer plane
    def new_shuffle_id(self) -> int:
        """A fresh pool-unique shuffle id; registered live until its
        query's finish broadcasts the drop."""
        sid = next(self._shuffle_seq)
        with self._cond:
            self._live_shuffles.add(sid)
        return sid

    def peer_token(self) -> str:
        return self._token

    def peer_ready(self) -> bool:
        """Any ready worker with a piece-server endpoint? (The p2p branch
        stands down to the star path otherwise.)"""
        with self._cond:
            return any(w.state == "ready" and not w.draining
                       and w.peer_addr is not None
                       for w in self.workers)

    def drop_shuffles(self, sids) -> None:
        """Broadcast end-of-life for the given shuffle ids: every worker
        (and the driver's own store) frees the hosted pieces. Fire-and-
        forget — a worker that misses the drop frees at process exit."""
        sids = [s for s in sids]
        if not sids:
            return
        from .peerplane import plane

        plane().drop_shuffles(sids)
        with self._cond:
            for s in sids:
                self._live_shuffles.discard(s)
            targets = [w for w in self.workers
                       if w.state == "ready" and w.sock is not None]
        for w in targets:
            try:
                with w.send_lock:
                    send_msg(w.sock, {"type": "drop_shuffles",
                                      "ids": sids},
                             checksum=self._checksum)
            except Exception:
                pass  # a dead worker's pieces died with it

    # ------------------------------------------------------------ health
    def snapshot(self) -> dict:
        """The dt.health() ``cluster`` section (mirrored as
        ``daft_tpu_cluster_*`` gauges)."""
        from .peerplane import plane

        peer = plane().snapshot()
        with self._cond:
            alive = sum(1 for w in self.workers if w.state == "ready")
            tripped = sum(1 for w in self.workers
                          if w.breaker.state == "open")
            inflight = sum(len(w.inflight) for w in self.workers)
            draining = sum(1 for w in self.workers if w.draining)
            # aggregate the workers' pong-piggybacked piece-store
            # snapshots over the driver's own (ensure_local pulls)
            for w in self.workers:
                for k, v in (w.peer_report or {}).items():
                    if k in peer and isinstance(v, int):
                        peer[k] += v
            peer["shuffles_active"] = len(self._live_shuffles)
            # fleet-wide persistent-result-tier rollup from the same
            # pong piggyback (persist/resultstore.pong_report)
            result_store = {"entries_hosted": 0, "hits": 0, "misses": 0,
                            "inserts": 0, "peer_serves": 0,
                            "peer_fetches": 0}
            for w in self.workers:
                rs = w.rs_report or {}
                result_store["entries_hosted"] += len(
                    rs.get("digests") or ())
                for k in ("hits", "misses", "inserts", "peer_serves",
                          "peer_fetches"):
                    v = rs.get(k)
                    if isinstance(v, int):
                        result_store[k] += v
            elastic = {
                "enabled": int(self._elastic),
                "workers_target": self.n,
                "workers_min": self.n_min,
                "workers_max": self.n_max,
                "draining": draining,
                "workers_drained_total": self.workers_drained_total,
                "scale_ups_total": self.scale_ups_total,
                "scale_downs_total": self.scale_downs_total,
                "last_scale_decision": self.last_scale_decision,
            }
            workers = {
                str(w.wid): {
                    "state": w.state,
                    "breaker": w.breaker.state,
                    "pid": w.pid,
                    "restarts": w.restarts,
                    "deaths": w.deaths,
                    "inflight": len(w.inflight),
                    "tasks_done": w.tasks_done,
                    "ledger_current": w.ledger_report.get("current", 0),
                    "ledger_high_water": w.ledger_report.get(
                        "high_water", 0),
                    "telemetry_rx": w.telemetry_rx,
                    "telemetry_dropped": w.telemetry_dropped,
                }
                for w in self.workers}
            return {
                "workers": self.n,
                "workers_alive": alive,
                "workers_restarting": self.n - alive - sum(
                    1 for w in self.workers
                    if w.state == "dead"
                    and self.restarts_used >= self.restart_budget),
                "workers_tripped": tripped,
                "tasks_inflight": inflight,
                "tasks_dispatched_total": self.tasks_dispatched_total,
                "tasks_completed_total": self.tasks_completed_total,
                "task_redispatches_total": self.task_redispatches_total,
                "worker_losses_total": self.worker_losses_total,
                "tasks_speculated_total": self.tasks_speculated_total,
                "speculation_wins_total": self.speculation_wins_total,
                "speculation_inflight": self._spec_inflight,
                "telemetry_dropped_total": self.telemetry_dropped_total,
                "driver_payload_bytes_total":
                    self.driver_payload_bytes_total,
                "workers_drained_total": self.workers_drained_total,
                "peer_plane": peer,
                "result_store": result_store,
                "elastic": elastic,
                "local_fallbacks_total": self.local_fallbacks_total,
                "restarts_used": self.restarts_used,
                "restart_budget": self.restart_budget,
                "restart_budget_remaining": max(
                    0, self.restart_budget - self.restarts_used),
                "degraded": not self._usable_locked(),
                "worker_detail": workers,
            }

    def worker_pids(self) -> Dict[int, int]:
        """slot -> live pid (the kill-a-worker tests' target list)."""
        with self._cond:
            return {w.wid: w.pid for w in self.workers
                    if w.state == "ready" and w.pid is not None}

    def live_worker_processes(self) -> int:
        """Spawned worker processes still alive (0 after shutdown — the
        zero-leak assertion surface)."""
        with self._cond:
            procs = [w.proc for w in self.workers if w.proc is not None]
        return sum(1 for p in procs if p.poll() is None)

    # ---------------------------------------------------------- shutdown
    def shutdown(self, timeout_s: float = 10.0) -> None:
        """Stop supervision, ask every worker to exit, SIGKILL stragglers,
        and fail over any still-waiting tasks to local execution."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            entries = [e for w in self.workers
                       for e in w.inflight.values()
                       if e.status == "inflight"]
            for w in self.workers:
                for e in list(w.inflight.values()):
                    if e.status == "inflight":
                        e.status = "lost"
                        if e.spec_wid is not None:
                            e.spec_wid = None
                            self._spec_inflight -= 1
                        if e.charged:
                            e.ctx.ledger.dist_done(e.charged)
                            e.charged = 0
                w.inflight.clear()
            self._cond.notify_all()
        for e in entries:
            e.event.set()
        deadline = time.monotonic() + timeout_s
        for w in self.workers:
            with self._cond:
                sock, proc = w.sock, w.proc
            if sock is not None:
                try:
                    with w.send_lock:
                        send_msg(sock, {"type": "shutdown"},
                                 checksum=self._checksum)
                except Exception:
                    pass
        for w in self.workers:
            with self._cond:
                proc = w.proc
            if proc is None:
                continue
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except Exception:
                try:
                    proc.kill()
                    proc.wait(timeout=5)
                except Exception:
                    pass
        for w in self.workers:
            with self._cond:
                sock, w.sock, w.state = w.sock, None, "dead"
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
        try:
            self._listener.close()
        except OSError:
            pass
        # _spawn_lock only guards the parked dict (held for dict ops, never
        # across IO), so shutdown can take it: the swap can't interleave
        # with a racing spawner's park, whose socket would otherwise leak
        # into the dropped dict
        with self._spawn_lock:
            parked, self._parked = self._parked, {}
        for cand, _hello in parked.values():
            try:
                cand.close()
            except OSError:
                pass
        if self._supervisor.is_alive():
            self._supervisor.join(timeout=max(
                0.1, deadline - time.monotonic()))
        for w in self.workers:
            if w.rx_thread is not None and w.rx_thread.is_alive():
                w.rx_thread.join(timeout=max(
                    0.05, deadline - time.monotonic()))
        logger.info("worker_pool_shutdown",
                    losses=self.worker_losses_total,
                    redispatches=self.task_redispatches_total,
                    restarts_used=self.restarts_used)


# ---------------------------------------------------------------------------
# process-wide pool lifecycle (one pool, rebuilt when the knobs change)
# ---------------------------------------------------------------------------

_POOL: Optional[WorkerPool] = None
_POOL_LOCK = threading.Lock()


def get_worker_pool(cfg) -> Optional[WorkerPool]:
    """The process's WorkerPool for ``cfg`` (spawned on first use; rebuilt
    when worker count or budget changes). None when distribution is off."""
    global _POOL
    if cfg.distributed_workers <= 0:
        return None
    with _POOL_LOCK:
        pool = _POOL
        if pool is not None and not pool._closed and (
                pool._cfg_key == (cfg.distributed_workers,
                                  getattr(cfg, "distributed_workers_min",
                                          None),
                                  getattr(cfg, "distributed_workers_max",
                                          None),
                                  cfg.memory_budget_bytes)):
            # adopt the caller's config for the tunables that need no
            # respawn (speculation knobs, driver-side frame checksums) —
            # worker-resident settings keep their spawn-time values
            pool.cfg = cfg
            pool._checksum = bool(getattr(cfg, "partition_integrity", True))
            return pool
        if pool is not None:
            pool.shutdown()
        _POOL = WorkerPool(cfg)
        return _POOL


def shutdown_worker_pool(timeout_s: float = 10.0) -> None:
    """Tear the process pool down (dt.shutdown(), atexit, tests)."""
    global _POOL
    with _POOL_LOCK:
        pool, _POOL = _POOL, None
    if pool is not None:
        pool.shutdown(timeout_s=timeout_s)


def worker_pool_snapshot() -> Optional[dict]:
    """The live pool's cluster snapshot, or None (idle) — the dt.health()
    hook that must never spawn a pool as a side effect."""
    with _POOL_LOCK:
        pool = _POOL
    if pool is None or pool._closed:
        return None
    return pool.snapshot()


def live_worker_process_count() -> int:
    with _POOL_LOCK:
        pool = _POOL
    return 0 if pool is None else pool.live_worker_processes()
