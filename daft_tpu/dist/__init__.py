"""Multi-process distributed runner (README "Distributed execution").

The spawn-based answer to upstream's RayRunner (PAPER.md L3): a
DistributedRunner behind the Runner ABC ships serialized map-class
PartitionTasks to a supervised pool of worker PROCESSES over a
length-prefixed socket transport, and treats worker failure as a
first-class, tested degradation path — heartbeats with a deadline, a
WorkerHealth breaker per worker, bounded-respawn supervision, task
re-dispatch with attempt counts and excluded-worker sets, exactly-once
results via a driver-side ledger, and a poison-task DaftError naming the
task instead of cycling forever. All behind ``cfg.distributed_workers``
(0 = off), byte-identical to the local runner when on.
"""

from .runner import DistributedRunner
from .supervisor import (WorkerPool, get_worker_pool, shutdown_worker_pool,
                         worker_pool_snapshot)

__all__ = ["DistributedRunner", "WorkerPool", "get_worker_pool",
           "shutdown_worker_pool", "worker_pool_snapshot"]
