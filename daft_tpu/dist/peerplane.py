# daftlint: migrated
"""Peer-to-peer shuffle data plane: workers host shuffle pieces, reducers
pull them directly from peers.

The star-topology DistributedRunner (dist/supervisor.py) moves every
partition payload through the driver, so driver NIC/memcpy is an
O(cluster) bottleneck. With ``cfg.peer_shuffle`` on, a hash/random
ShuffleOp instead dispatches **fanout tasks**: each source partition ships
to a worker (as its scan task when unloaded — the worker reads the file
itself), the worker runs the deterministic split and parks the pieces in
its process-local :class:`_PeerPlane` store, and only tiny piece METADATA
returns to the driver. The reduce side is a :class:`PeerPieceTask`-backed
unloaded partition carrying the piece-location map; whichever process
materializes it — a worker running the downstream map task (the true
peer-to-peer hop), or the driver for driver-side ops — pulls the pieces
over the token-authenticated crc-framed transport (dist/transport.py)
from the peers that hold them. Driver payload bytes stay flat as the
worker count grows; results are byte-identical to the star path at every
worker count (same pieces, same source order, same concat).

Robustness is the contract, not an afterthought:

- every fetch fires the ``peer.fetch`` fault site and verifies the
  piece's store-time crc32; a dead/draining peer, a severed link, or a
  corrupt payload all degrade the same way — the fetcher falls over to
  the piece's LINEAGE recipe (integrity/lineage.fanout_piece_recipe):
  re-read the scan-backed source, re-run the deterministic split, keep
  the one lost piece (``peer_refetches``). Only a piece with truncated
  lineage (loaded source, no recipe) raises DaftTransientError for the
  task-retry machinery — a typed error at worst, never a hung query;
- pieces live until the driver broadcasts the shuffle drop at query end
  (ExecutionContext.finish_query), so speculation losers and re-reads
  stay serveable; a worker draining (dist/supervisor.drain_worker) keeps
  serving pieces through its grace window, after which fetchers of its
  pieces re-source via the same recipe path.

The module-level :data:`_PLANE` is the sanctioned process-wide piece
store + counter account (one per process, like the worker pool itself);
it is registered in the daftlint ambient-state whitelist and surfaced by
``dt.health()["cluster"]["peer_plane"]``.
"""

from __future__ import annotations

import pickle
import socket
import threading
import zlib
from typing import Dict, List, NamedTuple, Optional, Tuple

from ..errors import DaftCorruptionError, DaftError, DaftTransientError
from ..obs.log import get_logger

logger = get_logger("dist.peer")

# one fetch round-trip's socket budget; a peer slower than this reads as
# dead and the recipe path owns recovery
FETCH_TIMEOUT_S = 30.0


class PieceRef(NamedTuple):
    """Location-map row for one hosted shuffle piece: where it lives
    (worker slot + piece-server address), which piece it is (shuffle id,
    reduce bucket, source sequence), and what must arrive (rows, payload
    bytes, store-time crc32 — None when integrity is off)."""

    wid: int
    host: str
    port: int
    sid: int
    bucket: int
    src: int
    rows: int
    nbytes: int
    crc: Optional[int]


class _PeerPlane:
    """Process-wide piece store + peer-plane counters (driver and worker
    alike run exactly one). Workers put fanout pieces here and the
    :class:`PieceServer` serves them; every process counts the fetches it
    performs, and pong piggybacks ship worker-side snapshots to the
    driver's health aggregation."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pieces: Dict[Tuple[int, int, int], Tuple[bytes, int]] = {}
        # the worker slot this process IS (None on the driver): fetches of
        # self-hosted pieces short-circuit the socket
        self.worker_id: Optional[int] = None
        # worker-side per-query stats hook (the worker's RuntimeStats —
        # counter bumps ride telemetry fragments back to the driver query)
        self.stats = None
        self.piece_bytes_hosted = 0
        self.pieces_stored_total = 0
        self.pieces_served_total = 0
        self.peer_bytes_served_total = 0
        self.pieces_fetched_total = 0
        self.pieces_refetched_total = 0
        self.peer_bytes_fetched_total = 0
        self.shuffles_dropped_total = 0

    def configure(self, worker_id: Optional[int], stats) -> None:
        with self._lock:
            self.worker_id = worker_id
            self.stats = stats

    def put(self, key: Tuple[int, int, int], payload: bytes,
            rows: int) -> None:
        with self._lock:
            old = self._pieces.get(key)
            if old is not None:
                # a re-dispatched fanout re-stored the same deterministic
                # piece: replace, never double-account
                self.piece_bytes_hosted -= len(old[0])
            self._pieces[key] = (payload, rows)
            self.piece_bytes_hosted += len(payload)
            self.pieces_stored_total += 1

    def get(self, key: Tuple[int, int, int],
            serving: bool = False) -> Optional[Tuple[bytes, int]]:
        with self._lock:
            hit = self._pieces.get(key)
            if hit is not None and serving:
                self.pieces_served_total += 1
                self.peer_bytes_served_total += len(hit[0])
            return hit

    def count_fetch(self, nbytes: int) -> None:
        with self._lock:
            self.pieces_fetched_total += 1
            self.peer_bytes_fetched_total += nbytes

    def count_refetch(self) -> None:
        with self._lock:
            self.pieces_refetched_total += 1

    def drop_shuffles(self, sids) -> int:
        """Drop every piece of the given shuffle ids (query-end broadcast,
        speculation-loser cleanup); returns pieces dropped."""
        sids = set(sids)
        with self._lock:
            doomed = [k for k in self._pieces if k[0] in sids]
            for k in doomed:
                payload, _ = self._pieces.pop(k)
                self.piece_bytes_hosted -= len(payload)
            self.shuffles_dropped_total += len(sids)
            return len(doomed)

    def clear(self) -> None:
        with self._lock:
            self._pieces.clear()
            self.piece_bytes_hosted = 0

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "pieces_hosted": len(self._pieces),
                "piece_bytes_hosted": self.piece_bytes_hosted,
                "pieces_stored_total": self.pieces_stored_total,
                "pieces_served_total": self.pieces_served_total,
                "peer_bytes_served_total": self.peer_bytes_served_total,
                "pieces_fetched_total": self.pieces_fetched_total,
                "pieces_refetched_total": self.pieces_refetched_total,
                "peer_bytes_fetched_total": self.peer_bytes_fetched_total,
                "shuffles_dropped_total": self.shuffles_dropped_total,
            }


_PLANE = _PeerPlane()


def plane() -> _PeerPlane:
    return _PLANE


# ---------------------------------------------------------------------------
# worker side: piece server + fanout execution
# ---------------------------------------------------------------------------

class PieceServer:
    """Worker-side piece server: a listener bound BEFORE the worker's
    hello (the supervisor learns the port from the handshake, so there is
    no window where a dispatched reduce task holds an address that was
    never bound). Each accepted connection is one peer's fetch channel:
    token-checked per request, framed/checksummed by dist/transport.py —
    the same integrity contract as the driver link. Read-only by design:
    drops and lifecycle arrive over the supervised driver channel, never
    from peers."""

    def __init__(self, token: str, checksum: bool = True):
        self.token = token
        self.checksum = checksum
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(64)
        self.port = self._listener.getsockname()[1]
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._accept_loop, name="daft-peer-server", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed: server is done
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="daft-peer-conn", daemon=True)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        from .transport import TransportClosed, recv_msg, send_msg

        try:
            conn.settimeout(FETCH_TIMEOUT_S)
            while True:
                msg = recv_msg(conn)
                if msg.get("type") != "fetch" \
                        or msg.get("token") != self.token:
                    # unauthenticated or foreign frame: drop the link (the
                    # fetcher degrades through its recipe path)
                    return
                key = tuple(msg["key"])
                if key and key[0] == "rs":
                    # persistent-result-tier fetch (persist/resultstore):
                    # same transport, same token, same degradation — a
                    # defect here reads as not-found and the fetcher
                    # executes its task for real
                    try:
                        from ..persist.resultstore import RESULT_STORE

                        hit = RESULT_STORE.serve_payload(key[1], key[2])
                    except Exception:
                        hit = None
                else:
                    hit = _PLANE.get(key, serving=True)
                reply = {"type": "piece", "found": hit is not None}
                if hit is not None:
                    reply["payload"], reply["rows"] = hit
                send_msg(conn, reply, checksum=self.checksum)
        except (TransportClosed, OSError):
            pass  # peer went away mid-fetch: its recovery is not ours
        except Exception as e:
            logger.warning("peer_server_conn_failed", error=repr(e))
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed.set()
        try:
            # close() alone does not wake a thread parked in accept();
            # shutdown() does
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        if (self._thread.ident is not None
                and self._thread is not threading.current_thread()):
            self._thread.join(timeout=2.0)


def execute_fanout(part, spec: dict, exec_ctx) -> List[Tuple]:
    """Run one fanout task worker-side: deterministic split of the source
    partition, pieces parked in the process piece store, piece metadata
    (bucket, rows, payload bytes, crc) returned — the ONLY bytes that
    travel back to the driver. Empty pieces are neither stored nor
    reported: concat skips them identically on the star path."""
    n = int(spec["num"])
    sid = int(spec["sid"])
    src = int(spec["src"])
    prof = exec_ctx.stats.profiler
    sp = prof.begin("worker.fanout", part=src, kind="bg") if prof.armed \
        else None
    try:
        if spec["scheme"] == "hash":
            pieces = part.partition_by_hash(spec["by"], n)
        else:
            pieces = part.partition_by_random(n, seed=int(spec["seed"]))
        metas: List[Tuple] = []
        for i, piece in enumerate(pieces):
            rows = piece.num_rows_or_none() or 0
            if not rows:
                continue
            payload = pickle.dumps(piece,
                                   protocol=pickle.HIGHEST_PROTOCOL)
            crc = zlib.crc32(payload) if spec.get("crc") else None
            _PLANE.put((sid, i, src), payload, rows)
            metas.append((i, rows, len(payload), crc))
        return metas
    finally:
        if sp is not None:
            prof.end(sp)


# ---------------------------------------------------------------------------
# fetch side: location-map-backed scan task with lineage failover
# ---------------------------------------------------------------------------

def _fetch_over(conns: dict, ref: PieceRef, token: str,
                checksum: bool) -> Tuple[bytes, int]:
    """Pull one piece from its hosting peer (connection cached per
    address for the materialization's lifetime). Raises on any transport
    or not-found defect — the caller owns degradation."""
    from .transport import dial, recv_msg, send_msg

    if _PLANE.worker_id is not None and _PLANE.worker_id == ref.wid:
        # self-hosted piece: the "fetch" is a local store read
        hit = _PLANE.get((ref.sid, ref.bucket, ref.src), serving=True)
        if hit is None:
            raise DaftTransientError(
                f"peer piece {ref.sid}/{ref.bucket}/{ref.src} missing "
                "from the local store")
        return hit
    addr = (ref.host, ref.port)
    conn = conns.get(addr)
    if conn is None:
        conn = conns[addr] = dial(ref.host, ref.port,
                                  timeout=FETCH_TIMEOUT_S)
    send_msg(conn, {"type": "fetch", "token": token,
                    "key": (ref.sid, ref.bucket, ref.src)},
             checksum=checksum)
    reply = recv_msg(conn)
    if not reply.get("found"):
        # a stale location map: the peer restarted, drained past its
        # grace window, or the piece was dropped — transient by contract
        raise DaftTransientError(
            f"peer {ref.wid} no longer hosts piece "
            f"{ref.sid}/{ref.bucket}/{ref.src}")
    return reply["payload"], reply.get("rows", 0)


class PeerPieceTask:
    """Scan-task-shaped holder for one reduce bucket of a peer shuffle:
    an ordered location map (PieceRefs, plus inline driver-local pieces
    from fanout fallbacks) and the recovery spec that re-derives any lost
    piece from its scan-backed source. ``read_chunks()`` is the pull —
    it runs in whichever process materializes the bucket, which is what
    makes the data plane peer-to-peer."""

    def __init__(self, schema, entries: List, token: str,
                 split: Tuple, sources: Dict[int, object],
                 checksum: bool = True, stats=None):
        self.schema = schema
        # PieceRef rows and inline loaded MicroPartitions, in source order
        # — the exact order the star path's bucket concat uses
        self.entries = entries
        self.token = token
        # (by-expressions, scheme, num-buckets): with a source task this
        # reconstructs integrity/lineage.fanout_piece_recipe on demand
        self.split = split
        self.sources = sources
        self.checksum = checksum
        self._rt_stats = stats
        self.stats = None  # scan-task TableStats surface (none)
        self.rows = sum(e.rows if isinstance(e, PieceRef)
                        else (e.num_rows_or_none() or 0)
                        for e in entries)
        self.nbytes = sum(e.nbytes if isinstance(e, PieceRef)
                          else (e.size_bytes() or 0)
                          for e in entries)

    # location maps cross process boundaries (the reduce-side partition
    # ships to workers as this task): the per-query RuntimeStats handle
    # holds thread locks and must not ride along — worker-side fetch
    # counters come from the process plane instead
    def __getstate__(self):
        state = dict(self.__dict__)
        state["_rt_stats"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)

    def _stats(self):
        return self._rt_stats if self._rt_stats is not None else _PLANE.stats

    # --- ScanTask metadata surface used by MicroPartition ----------------
    @property
    def materialized_schema(self):
        return self.schema

    def num_rows(self) -> Optional[int]:
        return self.rows

    def size_bytes(self) -> Optional[int]:
        return self.nbytes

    def preferred_wids(self) -> List[int]:
        """Worker slots hosting this bucket's bytes, heaviest first — the
        dispatch-locality hint (scheduler.py): running the reduce task
        where its pieces already live turns those fetches into local
        store reads."""
        weights: Dict[int, int] = {}
        for e in self.entries:
            if isinstance(e, PieceRef):
                weights[e.wid] = weights.get(e.wid, 0) + e.nbytes
        return sorted(weights, key=lambda w: -weights[w])

    def _recompute(self, ref: PieceRef, cause: BaseException) -> List:
        """Lineage failover for one lost/corrupt piece: rebuild the exact
        fanout recipe (integrity/lineage.py) from the recovery spec and
        re-derive just this piece at the read site."""
        from ..integrity.lineage import fanout_piece_recipe

        src_task = self.sources.get(ref.src)
        if src_task is None:
            raise DaftTransientError(
                f"peer piece {ref.sid}/{ref.bucket}/{ref.src} lost "
                f"({cause!r}) and its source is not recomputable "
                "(truncated lineage)") from cause
        by, scheme, num = self.split
        stats = self._stats()
        _PLANE.count_refetch()
        if stats is not None:
            stats.bump("peer_refetches")
        logger.warning("peer_piece_recomputed", sid=ref.sid,
                       bucket=ref.bucket, src=ref.src, peer=ref.wid,
                       cause=repr(cause))
        recipe = fanout_piece_recipe(src_task, by, scheme, num, ref.src,
                                     ref.bucket)
        chunks = recipe()
        got = sum(len(t) for t in chunks)
        if got != ref.rows:
            # the recompute disagreeing with the recorded piece meta is a
            # REAL defect (nondeterministic source?), not a transient
            raise DaftError(
                f"peer piece recompute returned {got} rows, location map "
                f"recorded {ref.rows} (sid={ref.sid} bucket={ref.bucket} "
                f"src={ref.src})")
        return chunks

    def read_chunks(self) -> List:
        from .. import faults

        stats = self._stats()
        chunks: List = []
        conns: dict = {}
        try:
            for e in self.entries:
                if not isinstance(e, PieceRef):
                    chunks.extend(e.chunk_tables())
                    continue
                try:
                    faults.check("peer.fetch", stats)
                    payload, _rows = _fetch_over(conns, e, self.token,
                                                 self.checksum)
                    if e.crc is not None:
                        got = zlib.crc32(payload)
                        if got != e.crc:
                            raise DaftCorruptionError(
                                f"peer piece failed its integrity check "
                                f"(crc {got:#010x} != {e.crc:#010x}, "
                                f"sid={e.sid} bucket={e.bucket})")
                    piece = pickle.loads(payload)
                    _PLANE.count_fetch(len(payload))
                    if stats is not None:
                        stats.bump("peer_fetches")
                        stats.bump("peer_bytes_fetched", len(payload))
                    chunks.extend(piece.chunk_tables())
                except (DaftTransientError, DaftCorruptionError, OSError,
                        EOFError, pickle.UnpicklingError) as err:
                    # a dead/draining/slow peer, a severed or corrupt
                    # link, a stale location map: all the same failover —
                    # drop the cached connection (it may be the broken
                    # half) and recompute this one piece from lineage
                    stale = conns.pop((e.host, e.port), None)
                    if stale is not None:
                        try:
                            stale.close()
                        except OSError:
                            pass
                    chunks.extend(self._recompute(e, err))
        finally:
            for c in conns.values():
                try:
                    c.close()
                except OSError:
                    pass
        return chunks

    def read(self):
        from ..table import Table

        chunks = [t for t in self.read_chunks() if len(t)]
        if not chunks:
            return Table.empty(self.schema)
        if len(chunks) == 1:
            return chunks[0]
        return Table.concat(chunks)

    # head()/select on unloaded partitions route through pushdowns; reduce
    # buckets never see them in practice, but keep the surface total
    @property
    def pushdowns(self):
        from ..io.scan import Pushdowns

        return Pushdowns()

    def with_pushdowns(self, pd):
        from ..spill import _SpillSlotView

        return _SpillSlotView(self, pd)

    def __repr__(self) -> str:
        remote = sum(1 for e in self.entries if isinstance(e, PieceRef))
        return (f"PeerPieceTask(rows={self.rows}, pieces={len(self.entries)}"
                f" remote={remote})")


def is_peer_backed(part) -> bool:
    """Is this partition's materialization a peer pull? (Root outputs are
    forced local before the query's finish drops their shuffles.)"""
    if part.is_loaded():
        return False
    task = part.scan_task()
    return isinstance(getattr(task, "_task", task), PeerPieceTask)


def ensure_local(part):
    """Force a peer-backed partition local (idempotent, cheap for
    everything else): execute_plan's root stream calls this per output so
    no result partition outlives its shuffle's pieces."""
    if is_peer_backed(part):
        part.table()
    return part


def peer_preference(part):
    """Dispatch-locality hint for the supervisor: the worker slots hosting
    most of this partition's piece bytes (top two), or None when the
    partition is not peer-backed. Best-effort — any surprise shape means
    no preference, never a failed dispatch."""
    try:
        if part.is_loaded():
            return None
        task = part.scan_task()
        task = getattr(task, "_task", task)
        if not isinstance(task, PeerPieceTask):
            return None
        wids = task.preferred_wids()[:2]
        return set(wids) if wids else None
    except Exception:
        return None
