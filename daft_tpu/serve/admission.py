"""Query-level admission control: bounded FIFO-with-slots + overload shed.

The per-task ResourceAccountant (execution.py) keeps one query from
oversubscribing the host; it does nothing about N queries arriving at
once. This controller sits in FRONT of execution: at most
``max_concurrent_queries`` queries hold an execution slot, at most
``queue_depth`` more wait in FIFO order, and everything beyond that —
or anything that waits longer than ``timeout_s``, or arrives while the
engine drains for shutdown — is SHED with ``DaftOverloadedError``. Shedding
is deliberate: a bounded queue with a fast rejection beats an unbounded
pile-up that times every caller out (the sustained-throughput lesson of
the pipelines paper in PAPERS.md).

Protocol (the ServingRuntime drives it):

    ticket = ctl.enqueue(query_id)      # sync; sheds on overflow/drain
    ctl.await_slot(ticket)              # FIFO wait; sheds on timeout/drain
    try: ... run the query ...
    finally: ctl.release(ticket)

``snapshot()`` feeds ``dt.health()`` and the admission gauges in
``metrics_text()``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional

from ..errors import DaftOverloadedError


class _Ticket:
    __slots__ = ("query_id", "enqueued_at", "admitted")

    def __init__(self, query_id: str):
        self.query_id = query_id
        self.enqueued_at = time.monotonic()
        # True once this ticket holds an execution slot (possibly claimed
        # already at enqueue time — see AdmissionController.enqueue)
        self.admitted = False


class AdmissionController:
    def __init__(self, slots: int, queue_depth: int,
                 timeout_s: Optional[float]):
        self.slots = max(1, int(slots))
        self.queue_depth = max(0, int(queue_depth))
        self.timeout_s = timeout_s
        self._cond = threading.Condition()
        self._active: Dict[str, float] = {}   # query_id -> admit time
        self._waiters: Deque[_Ticket] = deque()
        self._draining = False
        self.shed_total = 0
        self.admitted_total = 0

    # ------------------------------------------------------------ admission
    def enqueue(self, query_id: str) -> _Ticket:
        """Claim a queue position, or shed NOW: overflow and drain are
        rejected synchronously at submit time, never discovered after a
        wait. A query that can run immediately (empty FIFO, free slot)
        claims its slot HERE — a burst of submits fills all slots before
        the first driver thread is even scheduled, so effective burst
        capacity is slots + queue_depth and shed decisions never depend
        on thread-scheduling timing."""
        with self._cond:
            if self._draining:
                self.shed_total += 1
                raise DaftOverloadedError(
                    f"query {query_id} shed: engine is draining for "
                    "shutdown")
            ticket = _Ticket(query_id)
            if not self._waiters and len(self._active) < self.slots:
                self._admit_locked(ticket)
                return ticket
            if len(self._waiters) >= self.queue_depth:
                self.shed_total += 1
                raise DaftOverloadedError(
                    f"query {query_id} shed: admission queue full "
                    f"({len(self._active)} active / {len(self._waiters)} "
                    f"queued, slots={self.slots}, "
                    f"queue_depth={self.queue_depth})")
            self._waiters.append(ticket)
            self._cond.notify_all()
            return ticket

    def _admit_locked(self, ticket: _Ticket) -> None:
        # runs under self._cond (every caller holds it; the lexical
        # lock-discipline rule cannot see through the helper)
        ticket.admitted = True
        self._active[ticket.query_id] = time.monotonic()
        self.admitted_total += 1  # daftlint: disable=DTL002
        self._cond.notify_all()

    def await_slot(self, ticket: _Ticket,
                   timeout_s: Optional[float] = None) -> None:
        """Block until this ticket is at the head of the FIFO and a slot is
        free, then take the slot (a no-op for tickets already admitted at
        enqueue). Sheds on queue timeout or drain."""
        limit = timeout_s if timeout_s is not None else self.timeout_s
        deadline = (time.monotonic() + limit) if limit is not None else None
        with self._cond:
            if ticket.admitted:
                return
            while True:
                if self._draining:
                    self._drop(ticket)
                    raise DaftOverloadedError(
                        f"query {ticket.query_id} shed: engine is draining "
                        "for shutdown")
                if (self._waiters and self._waiters[0] is ticket
                        and len(self._active) < self.slots):
                    self._waiters.popleft()
                    # notify inside: the next waiter may also fit when
                    # several slots freed at once
                    self._admit_locked(ticket)
                    return
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        self._drop(ticket)
                        raise DaftOverloadedError(
                            f"query {ticket.query_id} shed: no execution "
                            f"slot within {limit}s "
                            f"(active={len(self._active)}, "
                            f"queued={len(self._waiters)})")
                self._cond.wait(remaining)

    def _drop(self, ticket: _Ticket) -> None:
        # runs under self._cond (every caller holds it — the lexical
        # lock-discipline rule cannot see through the helper): a shed
        # waiter leaves the FIFO so it cannot block the queries behind it
        try:
            self._waiters.remove(ticket)
        except ValueError:
            pass
        self.shed_total += 1  # daftlint: disable=DTL002
        self._cond.notify_all()

    def release(self, ticket: _Ticket) -> None:
        with self._cond:
            self._active.pop(ticket.query_id, None)
            self._cond.notify_all()

    # ---------------------------------------------------------------- drain
    def begin_drain(self) -> None:
        """Stop admitting: queued waiters shed immediately, new submits
        shed at enqueue; in-flight queries keep their slots."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()

    def wait_drained(self, timeout_s: float) -> List[str]:
        """Wait for in-flight queries to finish; returns the query ids
        still active when the timeout expires (the stragglers)."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._cond:
            while self._active:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            return sorted(self._active)

    # ------------------------------------------------------------- introspection
    def active_queries(self) -> List[str]:
        with self._cond:
            return sorted(self._active)

    def snapshot(self) -> dict:
        with self._cond:
            return {
                "slots": self.slots,
                "queue_depth": self.queue_depth,
                "active_queries": len(self._active),
                "queued_queries": len(self._waiters),
                "admitted_total": self.admitted_total,
                "shed_total": self.shed_total,
                "draining": self._draining,
            }
