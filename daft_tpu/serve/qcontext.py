"""QueryContext: the per-query half of what used to be ambient state.

Before the serving runtime, one query at a time meant per-query state could
live wherever it landed: RuntimeStats on the DataFrame, the deadline and
breakers threaded through ``Runner.run_iter``'s keyword arguments, and ONE
process-wide MemoryLedger that every buffer charged. With N queries in
flight those become interference channels — query A's spill pressure fills
the shared ledger and forces query B to spill; A's breaker trip degrades
B's device path; A's deadline is whatever the global config said at the
moment B mutated it.

QueryContext owns all of it, per query:

- ``stats``            — RuntimeStats (counters, cancellation handle)
- ``deadline``         — ONE absolute deadline across all AQE stages
- ``device_health`` /
  ``collective_health``— this query's circuit breakers (a poisoned query
                         trips its own breaker; the next query starts
                         closed)
- ``ledger``           — a MemoryLedger CHILD of the process root, so
                         budget decisions read this query's balance while
                         process totals stay exact
- ``memory_budget_bytes`` — the query's share of the global budget
                         (``memory_budget_bytes / max_concurrent_queries``
                         under the serving runtime; the whole budget solo)
- ``shared_pool``      — the serving runtime's SharedExecutorPool (None
                         solo: the ExecutionContext creates a private pool
                         exactly as before)

The process-global ``DaftContext`` is left holding only config + runner,
which is the de-globalization the DTL008 lint rule pins.
"""

from __future__ import annotations

from typing import Optional


class QueryContext:
    """Per-query mutable execution state (see module docstring). Built once
    per query by ``Runner.run_iter`` (solo path) or the ServingRuntime
    (concurrent path) and shared by every AQE stage of that query."""

    __slots__ = ("query_id", "stats", "deadline", "timeout_s",
                 "device_health", "collective_health", "ledger",
                 "memory_budget_bytes", "shared_pool")

    def __init__(self, stats, deadline: Optional[float],
                 device_health, collective_health,
                 ledger, memory_budget_bytes: Optional[int],
                 shared_pool=None, query_id: Optional[str] = None,
                 timeout_s: Optional[float] = None):
        self.query_id = query_id
        self.stats = stats
        self.deadline = deadline
        # the effective per-query limit behind `deadline` (config knob or
        # submit(timeout_s=...) override), kept for truthful error messages
        self.timeout_s = timeout_s
        self.device_health = device_health
        self.collective_health = collective_health
        self.ledger = ledger
        self.memory_budget_bytes = memory_budget_bytes
        self.shared_pool = shared_pool

    @classmethod
    def build(cls, cfg, stats=None, deadline: Optional[float] = None,
              device_health=None, collective_health=None,
              memory_budget_bytes: Optional[int] = None,
              shared_pool=None, query_id: Optional[str] = None,
              timeout_s: Optional[float] = None) -> "QueryContext":
        """Assemble a QueryContext from whatever the caller already has,
        defaulting the rest from ``cfg`` — the one place the solo path,
        the serving path, and directly-constructed test ExecutionContexts
        converge.

        ``memory_budget_bytes`` of None means "the whole configured
        budget" (solo semantics); the serving runtime passes the query's
        carved share instead. ``timeout_s`` (when given) overrides
        ``cfg.execution_timeout_s`` for this query only."""
        import time

        from ..execution import DeviceHealth, RuntimeStats
        from ..spill import MEMORY_LEDGER, MemoryLedger

        stats = stats if stats is not None else RuntimeStats()
        limit = (timeout_s if timeout_s is not None
                 else cfg.execution_timeout_s)
        if deadline is None and limit is not None:
            deadline = time.monotonic() + limit
        if device_health is None:
            device_health = DeviceHealth(cfg.device_breaker_threshold,
                                         cfg.device_breaker_cooldown_s)
        if collective_health is None:
            collective_health = DeviceHealth(cfg.device_breaker_threshold,
                                             cfg.device_breaker_cooldown_s,
                                             kind="collective")
        share = (memory_budget_bytes if memory_budget_bytes is not None
                 else cfg.memory_budget_bytes)
        # a child ledger is only worth its forwarding cost when queries
        # actually share the process: solo queries charge the root directly
        # (identical observable behavior — the root IS the only account)
        ledger = (MemoryLedger(parent=MEMORY_LEDGER)
                  if shared_pool is not None else MEMORY_LEDGER)
        return cls(stats, deadline, device_health, collective_health,
                   ledger, share, shared_pool=shared_pool,
                   query_id=query_id, timeout_s=limit)

    def register_health(self) -> None:
        """Expose this query's breakers to the engine-health snapshot
        (weakly held: a finished query's breaker reads as idle)."""
        from ..obs.health import register_breaker

        register_breaker(self.device_health)
        register_breaker(self.collective_health)

    def cancel(self) -> None:
        """Stop this query at the next partition boundary and cancel its
        queued-but-unstarted work on the shared pool (running tasks finish;
        the dispatch loop re-checks cancellation between results)."""
        self.stats.cancel()
        if self.shared_pool is not None and self.query_id is not None:
            self.shared_pool.cancel_queued(self.query_id)
