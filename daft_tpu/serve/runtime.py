"""ServingRuntime: N queries concurrently over the shared pool and mesh.

One runtime owns one AdmissionController and one SharedExecutorPool. Every
submitted query runs on its own driver thread through the admission gate:

    handle = runtime.submit(df)            # sheds DaftOverloadedError when
                                           # the bounded queue is full
    result_df = handle.result(timeout)     # or raises the query's error
    handle.record()                        # its flight-recorder QueryRecord

Robustness headline, per the ISSUE: admitted queries get a QueryContext —
their own RuntimeStats, breakers, deadline, cancellation handle, and a
MemoryLedger share carved from the global budget
(``memory_budget_bytes / max_concurrent_queries``) — so one heavy or
poisoned query spills, trips, times out, and dies ALONE. Shed queries get
a "shed" QueryRecord so the flight recorder sees every outcome, not just
executions.

``runtime.shutdown(timeout_s)`` is drain-mode: stop admitting (queued and
new queries shed), finish in-flight queries within the timeout, cancel and
report stragglers, then tear the shared pool down. The module-level
``shutdown()`` does that for every live runtime plus the actor pools —
``daft_tpu.shutdown()`` re-exports it and an atexit hook runs it with a
short timeout.
"""

from __future__ import annotations

import atexit
import itertools
import threading
import time
import weakref
from typing import List, Optional

from ..context import get_context, resolve_executor_threads
from ..errors import DaftOverloadedError
from ..obs.log import get_logger
from .admission import AdmissionController
from .pool import SharedExecutorPool
from .qcontext import QueryContext

logger = get_logger("serve")

# live runtimes, for engine-wide drain (dt.shutdown / atexit); weak so a
# dropped runtime never outlives its last user reference
_RUNTIMES: "weakref.WeakSet[ServingRuntime]" = weakref.WeakSet()
_runtimes_lock = threading.Lock()

# thread-name prefixes the engine owns; leaked_thread_count() scans these.
# Every spawn site's static name prefix must be covered by an entry here —
# daftlint DTL012 enforces the inventory, so a new subsystem prefix that
# forgets to register itself fails lint instead of leaking invisibly.
_ENGINE_THREAD_PREFIXES = ("daft-serve", "daft-exec", "daft-actor",
                           "daft-spill-writer", "daft-dist", "daft-peer",
                           "daft-mm")


class QueryHandle:
    """Future-like handle for one submitted query."""

    def __init__(self, query_id: str, stats):
        self.query_id = query_id
        self.stats = stats
        # submit -> terminal monotonic timestamps: the caller-visible
        # latency (queue wait included) the serving bench quantiles
        self.submitted_at = time.monotonic()
        self.finished_at: Optional[float] = None
        self._done = threading.Event()
        self._admitted = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None
        self._qctx: Optional[QueryContext] = None

    # ----------------------------------------------------------- completion
    def _set_result(self, df) -> None:
        self._result = df
        self.finished_at = time.monotonic()
        self._done.set()

    def _set_exception(self, e: BaseException) -> None:
        self._error = e
        self.finished_at = time.monotonic()
        self._done.set()

    def latency_s(self) -> Optional[float]:
        """Submit-to-terminal wall seconds (None until terminal)."""
        if self.finished_at is None:
            return None
        return self.finished_at - self.submitted_at

    def done(self) -> bool:
        return self._done.is_set()

    def wait_admitted(self, timeout: Optional[float] = None) -> bool:
        """True once the query holds an execution slot (shed/failed queries
        also return via ``done``)."""
        return self._admitted.wait(timeout)

    def result(self, timeout: Optional[float] = None):
        """The materialized DataFrame, or raises the query's terminal error
        (DaftOverloadedError when shed, DaftTimeoutError on deadline, ...)."""
        if not self._done.wait(timeout):
            from ..errors import DaftTimeoutError

            raise DaftTimeoutError(
                f"{self.query_id}: no terminal state within {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result

    def exception(self, timeout: Optional[float] = None):
        self._done.wait(timeout)
        return self._error

    def record(self):
        """This query's flight-recorder QueryRecord (None until terminal)."""
        return self.stats.last_record

    def progress(self):
        """Live progress snapshot of this query while it executes (the
        ``dt.health()["queries"]`` entry: ops completed/total, rows/bytes
        flowed, tasks in flight, per-worker dispatch state, streaming
        channel depths). None before admission and after completion —
        a finished query's truth lives in :meth:`record`."""
        from ..obs.cluster import query_progress

        return query_progress(self.query_id)

    def cancel(self) -> None:
        """Stop the query at the next partition boundary; queued-but-
        unstarted work on the shared pool is cancelled too."""
        qctx = self._qctx
        if qctx is not None:
            qctx.cancel()
        else:
            self.stats.cancel()


_UNSET = object()


class ServingRuntime:
    def __init__(self, max_concurrent_queries: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 admission_timeout_s=_UNSET):
        cfg = get_context().execution_config
        slots = (max_concurrent_queries if max_concurrent_queries is not None
                 else cfg.max_concurrent_queries)
        depth = (queue_depth if queue_depth is not None
                 else cfg.admission_queue_depth)
        timeout = (cfg.admission_timeout_s if admission_timeout_s is _UNSET
                   else admission_timeout_s)
        self.admission = AdmissionController(slots, depth, timeout)
        self.pool = SharedExecutorPool(resolve_executor_threads(cfg))
        self._qseq = itertools.count(1)
        self._threads: List[threading.Thread] = []
        self._threads_lock = threading.Lock()
        # query_id -> live handle (weak: a dropped handle's query still
        # finishes, but the runtime never pins results)
        self._handles: "weakref.WeakValueDictionary[str, QueryHandle]" = (
            weakref.WeakValueDictionary())
        self._closed = False
        from ..obs.health import register_admission

        register_admission(self.admission)
        with _runtimes_lock:
            _RUNTIMES.add(self)

    # ---------------------------------------------------------------- submit
    def submit(self, df, timeout_s: Optional[float] = None,
               admission_timeout_s: Optional[float] = None) -> QueryHandle:
        """Submit a DataFrame's plan. Raises DaftOverloadedError HERE when
        the bounded admission queue is already full (deterministic shed at
        the door); queue-timeout sheds surface on the handle.

        ``timeout_s`` is this query's execution deadline (overrides
        ``cfg.execution_timeout_s``); ``admission_timeout_s`` overrides the
        queue-wait limit."""
        from ..execution import RuntimeStats

        if self._closed:
            raise DaftOverloadedError("serving runtime is shut down")
        stats = RuntimeStats()
        query_id = f"serve-q{next(self._qseq)}"
        handle = QueryHandle(query_id, stats)
        submitted_at = time.monotonic()
        try:
            ticket = self.admission.enqueue(query_id)
        except DaftOverloadedError as e:
            self._record_shed(handle, e, submitted_at)
            raise
        t = threading.Thread(
            target=self._run_query,
            args=(handle, ticket, df._plan, timeout_s, admission_timeout_s,
                  submitted_at),
            name=f"daft-serve-{query_id}", daemon=True)
        with self._threads_lock:
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)
            self._handles[query_id] = handle
        t.start()
        return handle

    def _run_query(self, handle: QueryHandle, ticket, plan,
                   timeout_s: Optional[float],
                   admission_timeout_s: Optional[float],
                   submitted_at: float) -> None:
        try:
            self.admission.await_slot(ticket, admission_timeout_s)
        except DaftOverloadedError as e:
            logger.warning("query_shed", query=handle.query_id,
                           error=str(e))
            self._record_shed(handle, e, submitted_at)
            handle._set_exception(e)
            return
        handle._admitted.set()
        ctx = get_context()
        cfg = ctx.execution_config
        qctx = QueryContext.build(
            cfg, stats=handle.stats, query_id=handle.query_id,
            timeout_s=timeout_s, shared_pool=self.pool,
            memory_budget_bytes=self._memory_share(cfg))
        handle._qctx = qctx
        try:
            from ..dataframe import from_partitions

            pset = ctx.runner().run(plan, stats=handle.stats, qctx=qctx)
            out = from_partitions(pset.partitions, pset.schema)
            # the handle's stats carry the QueryRecord; hand them to the
            # result DataFrame so df.last_query_record() works there too
            out.stats = handle.stats
            handle._set_result(out)
        except BaseException as e:
            handle._set_exception(e)
        finally:
            self.admission.release(ticket)
            # a failed/cancelled query may leave queued work behind
            self.pool.cancel_queued(handle.query_id)

    def _memory_share(self, cfg) -> Optional[int]:
        """Each admitted query's MemoryLedger share: the global budget
        split across the execution slots, so all concurrently-admissible
        queries together can never exceed it."""
        if cfg.memory_budget_bytes is None:
            return None
        return max(1, cfg.memory_budget_bytes // self.admission.slots)

    def _record_shed(self, handle: QueryHandle, error: BaseException,
                     submitted_at: float) -> None:
        """Shed queries get a flight-recorder record too (outcome "shed");
        observability must never fail the shed path."""
        cfg = get_context().execution_config
        try:
            from ..obs.querylog import QUERY_LOG, build_record

            wall_ns = int((time.monotonic() - submitted_at) * 1e9)
            rec = build_record(handle.query_id, "unplanned", {}, cfg,
                               handle.stats, wall_ns, "shed", error=error)
            if getattr(cfg, "enable_query_log", True):
                QUERY_LOG.resize(cfg.query_log_depth)
                QUERY_LOG.append(rec)
                handle.stats.last_record = rec
        except Exception as e:
            logger.error("shed_record_failed", error=repr(e))

    # -------------------------------------------------------------- shutdown
    def shutdown(self, timeout_s: float = 30.0) -> dict:
        """Drain-mode shutdown: stop admitting (queued + new queries shed
        with DaftOverloadedError), let in-flight queries finish within the
        timeout, cancel and report stragglers, then stop the shared pool.
        Idempotent."""
        t0 = time.monotonic()
        self._closed = True
        self.admission.begin_drain()
        stragglers = self.admission.wait_drained(timeout_s)
        if stragglers:
            logger.warning("drain_stragglers", queries=stragglers)
            for qid in stragglers:
                # cancellation reaches each straggler's next partition
                # boundary; its queued-but-unstarted pool work dies now
                h = self._handles.get(qid)
                if h is not None:
                    h.cancel()
                else:
                    self.pool.cancel_queued(qid)
        remaining = max(0.0, timeout_s - (time.monotonic() - t0))
        # joining with wait=True would hang on a wedged straggler; bounded
        # join then daemon threads die with the process
        self.pool.shutdown(wait=not stragglers)
        for t in self._live_threads():
            t.join(timeout=max(0.05, remaining / max(
                1, len(self._live_threads()))))
        report = {
            "drained": not stragglers,
            "stragglers": stragglers,
            "waited_s": round(time.monotonic() - t0, 3),
            "shed_total": self.admission.shed_total,
            "admitted_total": self.admission.admitted_total,
        }
        logger.info("serving_shutdown", **{k: v for k, v in report.items()
                                           if k != "stragglers"})
        return report

    def _live_threads(self) -> List[threading.Thread]:
        with self._threads_lock:
            return [t for t in self._threads if t.is_alive()]


# ---------------------------------------------------------------------------
# engine-wide shutdown + leak accounting
# ---------------------------------------------------------------------------

def leaked_thread_count() -> int:
    """Engine-owned threads (daft-serve/exec/actor/spill prefixes) still
    alive — 0 after a clean ``shutdown()``. The serving leak test's
    assertion surface; actor-pool join leaks are also counted by
    ``actor_pool.leaked_thread_count`` with their own warning."""
    me = threading.current_thread()
    return sum(
        1 for t in threading.enumerate()
        if t is not me and t.is_alive()
        and t.name.startswith(_ENGINE_THREAD_PREFIXES))


def shutdown(timeout_s: float = 10.0) -> dict:
    """Graceful engine shutdown: drain every live ServingRuntime, stop the
    actor pools, then wait (bounded) for engine threads to exit. Returns a
    report with any stragglers and the final leaked-thread count.
    Registered atexit with a short timeout; safe to call repeatedly."""
    import gc

    t0 = time.monotonic()
    with _runtimes_lock:
        runtimes = list(_RUNTIMES)
    stragglers: List[str] = []
    for rt in runtimes:
        try:
            rep = rt.shutdown(timeout_s=max(
                0.1, timeout_s - (time.monotonic() - t0)))
            stragglers.extend(rep["stragglers"])
        except Exception as e:
            logger.error("runtime_shutdown_failed", error=repr(e))
    from ..actor_pool import shutdown_all

    shutdown_all()
    try:
        from ..dist.supervisor import shutdown_worker_pool

        # distributed worker PROCESSES die here too: zero leaked workers
        # after dt.shutdown() is part of the kill-a-worker acceptance
        shutdown_worker_pool(timeout_s=max(
            0.5, timeout_s - (time.monotonic() - t0)))
    except Exception as e:
        logger.error("worker_pool_shutdown_failed", error=repr(e))
    # private per-query pools are released by GC (their worker threads exit
    # via the executor's weakref wakeup); collect so the wait below sees it
    gc.collect()
    deadline = t0 + timeout_s
    while leaked_thread_count() and time.monotonic() < deadline:
        time.sleep(0.02)
    report = {
        "stragglers": stragglers,
        "leaked_threads": leaked_thread_count(),
        "waited_s": round(time.monotonic() - t0, 3),
    }
    logger.info("engine_shutdown", **{k: v for k, v in report.items()
                                      if k != "stragglers"})
    return report


def _atexit_shutdown() -> None:
    # bounded: a wedged straggler must not hang interpreter exit; daemon
    # threads die with the process anyway
    with _runtimes_lock:
        live = bool(_RUNTIMES)
    if live:
        shutdown(timeout_s=2.0)
        return
    try:
        import sys

        dist_mod = sys.modules.get("daft_tpu.dist.supervisor")
        if dist_mod is not None:
            # worker PROCESSES are not daemon threads: they must be told
            # to exit even when no serving runtime ever existed
            dist_mod.shutdown_worker_pool(timeout_s=2.0)
    except Exception:
        pass


atexit.register(_atexit_shutdown)
