"""Concurrent query serving runtime.

The engine executes one plan at a time per call stack; "millions of users"
means many plans at once over shared hardware. This package adds the
robustness layer between user traffic and the executor:

- ``admission.AdmissionController`` — bounded FIFO-with-slots admission in
  front of execution (``max_concurrent_queries`` slots, bounded wait queue,
  queue timeout); overflow sheds deterministically with
  ``DaftOverloadedError`` instead of piling up.
- ``qcontext.QueryContext`` — the per-query mutable execution state
  (RuntimeStats, breakers, deadline, MemoryLedger share, cancellation)
  factored OUT of the process-global context, so one poisoned query
  degrades alone.
- ``pool.SharedExecutorPool`` — one worker pool shared by every admitted
  query, with fair round-robin FIFO dispatch across queries.
- ``runtime.ServingRuntime`` — N queries concurrently over the shared pool
  and mesh, drain-mode shutdown, per-query QueryHandles.
"""

from .admission import AdmissionController
from .pool import SharedExecutorPool
from .qcontext import QueryContext
from .runtime import (QueryHandle, ServingRuntime, leaked_thread_count,
                      shutdown)

__all__ = ["AdmissionController", "QueryContext", "QueryHandle",
           "ServingRuntime", "SharedExecutorPool", "leaked_thread_count",
           "shutdown"]
