"""SharedExecutorPool: one worker pool, fairly shared by N queries.

Solo execution gives every query a private ThreadPoolExecutor; N private
pools would oversubscribe the host N-fold and let one flood of tasks from
a heavy query starve everyone behind it in a single FIFO. This pool keeps
ONE executor of ``num_workers`` threads and dispatches across per-query
FIFO queues round-robin ("fair FIFO-with-slots"): each pump picks the next
query in rotation that has work, so an admitted query always makes
progress at roughly 1/active-queries of the pool no matter how deep a
neighbor's backlog is.

Deadlock/futures contract (what the engine's pipelined-IO layer relies
on):

- ``Future.cancel()`` works while a task is still in its query's queue —
  the prefetcher/unspill-readahead "never wait on a fetch that hasn't
  started" discipline keeps working unchanged.
- A task handed to the executor occupies a real worker immediately (the
  pump only dispatches while idle workers exist), so a ``result()`` wait
  on a RUNNING future can always complete.
- ``cancel_queued(query)`` cancels everything of one query that has not
  started — cancellation propagation for shed/cancelled queries.
"""

from __future__ import annotations

import threading
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Deque, Dict, Optional, Tuple


class SharedExecutorPool:
    def __init__(self, num_workers: int):
        self.num_workers = max(1, int(num_workers))
        self._exec = ThreadPoolExecutor(
            max_workers=self.num_workers,
            thread_name_prefix="daft-serve-exec")
        self._lock = threading.Lock()
        self._queues: Dict[str, Deque[Tuple[Future, tuple]]] = {}
        self._rr: Deque[str] = deque()  # round-robin rotation of query keys
        self._idle = self.num_workers
        self._closed = False

    # ------------------------------------------------------------- clients
    def client(self, key: str) -> "_PoolClient":
        """A per-query façade with the ``submit(fn, *args)`` surface the
        ExecutionContext/scheduler/prefetcher expect from a pool."""
        with self._lock:
            if key not in self._queues:
                self._queues[key] = deque()
                self._rr.append(key)
        return _PoolClient(self, key)

    def submit(self, key: str, fn, args, kwargs) -> Future:
        fut: Future = Future()
        with self._lock:
            if self._closed:
                raise RuntimeError("worker pool already shut down")
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = deque()
                self._rr.append(key)
            q.append((fut, (fn, args, kwargs)))
        self._pump()
        return fut

    # ------------------------------------------------------------ dispatch
    def _pump(self) -> None:
        """Hand queued tasks to idle workers, one per pump step, rotating
        across queries. Runs on submitter AND completer threads; the lock
        makes each claim atomic."""
        while True:
            with self._lock:
                if self._idle <= 0 or self._closed:
                    return
                item = None
                for _ in range(len(self._rr)):
                    key = self._rr[0]
                    self._rr.rotate(-1)
                    q = self._queues.get(key)
                    while q:
                        fut, work = q.popleft()
                        # cancelled-while-queued futures settle here
                        if fut.set_running_or_notify_cancel():
                            item = (fut, work)
                            break
                    if item is not None:
                        break
                if item is None:
                    return
                self._idle -= 1
            fut, (fn, args, kwargs) = item
            try:
                self._exec.submit(self._run, fut, fn, args, kwargs)
            except RuntimeError as e:  # closed between check and submit
                with self._lock:
                    self._idle += 1
                fut.set_exception(e)
                return

    def _run(self, fut: Future, fn, args, kwargs) -> None:
        try:
            result = fn(*args, **kwargs)
        except BaseException as e:  # delivered via fut.result(), not lost
            fut.set_exception(e)
        else:
            fut.set_result(result)
        finally:
            with self._lock:
                self._idle += 1
            self._pump()

    # ------------------------------------------------------------- control
    def cancel_queued(self, key: str) -> int:
        """Cancel every not-yet-started task of one query (its running
        tasks finish; the engine's dispatch loop releases their admissions
        as usual). Returns how many were cancelled."""
        with self._lock:
            q = self._queues.get(key)
            items = list(q) if q else []
            if q:
                q.clear()
        n = 0
        for fut, _ in items:
            if fut.cancel():
                n += 1
        return n

    def remove(self, key: str) -> None:
        """Drop a finished query's queue (cancelling any stragglers)."""
        self.cancel_queued(key)
        with self._lock:
            self._queues.pop(key, None)
            try:
                self._rr.remove(key)
            except ValueError:
                pass

    def queued_tasks(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    def shutdown(self, wait: bool = True) -> None:
        with self._lock:
            self._closed = True
            pending = [it for q in self._queues.values() for it in q]
            for q in self._queues.values():
                q.clear()
        for fut, _ in pending:
            fut.cancel()
        self._exec.shutdown(wait=wait)


class _PoolClient:
    """One query's view of the shared pool. ``close()`` makes further
    submits raise RuntimeError — the same contract a shut-down private
    ThreadPoolExecutor gives the prefetch/readahead layers."""

    def __init__(self, pool: SharedExecutorPool, key: str):
        self._pool = pool
        self._key = key
        self._closed = False

    def submit(self, fn, *args, **kwargs) -> Future:
        if self._closed:
            raise RuntimeError("worker pool already shut down")
        return self._pool.submit(self._key, fn, args, kwargs)

    def shutdown(self, wait: bool = False) -> None:
        self.close()

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._pool.remove(self._key)
