"""Column/table statistics for pruning and cost estimation.

Role-equivalent to the reference's daft-stats crate
(src/daft-stats/src/column_stats/mod.rs, table_stats.rs): per-column
min/max/null_count bounds that flow from file metadata (parquet row-group stats)
through MicroPartitions to the planner, powering row-group pruning and
partition-count / join-strategy decisions.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .datatypes import DataType
from .schema import Schema


class ColumnStats:
    """Bounds for one column: [min, max] (python scalars) + null_count.

    A ``None`` field means "unknown" (missing bound), matching the reference's
    ColumnRangeStatistics::Missing.
    """

    __slots__ = ("min", "max", "null_count")

    def __init__(self, min: Any = None, max: Any = None, null_count: Optional[int] = None):
        self.min = min
        self.max = max
        self.null_count = null_count

    def __repr__(self) -> str:
        return f"ColumnStats(min={self.min!r}, max={self.max!r}, nulls={self.null_count})"

    def merge(self, other: "ColumnStats") -> "ColumnStats":
        mn = None
        if self.min is not None and other.min is not None:
            try:
                mn = min(self.min, other.min)
            except TypeError:
                mn = None
        mx = None
        if self.max is not None and other.max is not None:
            try:
                mx = max(self.max, other.max)
            except TypeError:
                mx = None
        nc = None
        if self.null_count is not None and other.null_count is not None:
            nc = self.null_count + other.null_count
        return ColumnStats(mn, mx, nc)


class TableStats:
    """Per-column stats + row count for a table/partition/file fragment."""

    __slots__ = ("columns", "num_rows", "size_bytes")

    def __init__(self, columns: Optional[Dict[str, ColumnStats]] = None,
                 num_rows: Optional[int] = None, size_bytes: Optional[int] = None):
        self.columns = columns or {}
        self.num_rows = num_rows
        self.size_bytes = size_bytes

    def __repr__(self) -> str:
        return f"TableStats(rows={self.num_rows}, bytes={self.size_bytes}, cols={list(self.columns)})"

    def merge(self, other: "TableStats") -> "TableStats":
        cols: Dict[str, ColumnStats] = {}
        for name in set(self.columns) | set(other.columns):
            a, b = self.columns.get(name), other.columns.get(name)
            if a is not None and b is not None:
                cols[name] = a.merge(b)
        nr = None
        if self.num_rows is not None and other.num_rows is not None:
            nr = self.num_rows + other.num_rows
        sb = None
        if self.size_bytes is not None and other.size_bytes is not None:
            sb = self.size_bytes + other.size_bytes
        return TableStats(cols, nr, sb)

    @staticmethod
    def merge_all(stats: List["TableStats"]) -> "TableStats":
        if not stats:
            return TableStats(num_rows=0, size_bytes=0)
        out = stats[0]
        for s in stats[1:]:
            out = out.merge(s)
        return out


# ---------------------------------------------------------------------------
# Filter evaluation against stats (row-group / partition pruning)
# ---------------------------------------------------------------------------

# Tri-state result of evaluating a predicate against bounds:
#   True  -> predicate may be true for some row (keep fragment)
#   False -> predicate is false for ALL rows (prune fragment)
# Unknown is represented as True (keep).


def filter_may_match(expr_node, stats: TableStats) -> bool:
    """Conservatively decide whether any row in a fragment with these stats can
    satisfy the predicate. Mirrors the reference's stats-based pruning in
    src/daft-scan/src/lib.rs (ScanTask pushdown + daft-stats truth tables).
    """
    res = _eval(expr_node, stats)
    return res is not False


def _eval(node, stats: TableStats):
    """Returns True (may match), False (cannot match), or None (unknown)."""
    from .expressions import Alias, BinaryOp, Column, IsNull, Literal, Not

    if isinstance(node, Alias):
        return _eval(node.child, stats)
    if isinstance(node, Not):
        inner = _eval(node.child, stats)
        # Only an *exact* False/True could be negated; our lattice loses
        # exactness, so Not() is always unknown unless the child is unknown.
        return None
    if isinstance(node, IsNull):
        return None  # null_count bound alone can't prove all-match/none-match cheaply
    if isinstance(node, BinaryOp):
        op = node.op
        if op == "&":
            l, r = _eval(node.left, stats), _eval(node.right, stats)
            if l is False or r is False:
                return False
            return None
        if op == "|":
            l, r = _eval(node.left, stats), _eval(node.right, stats)
            if l is False and r is False:
                return False
            return None
        if op in ("==", "<", "<=", ">", ">=", "!="):
            return _eval_cmp(op, node.left, node.right, stats)
    return None


def _bounds_of(node, stats: TableStats):
    """(min, max) bounds of an expression, or None if unknown."""
    from .expressions import Alias, Column, Literal

    if isinstance(node, Alias):
        return _bounds_of(node.child, stats)
    if isinstance(node, Literal):
        v = node.value
        if v is None:
            return None
        return (v, v)
    if isinstance(node, Column):
        cs = stats.columns.get(node.cname)
        if cs is None or cs.min is None or cs.max is None:
            return None
        return (cs.min, cs.max)
    return None


def _eval_cmp(op: str, left, right, stats: TableStats):
    lb = _bounds_of(left, stats)
    rb = _bounds_of(right, stats)
    if lb is None or rb is None:
        return None
    lmin, lmax = lb
    rmin, rmax = rb
    try:
        if op == "==":
            if lmax < rmin or lmin > rmax:
                return False
        elif op == "<":
            if lmin >= rmax:
                return False
        elif op == "<=":
            if lmin > rmax:
                return False
        elif op == ">":
            if lmax <= rmin:
                return False
        elif op == ">=":
            if lmax < rmin:
                return False
        elif op == "!=":
            # can only prune if both sides are single constant and equal... but
            # equal bounds still admit nulls; stay conservative
            return None
    except TypeError:
        return None
    return True
