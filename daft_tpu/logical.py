"""Logical plan: the lazy operator tree behind a DataFrame.

Role-equivalent to the reference's src/daft-plan/src/logical_plan.rs:15-33 (op
enum), logical_ops/, and builder.rs. Every node resolves and validates its
output schema at construction time, so API misuse fails at build time, not at
collect time — same contract as the reference.

Expression analysis helpers (input columns, substitution) power the optimizer
(see optimizer.py), standing in for daft-dsl's resolve_expr.rs utilities.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from .datatypes import DataType, try_unify
from .expressions import (
    AggExpr,
    Alias,
    Column,
    Expression,
    col,
)
from .schema import Field, Schema


# ---------------------------------------------------------------------------
# expression analysis
# ---------------------------------------------------------------------------

def expr_input_columns(e: Expression) -> List[str]:
    """Column names an expression reads (order of first reference)."""
    out: List[str] = []

    def walk(n):
        if isinstance(n, Column):
            if n.cname not in out:
                out.append(n.cname)
        for c in n.children():
            walk(c)

    walk(e._node)
    return out


def substitute_columns(e: Expression, mapping: Dict[str, Expression]) -> Expression:
    """Replace col(name) references with the mapped defining expressions."""

    def walk(n):
        if isinstance(n, Column) and n.cname in mapping:
            return mapping[n.cname]._node
        kids = n.children()
        if not kids:
            return n
        return n.with_children([walk(c) for c in kids])

    return Expression(walk(e._node))


def expr_has_special(e: Expression) -> bool:
    """True if the expression contains an agg or a UDF (not freely movable)."""
    from .expressions import PyUdf

    found = [False]

    def walk(n):
        if isinstance(n, (AggExpr, PyUdf)):
            found[0] = True
        for c in n.children():
            walk(c)

    walk(e._node)
    return found[0]


def is_trivial_passthrough(e: Expression) -> Optional[str]:
    """If the expression is just col(x) (possibly aliased to the same name),
    return x; else None."""
    n = e._node
    alias = None
    while isinstance(n, Alias):
        alias = n.alias
        n = n.child
    if isinstance(n, Column) and (alias is None or alias == n.cname):
        return n.cname
    return None


# ---------------------------------------------------------------------------
# plan nodes
# ---------------------------------------------------------------------------

class LogicalPlan:
    """Base class. Subclasses set .schema at construction."""

    schema: Schema

    def children(self) -> List["LogicalPlan"]:
        return []

    def with_children(self, children: List["LogicalPlan"]) -> "LogicalPlan":
        raise NotImplementedError

    def name(self) -> str:
        return type(self).__name__

    def multiline_display(self) -> List[str]:
        return [self.name()]

    # -- estimates for planning ------------------------------------------------
    def num_partitions(self) -> int:
        ch = self.children()
        return max((c.num_partitions() for c in ch), default=1)

    def approx_num_rows(self) -> Optional[int]:
        ch = self.children()
        if len(ch) == 1:
            return ch[0].approx_num_rows()
        return None

    def approx_size_bytes(self) -> Optional[int]:
        ch = self.children()
        if len(ch) == 1:
            return ch[0].approx_size_bytes()
        return None

    def display_tree(self, indent: str = "") -> str:
        lines = self.multiline_display()
        out = [indent + ("* " if indent else "") + lines[0]]
        for l in lines[1:]:
            out.append(indent + "|   " + l)
        for c in self.children():
            out.append(c.display_tree(indent + "  "))
        return "\n".join(out)

    def __repr__(self) -> str:
        return self.display_tree()


class InMemorySource(LogicalPlan):
    """Scan over already-materialized partitions (from_pydict / from_arrow).
    Reference: logical_ops/source.rs InMemoryInfo."""

    def __init__(self, schema: Schema, partitions: List[Any]):
        import uuid

        self.schema = schema
        self.partitions = partitions
        # Unique data-identity token for the result cache. id(partitions) is
        # unsound — CPython reuses ids after GC (a later frame with identical
        # plan structure would hit a stale entry); uuids are never reused.
        self._cache_token = uuid.uuid4().hex

    def with_children(self, children):
        assert not children
        return self

    def num_partitions(self) -> int:
        return max(len(self.partitions), 1)

    def approx_num_rows(self):
        try:
            return sum(len(p) for p in self.partitions)
        except Exception:
            return None

    def approx_size_bytes(self):
        try:
            return sum(p.size_bytes() or 0 for p in self.partitions)
        except Exception:
            return None

    def multiline_display(self):
        return [f"InMemorySource: {len(self.partitions)} partitions",
                f"Schema = {self.schema.short_repr()}"]


class ScanSource(LogicalPlan):
    """Scan over files via ScanTasks. Pushdowns live on the tasks and are
    installed by the optimizer. Reference: daft-scan ScanExternalInfo."""

    def __init__(self, schema: Schema, tasks: List[Any]):
        self.file_schema = schema
        self.tasks = tasks
        # visible schema reflects column pushdowns (uniform across tasks)
        self.schema = tasks[0].materialized_schema if tasks else schema

    def with_children(self, children):
        assert not children
        return self

    def with_pushdowns(self, pushdowns) -> "ScanSource":
        return ScanSource(self.file_schema, [t.with_pushdowns(pushdowns) for t in self.tasks])

    def pushdowns(self):
        from .io.scan import Pushdowns

        return self.tasks[0].pushdowns if self.tasks else Pushdowns()

    def num_partitions(self) -> int:
        return max(len(self.tasks), 1)

    def approx_num_rows(self):
        total = 0
        for t in self.tasks:
            n = t.num_rows()
            if n is None:
                return None
            total += n
        return total

    def approx_size_bytes(self):
        total = 0
        for t in self.tasks:
            n = t.size_bytes()
            if n is None:
                return None
            total += n
        return total

    def multiline_display(self):
        lines = [f"ScanSource: {len(self.tasks)} tasks"]
        if self.tasks:
            lines.append(f"Format = {self.tasks[0].format}")
            pd = self.pushdowns()
            if not pd.is_empty():
                lines.append(f"Pushdowns = {pd!r}")
        lines.append(f"Schema = {self.schema.short_repr()}")
        return lines


class UnaryNode(LogicalPlan):
    def __init__(self, input: LogicalPlan):
        self.input = input

    def children(self):
        return [self.input]


class Project(UnaryNode):
    def __init__(self, input: LogicalPlan, exprs: List[Expression]):
        super().__init__(input)
        self.exprs = exprs
        fields = []
        seen = set()
        for e in exprs:
            f = e._node.to_field(input.schema)
            f = Field(e.name(), f.dtype)
            if f.name in seen:
                raise ValueError(f"duplicate column name {f.name!r} in projection")
            seen.add(f.name)
            fields.append(f)
        self.schema = Schema(fields)

    def with_children(self, c):
        return Project(c[0], self.exprs)

    def multiline_display(self):
        return ["Project: " + ", ".join(e._node.display() for e in self.exprs)]


class Filter(UnaryNode):
    def __init__(self, input: LogicalPlan, predicate: Expression):
        super().__init__(input)
        f = predicate._node.to_field(input.schema)
        if not (f.dtype.is_boolean() or f.dtype.is_null()):
            raise ValueError(f"filter predicate must be boolean, got {f.dtype}")
        self.predicate = predicate
        self.schema = input.schema

    def with_children(self, c):
        return Filter(c[0], self.predicate)

    def multiline_display(self):
        return [f"Filter: {self.predicate._node.display()}"]


class Limit(UnaryNode):
    def __init__(self, input: LogicalPlan, limit: int, eager: bool = True):
        super().__init__(input)
        self.limit = int(limit)
        self.eager = eager
        self.schema = input.schema

    def with_children(self, c):
        return Limit(c[0], self.limit, self.eager)

    def approx_num_rows(self):
        n = self.input.approx_num_rows()
        return min(n, self.limit) if n is not None else self.limit

    def multiline_display(self):
        return [f"Limit: {self.limit}"]


class Sort(UnaryNode):
    def __init__(self, input: LogicalPlan, sort_by: List[Expression],
                 descending: List[bool], nulls_first: List[Optional[bool]]):
        super().__init__(input)
        for e in sort_by:
            f = e._node.to_field(input.schema)
            if not f.dtype.is_comparable():
                raise ValueError(f"cannot sort by {f.dtype}")
        self.sort_by = sort_by
        self.descending = descending
        self.nulls_first = nulls_first
        self.schema = input.schema

    def with_children(self, c):
        return Sort(c[0], self.sort_by, self.descending, self.nulls_first)

    def multiline_display(self):
        keys = ", ".join(
            f"{e._node.display()}{' desc' if d else ''}" for e, d in zip(self.sort_by, self.descending)
        )
        return [f"Sort: {keys}"]


class Repartition(UnaryNode):
    """scheme: 'hash' | 'random' | 'range' | 'into' (coalesce/split without shuffle)."""

    def __init__(self, input: LogicalPlan, scheme: str, num: Optional[int],
                 by: Optional[List[Expression]] = None,
                 descending: Optional[List[bool]] = None):
        super().__init__(input)
        if scheme not in ("hash", "random", "range", "into"):
            raise ValueError(f"unknown repartition scheme {scheme!r}")
        if scheme == "hash" and not by:
            raise ValueError("hash repartition requires partition-by expressions")
        self.scheme = scheme
        self.num = num
        self.by = by or []
        self.descending = descending or [False] * len(self.by)
        self.schema = input.schema

    def with_children(self, c):
        return Repartition(c[0], self.scheme, self.num, self.by, self.descending)

    def num_partitions(self) -> int:
        return self.num if self.num is not None else self.input.num_partitions()

    def multiline_display(self):
        by = ", ".join(e._node.display() for e in self.by)
        return [f"Repartition: {self.scheme} num={self.num}" + (f" by=[{by}]" if by else "")]


class Distinct(UnaryNode):
    def __init__(self, input: LogicalPlan, subset: Optional[List[Expression]] = None):
        super().__init__(input)
        self.subset = subset
        self.schema = input.schema

    def with_children(self, c):
        return Distinct(c[0], self.subset)


class Sample(UnaryNode):
    def __init__(self, input: LogicalPlan, fraction: float, with_replacement: bool, seed: Optional[int]):
        super().__init__(input)
        self.fraction = fraction
        self.with_replacement = with_replacement
        self.seed = seed
        self.schema = input.schema

    def with_children(self, c):
        return Sample(c[0], self.fraction, self.with_replacement, self.seed)


class Aggregate(UnaryNode):
    def __init__(self, input: LogicalPlan, aggregations: List[Expression],
                 groupby: List[Expression]):
        super().__init__(input)
        self.aggregations = aggregations
        self.groupby = groupby
        fields = []
        seen = set()
        for e in groupby + aggregations:
            f = e._node.to_field(input.schema)
            f = Field(e.name(), f.dtype)
            if f.name in seen:
                raise ValueError(f"duplicate column {f.name!r} in aggregation output")
            seen.add(f.name)
            fields.append(f)
        self.schema = Schema(fields)

    def with_children(self, c):
        return Aggregate(c[0], self.aggregations, self.groupby)

    def approx_num_rows(self):
        return None if self.groupby else 1

    def multiline_display(self):
        lines = ["Aggregate: " + ", ".join(e._node.display() for e in self.aggregations)]
        if self.groupby:
            lines.append("Group by = " + ", ".join(e._node.display() for e in self.groupby))
        return lines


class Pivot(UnaryNode):
    def __init__(self, input: LogicalPlan, groupby: List[Expression], pivot_col: Expression,
                 value_col: Expression, agg_fn: str, names: List[str]):
        super().__init__(input)
        self.groupby = groupby
        self.pivot_col = pivot_col
        self.value_col = value_col
        self.agg_fn = agg_fn
        self.names = names
        vf = AggExpr(agg_fn, value_col._node).to_field(input.schema)
        fields = [Field(e.name(), e._node.to_field(input.schema).dtype) for e in groupby]
        fields += [Field(str(n), vf.dtype) for n in names]
        self.schema = Schema(fields)

    def with_children(self, c):
        return Pivot(c[0], self.groupby, self.pivot_col, self.value_col, self.agg_fn, self.names)


def join_output_schema(left: Schema, right: Schema, left_on: List[Expression],
                       right_on: List[Expression], how: str, suffix: str = "right.") -> Schema:
    """Schema of a join output; must stay in lockstep with Table.hash_join."""
    if how in ("semi", "anti"):
        return left
    lk_names = [e.name() for e in left_on]
    rk_names = [e.name() for e in right_on]
    fields: List[Field] = []
    left_names = set(left.field_names())
    for i, ln in enumerate(lk_names):
        lf = left_on[i]._node.to_field(left)
        rf = right_on[i]._node.to_field(right)
        u = try_unify(lf.dtype, rf.dtype)
        if u is None:
            raise ValueError(f"cannot join on {lf.dtype} vs {rf.dtype}")
        fields.append(Field(ln, u))
    for f in left:
        if f.name not in lk_names:
            fields.append(f)
    for f in right:
        if f.name in rk_names:
            continue
        name = f.name if f.name not in left_names else f"{suffix}{f.name}"
        fields.append(Field(name, f.dtype))
    return Schema(fields)


class Join(LogicalPlan):
    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 left_on: List[Expression], right_on: List[Expression],
                 how: str = "inner", strategy: Optional[str] = None,
                 suffix: str = "right."):
        if how not in ("inner", "left", "right", "outer", "semi", "anti", "cross"):
            raise ValueError(f"unknown join type {how!r}")
        if strategy not in (None, "hash", "sort_merge", "broadcast"):
            raise ValueError(f"unknown join strategy {strategy!r}")
        if how == "cross":
            if left_on or right_on:
                raise ValueError("cross join takes no keys")
        elif not left_on or len(left_on) != len(right_on):
            raise ValueError("join requires equal-length left_on/right_on")
        self.left = left
        self.right = right
        self.left_on = left_on
        self.right_on = right_on
        self.how = how
        self.strategy = strategy
        self.suffix = suffix
        if how == "cross":
            fields = list(left.schema)
            lnames = set(left.schema.field_names())
            for f in right.schema:
                nm = f.name if f.name not in lnames else f"{suffix}{f.name}"
                fields.append(Field(nm, f.dtype))
            self.schema = Schema(fields)
        else:
            self.schema = join_output_schema(left.schema, right.schema, left_on, right_on, how, suffix)

    def children(self):
        return [self.left, self.right]

    def with_children(self, c):
        return Join(c[0], c[1], self.left_on, self.right_on, self.how, self.strategy, self.suffix)

    def num_partitions(self) -> int:
        return max(self.left.num_partitions(), self.right.num_partitions())

    def approx_num_rows(self):
        return None

    def multiline_display(self):
        on = ", ".join(
            f"{l._node.display()}={r._node.display()}" for l, r in zip(self.left_on, self.right_on)
        )
        return [f"Join: {self.how}" + (f" on {on}" if on else "")
                + (f" [{self.strategy}]" if self.strategy else "")]


class Concat(LogicalPlan):
    def __init__(self, input: LogicalPlan, other: LogicalPlan):
        if input.schema.field_names() != other.schema.field_names():
            raise ValueError(
                f"concat schema mismatch: {input.schema.field_names()} vs {other.schema.field_names()}")
        fields = []
        for a, b in zip(input.schema, other.schema):
            u = try_unify(a.dtype, b.dtype)
            if u is None:
                raise ValueError(f"concat column {a.name!r}: {a.dtype} vs {b.dtype}")
            fields.append(Field(a.name, u))
        self.input = input
        self.other = other
        self.schema = Schema(fields)

    def children(self):
        return [self.input, self.other]

    def with_children(self, c):
        return Concat(c[0], c[1])

    def num_partitions(self) -> int:
        return self.input.num_partitions() + self.other.num_partitions()

    def approx_num_rows(self):
        a, b = self.input.approx_num_rows(), self.other.approx_num_rows()
        return a + b if a is not None and b is not None else None


class Explode(UnaryNode):
    def __init__(self, input: LogicalPlan, to_explode: List[Expression]):
        super().__init__(input)
        self.to_explode = to_explode
        names = {e.name() for e in to_explode}
        fields = []
        for f in input.schema:
            if f.name in names:
                if not f.dtype.is_list():
                    raise ValueError(f"cannot explode non-list column {f.name!r} ({f.dtype})")
                fields.append(Field(f.name, f.dtype.inner))
            else:
                fields.append(f)
        self.schema = Schema(fields)

    def with_children(self, c):
        return Explode(c[0], self.to_explode)


class Unpivot(UnaryNode):
    def __init__(self, input: LogicalPlan, ids: List[Expression], values: List[Expression],
                 variable_name: str, value_name: str):
        super().__init__(input)
        if not values:
            raise ValueError("unpivot requires at least one value column")
        self.ids = ids
        self.values = values
        self.variable_name = variable_name
        self.value_name = value_name
        vdt = None
        for e in values:
            dt = e._node.to_field(input.schema).dtype
            vdt = dt if vdt is None else try_unify(vdt, dt)
            if vdt is None:
                raise ValueError("unpivot value columns have incompatible types")
        fields = [Field(e.name(), e._node.to_field(input.schema).dtype) for e in ids]
        fields.append(Field(variable_name, DataType.string()))
        fields.append(Field(value_name, vdt))
        self.schema = Schema(fields)

    def with_children(self, c):
        return Unpivot(c[0], self.ids, self.values, self.variable_name, self.value_name)


class MonotonicallyIncreasingId(UnaryNode):
    def __init__(self, input: LogicalPlan, column_name: str = "id"):
        super().__init__(input)
        self.column_name = column_name
        self.schema = Schema([Field(column_name, DataType.uint64())] + list(input.schema))

    def with_children(self, c):
        return MonotonicallyIncreasingId(c[0], self.column_name)


class Write(UnaryNode):
    def __init__(self, input: LogicalPlan, root_dir: str, format: str = "parquet",
                 compression: Optional[str] = None,
                 partition_cols: Optional[List[Expression]] = None):
        super().__init__(input)
        self.root_dir = root_dir
        self.format = format
        self.compression = compression
        self.partition_cols = partition_cols
        fields = [Field("path", DataType.string())]
        for e in partition_cols or []:
            f = e._node.to_field(input.schema)
            fields.append(Field(e.name(), f.dtype))
        self.schema = Schema(fields)

    def with_children(self, c):
        return Write(c[0], self.root_dir, self.format, self.compression, self.partition_cols)

    def multiline_display(self):
        return [f"Write: {self.format} -> {self.root_dir}"]
