"""SQL frontend: tokenizer + recursive-descent planner onto the DataFrame API.

Role-equivalent to the reference's src/daft-sql/src/planner.rs:74 (SQLPlanner
-> LogicalPlanBuilder over a SQLCatalog of registered dataframes) and
planner.rs:910 (sql_expr for single expressions). Ground-up design: a small
hand-rolled lexer and precedence-climbing expression parser — no external
sqlparser — planning directly against daft_tpu DataFrames.

Supported surface (mirrors the reference's function-module coverage,
src/daft-sql/src/modules/): SELECT [DISTINCT] with aliases, FROM tables and
(subquery) aliases, INNER/LEFT/RIGHT/FULL/CROSS JOIN with ON equi-conditions
or USING(...), WHERE, GROUP BY (exprs / positions / select aliases), HAVING,
ORDER BY [ASC|DESC] [NULLS FIRST|LAST], LIMIT, aggregates incl. COUNT(*),
COUNT(DISTINCT x) and compound agg expressions (SUM(x)*2), CASE, CAST,
BETWEEN, IN, LIKE/ILIKE, IS [NOT] NULL, COALESCE/NULLIF/IF, and a scalar
function library over the numeric/string/temporal namespaces.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from .datatypes import DataType
from .expressions import Expression, col, lit

# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<comment>--[^\n]*)
  | (?P<number>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?)
  | (?P<string>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*")
  | (?P<ident>[A-Za-z_][A-Za-z_0-9]*)
  | (?P<op><=>|<>|!=|<=|>=|\|\||<<|>>|[-+*/%<>=(),.\[\]])
""", re.VERBOSE)


class Token:
    __slots__ = ("kind", "value", "pos")

    def __init__(self, kind: str, value: str, pos: int):
        self.kind = kind
        self.value = value
        self.pos = pos

    def __repr__(self):
        return f"Token({self.kind},{self.value!r})"


def tokenize(text: str) -> List[Token]:
    out: List[Token] = []
    i = 0
    while i < len(text):
        m = _TOKEN_RE.match(text, i)
        if not m:
            raise ValueError(f"SQL syntax error at position {i}: {text[i:i+20]!r}")
        i = m.end()
        kind = m.lastgroup
        if kind in ("ws", "comment"):
            continue
        val = m.group()
        if kind == "ident":
            out.append(Token("ident", val, m.start()))
        elif kind == "string":
            out.append(Token("string", val[1:-1].replace("''", "'"), m.start()))
        elif kind == "qident":
            out.append(Token("ident", val[1:-1].replace('""', '"'), m.start()))
        else:
            out.append(Token(kind, val, m.start()))
    out.append(Token("eof", "", len(text)))
    return out


_TYPE_NAMES = {
    "TINYINT": DataType.int8, "SMALLINT": DataType.int16,
    "INT": DataType.int32, "INTEGER": DataType.int32,
    "BIGINT": DataType.int64, "LONG": DataType.int64,
    "FLOAT": DataType.float32, "REAL": DataType.float32,
    "DOUBLE": DataType.float64,
    "TEXT": DataType.string, "VARCHAR": DataType.string, "STRING": DataType.string,
    "BOOL": DataType.bool, "BOOLEAN": DataType.bool,
    "DATE": DataType.date, "BINARY": DataType.binary, "BYTES": DataType.binary,
}

_AGG_FNS = {"SUM", "AVG", "MEAN", "MIN", "MAX", "COUNT", "STDDEV", "STDDEV_SAMP",
            "ANY_VALUE", "APPROX_COUNT_DISTINCT", "COUNT_DISTINCT", "LIST", "ARRAY_AGG"}

_CLAUSE_KWS = ("FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "UNION",
               "JOIN", "ON", "AND", "OR", "USING", "INNER", "LEFT", "RIGHT",
               "FULL", "CROSS", "AS", "ASC", "DESC", "NULLS")

# words that may never be parsed as a bare column reference
_RESERVED = set(_CLAUSE_KWS) | {"SELECT", "BY", "DISTINCT", "WHEN", "THEN",
                                "ELSE", "END", "IS", "IN", "BETWEEN", "LIKE",
                                "ILIKE", "NOT"}


class Parser:
    """Recursive-descent parser; `catalog` maps table name -> DataFrame."""

    def __init__(self, tokens: List[Token], catalog: Dict[str, "object"]):
        self.toks = tokens
        self.i = 0
        self.catalog = {k.lower(): v for k, v in catalog.items()}
        # qualifier -> {source column -> actual output column} (joins rename
        # right-side duplicates with the "right." suffix)
        self._alias_cols: Dict[str, Dict[str, str]] = {}

    # -- token helpers ------------------------------------------------------
    def peek(self, k: int = 0) -> Token:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "ident" and t.value.upper() in kws

    def eat_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.eat_kw(kw):
            raise ValueError(f"expected {kw} at {self.peek().value!r}")

    def eat_op(self, op: str) -> bool:
        if self.peek().kind == "op" and self.peek().value == op:
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.eat_op(op):
            raise ValueError(f"expected {op!r} at {self.peek().value!r}")

    # -- expressions --------------------------------------------------------
    def parse_expr(self) -> Expression:
        return self._or()

    def _or(self) -> Expression:
        e = self._and()
        while self.eat_kw("OR"):
            e = e | self._and()
        return e

    def _and(self) -> Expression:
        e = self._not()
        while self.eat_kw("AND"):
            e = e & self._not()
        return e

    def _not(self) -> Expression:
        if self.eat_kw("NOT"):
            return ~self._not()
        return self._predicate()

    def _predicate(self) -> Expression:
        e = self._additive()
        saw_cmp = False
        while True:
            neg = False
            save = self.i
            if self.eat_kw("NOT"):
                if self.at_kw("IN", "BETWEEN", "LIKE", "ILIKE"):
                    neg = True
                else:
                    self.i = save
                    break
            if self.eat_kw("IS"):
                isnot = self.eat_kw("NOT")
                self.expect_kw("NULL")
                e = e.not_null() if isnot else e.is_null()
            elif self.eat_kw("BETWEEN"):
                lo = self._additive()
                self.expect_kw("AND")
                hi = self._additive()
                e = e.between(lo, hi)
                if neg:
                    e = ~e
            elif self.eat_kw("IN"):
                self.expect_op("(")
                items = [self._literal_value()]
                while self.eat_op(","):
                    items.append(self._literal_value())
                self.expect_op(")")
                e = e.is_in(items)
                if neg:
                    e = ~e
            elif self.at_kw("LIKE", "ILIKE"):
                insensitive = self.next().value.upper() == "ILIKE"
                pat = self.next()
                if pat.kind != "string":
                    raise ValueError("LIKE requires a string literal pattern")
                e = e.str.ilike(pat.value) if insensitive else e.str.like(pat.value)
                if neg:
                    e = ~e
            elif self.peek().kind == "op" and self.peek().value in (
                    "=", "<>", "!=", "<", "<=", ">", ">=", "<=>"):
                if saw_cmp:
                    raise ValueError(
                        "chained comparisons (a < b < c) are not valid SQL; "
                        "use AND")
                saw_cmp = True
                op = self.next().value
                r = self._additive()
                if op == "=":
                    e = e == r
                elif op in ("<>", "!="):
                    e = e != r
                elif op == "<":
                    e = e < r
                elif op == "<=":
                    e = e <= r
                elif op == ">":
                    e = e > r
                elif op == ">=":
                    e = e >= r
                else:
                    e = e.eq_null_safe(r)
            else:
                break
        return e

    def _literal_value(self):
        """IN-list item: a bare python literal."""
        t = self.peek()
        if t.kind == "number":
            self.next()
            return _num(t.value)
        if t.kind == "string":
            self.next()
            return t.value
        if self.eat_kw("NULL"):
            return None
        if self.eat_kw("TRUE"):
            return True
        if self.eat_kw("FALSE"):
            return False
        if self.eat_op("-"):
            tt = self.next()
            if tt.kind != "number":
                raise ValueError("bad IN-list literal")
            return -_num(tt.value)
        raise ValueError(f"IN list supports literals only, got {t.value!r}")

    def _additive(self) -> Expression:
        e = self._mult()
        while True:
            if self.eat_op("+"):
                e = e + self._mult()
            elif self.eat_op("-"):
                e = e - self._mult()
            elif self.eat_op("||"):
                e = e + self._mult()  # string concat
            else:
                return e

    def _mult(self) -> Expression:
        e = self._unary()
        while True:
            if self.eat_op("*"):
                e = e * self._unary()
            elif self.eat_op("/"):
                e = e / self._unary()
            elif self.eat_op("%"):
                e = e % self._unary()
            elif self.eat_op("<<"):
                e = e.shift_left(self._unary())
            elif self.eat_op(">>"):
                e = e.shift_right(self._unary())
            else:
                return e

    def _unary(self) -> Expression:
        if self.eat_op("-"):
            return -self._unary()
        if self.eat_op("+"):
            return self._unary()
        return self._primary()

    def _primary(self) -> Expression:
        t = self.peek()
        if t.kind == "number":
            self.next()
            return lit(_num(t.value))
        if t.kind == "string":
            self.next()
            return lit(t.value)
        if self.eat_op("("):
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind != "ident":
            raise ValueError(f"unexpected token {t.value!r}")
        up = t.value.upper()
        if up == "NULL":
            self.next()
            return lit(None)
        if up == "TRUE":
            self.next()
            return lit(True)
        if up == "FALSE":
            self.next()
            return lit(False)
        if up == "DATE" and self.peek(1).kind == "string":
            self.next()
            import datetime

            return lit(datetime.date.fromisoformat(self.next().value))
        if up == "TIMESTAMP" and self.peek(1).kind == "string":
            self.next()
            import datetime

            return lit(datetime.datetime.fromisoformat(self.next().value))
        if up == "CAST":
            self.next()
            self.expect_op("(")
            e = self.parse_expr()
            self.expect_kw("AS")
            dt = self._type_name()
            self.expect_op(")")
            return e.cast(dt)
        if up == "CASE":
            return self._case()
        if self.peek(1).kind == "op" and self.peek(1).value == "(":
            return self._function_call()
        if up in _RESERVED:
            raise ValueError(f"expected expression, got keyword {t.value!r}")
        # qualified (alias.column) or plain column reference
        self.next()
        name = t.value
        if self.eat_op("."):
            sub = self.next()
            if sub.kind != "ident":
                raise ValueError(f"expected column after {name}.")
            m = self._alias_cols.get(name.lower())
            if m is not None:
                if sub.value not in m:
                    raise ValueError(
                        f"column {sub.value!r} not found in table {name!r}")
                return col(m[sub.value])
            # select list parses before FROM: defer resolution (see
            # _resolve_qualified in _apply_projection)
            return col(f"{name}\x00{sub.value}")
        return col(name)

    def _case(self) -> Expression:
        self.expect_kw("CASE")
        base = None
        if not self.at_kw("WHEN"):
            base = self.parse_expr()
        arms: List[Tuple[Expression, Expression]] = []
        while self.eat_kw("WHEN"):
            c = self.parse_expr()
            if base is not None:
                c = base == c
            self.expect_kw("THEN")
            v = self.parse_expr()
            arms.append((c, v))
        default = lit(None)
        if self.eat_kw("ELSE"):
            default = self.parse_expr()
        self.expect_kw("END")
        out = default
        for c, v in reversed(arms):
            out = c.if_else(v, out)
        return out

    def _type_name(self) -> DataType:
        t = self.next()
        if t.kind != "ident":
            raise ValueError(f"expected type name, got {t.value!r}")
        up = t.value.upper()
        if up in _TYPE_NAMES:
            return _TYPE_NAMES[up]()
        raise ValueError(f"unknown SQL type {t.value!r}")

    def _function_call(self) -> Expression:
        name = self.next().value
        up = name.upper()
        self.expect_op("(")
        if up == "COUNT" and self.eat_op("*"):
            self.expect_op(")")
            # '*' placeholder column is bound to the first input column at
            # planning time (_apply_projection), counting every row.
            return col("*").count(mode="all").alias("count")
        distinct = False
        if up in _AGG_FNS and self.eat_kw("DISTINCT"):
            distinct = True
        args: List[Expression] = []
        if not self.eat_op(")"):
            args.append(self.parse_expr())
            while self.eat_op(","):
                args.append(self.parse_expr())
            self.expect_op(")")
        return _apply_function(up, args, distinct)


def _num(text: str):
    if re.fullmatch(r"\d+", text):
        return int(text)
    return float(text)


_SCALAR_FNS = {
    "ABS": lambda a: a[0].abs(),
    "CEIL": lambda a: a[0].ceil(), "CEILING": lambda a: a[0].ceil(),
    "FLOOR": lambda a: a[0].floor(),
    "SIGN": lambda a: a[0].sign(),
    "ROUND": lambda a: a[0].round(_lit_val(a[1]) if len(a) > 1 else 0),
    "SQRT": lambda a: a[0].sqrt(),
    "CBRT": lambda a: a[0].cbrt(),
    "EXP": lambda a: a[0].exp(),
    "LN": lambda a: a[0].ln(),
    "LOG": lambda a: a[0].log(_lit_val(a[1])) if len(a) > 1 else a[0].log(),
    "LOG2": lambda a: a[0].log2(),
    "LOG10": lambda a: a[0].log10(),
    "SIN": lambda a: a[0].sin(), "COS": lambda a: a[0].cos(), "TAN": lambda a: a[0].tan(),
    "ASIN": lambda a: a[0].arcsin(), "ACOS": lambda a: a[0].arccos(),
    "ATAN": lambda a: a[0].arctan(),
    "RADIANS": lambda a: a[0].radians(), "DEGREES": lambda a: a[0].degrees(),
    "POW": lambda a: a[0] ** a[1], "POWER": lambda a: a[0] ** a[1],
    "UPPER": lambda a: a[0].str.upper(), "LOWER": lambda a: a[0].str.lower(),
    "LENGTH": lambda a: a[0].str.length(),
    "TRIM": lambda a: a[0].str.lstrip().str.rstrip(),
    "LTRIM": lambda a: a[0].str.lstrip(), "RTRIM": lambda a: a[0].str.rstrip(),
    "REVERSE": lambda a: a[0].str.reverse(),
    "CAPITALIZE": lambda a: a[0].str.capitalize(),
    "CONTAINS": lambda a: a[0].str.contains(a[1]),
    "STARTS_WITH": lambda a: a[0].str.startswith(a[1]),
    "ENDS_WITH": lambda a: a[0].str.endswith(a[1]),
    "REGEXP_MATCH": lambda a: a[0].str.match(a[1]),
    "REPLACE": lambda a: a[0].str.replace(a[1], a[2]),
    "SPLIT": lambda a: a[0].str.split(a[1]),
    "SUBSTR": lambda a: a[0].str.substr(a[1] - 1, a[2] if len(a) > 2 else None),
    "SUBSTRING": lambda a: a[0].str.substr(a[1] - 1, a[2] if len(a) > 2 else None),
    "CONCAT": lambda a: _chain_add(a),
    "LPAD": lambda a: a[0].str.lpad(_lit_val(a[1]), _lit_val(a[2])),
    "RPAD": lambda a: a[0].str.rpad(_lit_val(a[1]), _lit_val(a[2])),
    "YEAR": lambda a: a[0].dt.year(), "MONTH": lambda a: a[0].dt.month(),
    "DAY": lambda a: a[0].dt.day(), "HOUR": lambda a: a[0].dt.hour(),
    "MINUTE": lambda a: a[0].dt.minute(), "SECOND": lambda a: a[0].dt.second(),
    "DAY_OF_WEEK": lambda a: a[0].dt.day_of_week(),
    "COALESCE": lambda a: _coalesce(a),
    "IF": lambda a: a[0].if_else(a[1], a[2]),
    "IIF": lambda a: a[0].if_else(a[1], a[2]),
    "NULLIF": lambda a: (a[0] == a[1]).if_else(lit(None), a[0]),
    "HASH": lambda a: a[0].hash(),
    "MURMUR3_32": lambda a: a[0]._fn("murmur3_32"),
}


def _lit_val(e: Expression):
    from .expressions import Literal

    if not isinstance(e._node, Literal):
        raise ValueError("expected a literal argument")
    return e._node.value


def _chain_add(args: List[Expression]) -> Expression:
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


def _coalesce(args: List[Expression]) -> Expression:
    out = args[-1]
    for a in reversed(args[:-1]):
        out = a.fill_null(out)
    return out


def _apply_function(up: str, args: List[Expression], distinct: bool) -> Expression:
    if up in _AGG_FNS:
        if distinct:
            if up != "COUNT":
                raise ValueError(f"DISTINCT not supported for {up}")
            return args[0].count_distinct()
        if up == "SUM":
            return args[0].sum()
        if up in ("AVG", "MEAN"):
            return args[0].mean()
        if up == "MIN":
            return args[0].min()
        if up == "MAX":
            return args[0].max()
        if up == "COUNT":
            return args[0].count()
        if up in ("STDDEV", "STDDEV_SAMP"):
            return args[0].stddev()
        if up == "ANY_VALUE":
            return args[0].any_value()
        if up == "APPROX_COUNT_DISTINCT":
            return args[0].approx_count_distinct()
        if up in ("LIST", "ARRAY_AGG"):
            return args[0].agg_list()
    if up == "COUNT_DISTINCT":
        return args[0].count_distinct()
    if up in _SCALAR_FNS:
        return _SCALAR_FNS[up](args)
    raise ValueError(f"unknown SQL function {up!r}")


# ---------------------------------------------------------------------------
# Query planner
# ---------------------------------------------------------------------------

class _SelectItem:
    __slots__ = ("expr", "alias", "star")

    def __init__(self, expr=None, alias=None, star=False):
        self.expr = expr
        self.alias = alias
        self.star = star


def _is_agg_tree(node) -> bool:
    return node.is_aggregation()


class QueryPlanner(Parser):
    def parse_query(self):
        df = self._select_stmt()
        if self.peek().kind != "eof":
            raise ValueError(f"trailing tokens at {self.peek().value!r}")
        return df

    def _select_stmt(self):
        # parse every clause first, then plan (ORDER BY may reference columns
        # the projection drops, so sort placement depends on the whole query)
        self.expect_kw("SELECT")
        distinct = self.eat_kw("DISTINCT")
        items = self._select_list()
        if self.eat_kw("FROM"):
            df = self._from_clause()
        else:
            from .api import from_pydict

            df = from_pydict({"__no_from__": [0]})
        if self.eat_kw("WHERE"):
            df = df.where(self.parse_expr())
        group_exprs: Optional[List[Expression]] = None
        if self.eat_kw("GROUP"):
            self.expect_kw("BY")
            group_exprs = [self._group_item(items, df)]
            while self.eat_op(","):
                group_exprs.append(self._group_item(items, df))
        having = None
        if self.eat_kw("HAVING"):
            having = self.parse_expr()
        order_keys: List[Expression] = []
        desc: List[bool] = []
        nf: List[Optional[bool]] = []
        if self.eat_kw("ORDER"):
            self.expect_kw("BY")
            while True:
                order_keys.append(self._order_item(items))
                d = False
                if self.eat_kw("DESC"):
                    d = True
                else:
                    self.eat_kw("ASC")
                n = None
                if self.eat_kw("NULLS"):
                    if self.eat_kw("FIRST"):
                        n = True
                    else:
                        self.expect_kw("LAST")
                        n = False
                desc.append(d)
                nf.append(n)
                if not self.eat_op(","):
                    break
        limit = None
        if self.eat_kw("LIMIT"):
            t = self.next()
            if t.kind != "number":
                raise ValueError("LIMIT requires a number")
            limit = int(t.value)
        df = self._apply_projection(df, items, group_exprs, having,
                                    order_keys, desc, nf, distinct)
        if limit is not None:
            df = df.limit(limit)
        return df

    def _select_list(self) -> List[_SelectItem]:
        items = []
        while True:
            if self.eat_op("*"):
                items.append(_SelectItem(star=True))
            else:
                e = self.parse_expr()
                alias = None
                if self.eat_kw("AS"):
                    a = self.next()
                    if a.kind != "ident":
                        raise ValueError("expected alias after AS")
                    alias = a.value
                elif (self.peek().kind == "ident"
                      and self.peek().value.upper() not in _CLAUSE_KWS):
                    alias = self.next().value
                items.append(_SelectItem(expr=e, alias=alias))
            if not self.eat_op(","):
                return items

    def _from_clause(self):
        df, alias = self._table_factor()
        self._register_alias(alias, df)
        while True:
            if self.eat_kw("CROSS"):
                self.expect_kw("JOIN")
                how = "cross"
            elif self.eat_kw("INNER"):
                self.expect_kw("JOIN")
                how = "inner"
            elif self.at_kw("LEFT", "RIGHT", "FULL"):
                side = self.next().value.upper()
                self.eat_kw("OUTER")
                self.expect_kw("JOIN")
                how = {"LEFT": "left", "RIGHT": "right", "FULL": "outer"}[side]
            elif self.eat_kw("JOIN"):
                how = "inner"
            elif self.eat_op(","):
                how = "cross"
            else:
                return df
            right, ralias = self._table_factor()
            self._register_alias(ralias, right)
            pre_left = set(df.column_names)
            if how == "cross":
                df = df.join(right, how="cross")
                self._remap_right_alias(ralias, right, pre_left, {})
                continue
            if self.eat_kw("USING"):
                self.expect_op("(")
                cols = [self.next().value]
                while self.eat_op(","):
                    cols.append(self.next().value)
                self.expect_op(")")
                df = df.join(right, on=cols, how=how)
                self._remap_right_alias(ralias, right, pre_left,
                                        {c: c for c in cols})
                continue
            self.expect_kw("ON")
            left_on, right_on, extra = self._join_condition(df, right)
            if extra is not None and how != "inner":
                raise ValueError(
                    "non-equi conditions in an OUTER JOIN ON clause are not "
                    "supported (a post-join filter would change the join "
                    "semantics); move the condition to WHERE if inner "
                    "semantics are intended")
            df = df.join(right, left_on=left_on, right_on=right_on, how=how)
            self._remap_right_alias(
                ralias, right, pre_left,
                {r.name(): l.name() for l, r in zip(left_on, right_on)})
            if extra is not None:
                df = df.where(extra)

    def _table_factor(self):
        if self.eat_op("("):
            sub = self._select_stmt()
            self.expect_op(")")
            alias = self._opt_alias()
            return sub, alias
        t = self.next()
        if t.kind != "ident":
            raise ValueError(f"expected table name, got {t.value!r}")
        name = t.value.lower()
        if name not in self.catalog:
            raise ValueError(f"unknown table {t.value!r} "
                             f"(catalog: {sorted(self.catalog)})")
        alias = self._opt_alias() or name
        return self.catalog[name], alias

    def _opt_alias(self) -> Optional[str]:
        if self.eat_kw("AS"):
            return self.next().value
        if (self.peek().kind == "ident"
                and self.peek().value.upper() not in _CLAUSE_KWS):
            return self.next().value
        return None

    def _remap_right_alias(self, ralias: Optional[str], right, pre_left: set,
                           key_map: Dict[str, str]) -> None:
        """After a join, the right table's columns may have been renamed
        (key columns take the left name; duplicates get the 'right.' suffix) —
        keep the qualifier map pointing at the actual output columns."""
        if not ralias:
            return
        m: Dict[str, str] = {}
        for c in right.column_names:
            if c in key_map:
                m[c] = key_map[c]
            elif c in pre_left:
                m[c] = f"right.{c}"
            else:
                m[c] = c
        self._alias_cols[ralias.lower()] = m

    def _register_alias(self, alias: Optional[str], df) -> None:
        if alias:
            self.catalog.setdefault(alias.lower(), df)
            self._alias_cols.setdefault(
                alias.lower(), {c: c for c in df.column_names})

    def _join_condition(self, left_df, right_df):
        """Parse `a.x = b.y [AND ...]` into key lists; non-equi terms become a
        post-filter."""
        lcols = set(left_df.column_names)
        rcols = set(right_df.column_names)
        left_on: List[Expression] = []
        right_on: List[Expression] = []
        extra = None
        while True:
            e1 = self._predicate()
            matched = False
            from .expressions import BinaryOp, Column

            n = e1._node
            if isinstance(n, BinaryOp) and n.op == "==" \
                    and isinstance(n.left, Column) and isinstance(n.right, Column):
                a, b = n.left.cname, n.right.cname
                if a in lcols and b in rcols:
                    left_on.append(col(a))
                    right_on.append(col(b))
                    matched = True
                elif b in lcols and a in rcols:
                    left_on.append(col(b))
                    right_on.append(col(a))
                    matched = True
            if not matched:
                extra = e1 if extra is None else (extra & e1)
            if not self.eat_kw("AND"):
                break
        if not left_on:
            raise ValueError("JOIN ON requires at least one equi-condition")
        return left_on, right_on, extra

    def _group_item(self, items: List[_SelectItem], df) -> Expression:
        t = self.peek()
        if t.kind == "number":
            self.next()
            idx = int(t.value) - 1
            if idx < 0 or idx >= len(items) or items[idx].star:
                raise ValueError(f"GROUP BY position {t.value} out of range")
            return items[idx].expr
        e = self.parse_expr()
        from .expressions import Column

        if isinstance(e._node, Column) and e._node.cname not in df.column_names:
            # not an input column: try a select-list alias (input wins, per SQL)
            for it in items:
                if it.alias == e._node.cname and it.expr is not None:
                    return it.expr
        return e

    def _order_item(self, items: List[_SelectItem]) -> Expression:
        t = self.peek()
        if t.kind == "number":
            self.next()
            idx = int(t.value) - 1
            if idx < 0 or idx >= len(items) or items[idx].star:
                raise ValueError(f"ORDER BY position {t.value} out of range")
            it = items[idx]
            return col(it.alias) if it.alias else it.expr
        return self.parse_expr()

    def _resolve_qualified(self, node):
        """Resolve deferred alias.column refs (select list parses before FROM)."""
        from .expressions import Column

        if isinstance(node, Column) and "\x00" in node.cname:
            q, c = node.cname.split("\x00", 1)
            m = self._alias_cols.get(q.lower())
            if m is None:
                raise ValueError(f"unknown table alias {q!r}")
            if c not in m:
                raise ValueError(f"column {c!r} not found in table {q!r}")
            return col(m[c])._node
        kids = node.children()
        if not kids:
            return node
        return node.with_children([self._resolve_qualified(c) for c in kids])

    def _apply_projection(self, df, items: List[_SelectItem],
                          group_exprs: Optional[List[Expression]],
                          having: Optional[Expression],
                          order_keys: List[Expression],
                          desc: List[bool], nf: List[Optional[bool]],
                          distinct: bool = False):
        # expand stars; bind COUNT(*)'s '*' placeholder to the first column;
        # resolve deferred alias.column refs now that FROM is planned
        first_col = df.column_names[0]
        exprs: List[Expression] = []
        alias_map: Dict[str, Expression] = {}
        for it in items:
            if it.star:
                exprs.extend(col(n) for n in df.column_names)
            else:
                e = Expression(self._resolve_qualified(
                    _resolve_star(it.expr._node, first_col)))
                if it.alias:
                    alias_map[it.alias] = e
                    e = e.alias(it.alias)
                exprs.append(e)
        if having is not None:
            having = Expression(self._resolve_qualified(
                _resolve_star(having._node, first_col)))
        order_keys = [Expression(self._resolve_qualified(
            _resolve_star(k._node, first_col))) for k in order_keys]
        nulls_first = nf if any(x is not None for x in nf) else None
        out_names = [e.name() for e in exprs]
        has_agg = any(_is_agg_tree(e._node) for e in exprs) or any(
            _is_agg_tree(k._node) for k in order_keys)
        if group_exprs is None and not has_agg:
            if having is not None:
                raise ValueError("HAVING requires GROUP BY or aggregates")
            if distinct:
                # DISTINCT dedupes the projected rows (hash-shuffled, so the
                # sort must come after); ORDER BY may only use selected columns
                out = df.select(*exprs).distinct()
                if order_keys:
                    keys = [Expression(_subst_aliases(k._node, alias_map, []))
                            for k in order_keys]
                    for k in keys:
                        if not _refs_only_keys(k._node, out_names):
                            raise ValueError(
                                "ORDER BY with DISTINCT must reference "
                                "selected columns")
                    out = out.sort(keys, desc=desc, nulls_first=nulls_first)
                return out
            if order_keys:
                # sort BEFORE projecting: ORDER BY may reference input columns
                # the projection drops; select aliases resolve to their exprs
                keys = [Expression(_subst_aliases(k._node, alias_map, df.column_names))
                        for k in order_keys]
                df = df.sort(keys, desc=desc, nulls_first=nulls_first)
            return df.select(*exprs)
        # aggregate path: pull every AggExpr subtree out as a synthetic agg
        # column, aggregate once, then compute finals/HAVING/ORDER BY as plain
        # arithmetic over synthetic columns (compound items like SUM(x)*2 work).
        keys = group_exprs or []
        key_names = [k.name() for k in keys]
        key_by_key = {k._node._key(): k.name() for k in keys}
        agg_map: Dict = {}
        agg_list: List[Expression] = []

        def rewrite(e: Expression) -> Expression:
            return Expression(_pull_aggs(e._node, key_by_key, agg_map, agg_list))

        finals = [rewrite(e).alias(e.name()) for e in exprs]
        having_final = rewrite(having) if having is not None else None
        order_final = []
        for k in order_keys:
            n = _subst_aliases(k._node, alias_map, [])
            order_final.append(rewrite(Expression(n)))
        for e, f in zip(exprs, finals):
            if not _is_agg_tree(e._node):
                # non-aggregate item must be (derived from) a group key
                from .expressions import Alias

                n = f._node
                while isinstance(n, Alias):
                    n = n.child
                if not _refs_only_keys(n, key_names):
                    raise ValueError(
                        f"non-aggregate select item {e.name()!r} must appear in GROUP BY")
        if keys:
            gdf = df.groupby(*keys).agg(*agg_list) if agg_list else df.distinct(*keys)
        else:
            gdf = df.agg(*agg_list)
        if having_final is not None:
            gdf = gdf.where(having_final)
        if distinct:
            out = gdf.select(*finals).distinct()
            if order_final:
                for k in order_final:
                    if not _refs_only_keys(k._node, out_names):
                        raise ValueError("ORDER BY with DISTINCT must "
                                         "reference selected columns")
                out = out.sort(order_final, desc=desc, nulls_first=nulls_first)
            return out
        if order_final:
            gdf = gdf.sort(order_final, desc=desc, nulls_first=nulls_first)
        return gdf.select(*finals)


def _pull_aggs(node, key_by_key: Dict, agg_map: Dict, agg_list: List[Expression]):
    """Replace group-key subtrees and AggExpr subtrees with column refs,
    recording synthetic agg outputs in agg_list."""
    from .expressions import AggExpr, Expression as E

    if node._key() in key_by_key:
        return col(key_by_key[node._key()])._node
    if isinstance(node, AggExpr):
        k = node._key()
        if k not in agg_map:
            name = f"__agg_{len(agg_map)}"
            agg_map[k] = name
            agg_list.append(E(node).alias(name))
        return col(agg_map[k])._node
    return node.with_children([_pull_aggs(c, key_by_key, agg_map, agg_list)
                               for c in node.children()])


def _subst_aliases(node, alias_map: Dict[str, Expression], input_cols):
    """Resolve a bare column ref to its select-alias definition (input columns
    take precedence when the name exists in the input schema)."""
    from .expressions import Column

    if isinstance(node, Column):
        if node.cname in alias_map and node.cname not in input_cols:
            return alias_map[node.cname]._node
        return node
    kids = node.children()
    if not kids:
        return node
    return node.with_children([_subst_aliases(c, alias_map, input_cols)
                               for c in kids])


def _resolve_star(node, first_col: str):
    from .expressions import Column

    if isinstance(node, Column) and node.cname == "*":
        return col(first_col)._node
    kids = node.children()
    if not kids:
        return node
    return node.with_children([_resolve_star(c, first_col) for c in kids])


def _refs_only_keys(node, key_names: List[str]) -> bool:
    from .expressions import Column

    if isinstance(node, Column):
        return node.cname in key_names
    kids = node.children()
    if not kids:
        return True
    return all(_refs_only_keys(c, key_names) for c in kids)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def sql(query: str, **catalog):
    """Plan a SQL query over registered DataFrames: sql("SELECT ...", tbl=df)."""
    if not catalog:
        raise ValueError("register at least one table: sql(query, name=df)")
    return QueryPlanner(tokenize(query), catalog).parse_query()


def sql_expr(text: str) -> Expression:
    """Parse a single SQL expression to an Expression."""
    p = Parser(tokenize(text), {})
    e = p.parse_expr()
    if p.peek().kind != "eof":
        raise ValueError(f"trailing tokens at {p.peek().value!r}")
    return e
