"""SQL frontend (placeholder — full planner lands with the SQL milestone).

Role-equivalent to the reference's src/daft-sql/src/planner.rs:74. The real
implementation (recursive-descent parser -> LogicalPlanBuilder) replaces this
module; until then both entry points raise with a clear message.
"""

from __future__ import annotations


def sql(query: str, **catalog):
    raise NotImplementedError("daft_tpu.sql is not wired up yet in this build")


def sql_expr(text: str):
    raise NotImplementedError("daft_tpu.sql_expr is not wired up yet in this build")
