"""Bounded-memory execution: spillable partition buffers.

The reference completes TPC-H SF1000 on a single node at a 16x
data-to-memory ratio (docs/source/faq/benchmarks.rst:111-124) by keeping
MicroPartitions lazy and spilling pipeline-breaker state. Here, every
pipeline breaker that must hold many partitions (shuffle fanout buckets,
join builds, sort-merge buckets) accumulates into a PartitionBuffer: once
the process-wide in-memory budget (ExecutionConfig.memory_budget_bytes) is
exceeded, further partitions are written as arrow IPC files in a per-query
spill directory and handed back as UNLOADED MicroPartitions — the consumer
re-materializes them one at a time, so peak engine-held memory stays at
(budget + one working partition).

Accounting is engine-level (sum of buffered partition byte sizes tracked by
a process-wide ledger with a high-water mark), which tests can assert
exactly — RSS would be dominated by the jax runtime."""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
from typing import List, Optional

from .micropartition import MicroPartition


class MemoryLedger:
    """Process-wide account of bytes held by partition buffers."""

    def __init__(self):
        self._lock = threading.Lock()
        self.current = 0
        self.high_water = 0
        self.spilled_bytes = 0
        self.spilled_partitions = 0

    def add(self, n: int) -> None:
        with self._lock:
            self.current += n
            self.high_water = max(self.high_water, self.current)

    def sub(self, n: int) -> None:
        with self._lock:
            self.current -= n

    def spilled(self, n: int) -> None:
        with self._lock:
            self.spilled_bytes += n
            self.spilled_partitions += 1

    def reset(self) -> None:
        with self._lock:
            self.current = 0
            self.high_water = 0
            self.spilled_bytes = 0
            self.spilled_partitions = 0


MEMORY_LEDGER = MemoryLedger()

_SPILL_LOCK = threading.Lock()
_SPILL_SEQ = [0]
# IPC body codec for spill files. None = uncompressed: writes land in the
# page cache at memcpy speed and mmap re-reads are zero-copy; the kernel
# writes dirty pages back asynchronously. "lz4" trades one-core compress
# CPU for ~35% fewer dirty bytes — worth it only when spill volume outruns
# RAM so the disk itself gates. A/B at SF10 on this host (r5, two
# interleaved trials): uncompressed 34.8/32.2s vs lz4 46.4/34.3s.
_SPILL_CODEC: Optional[str] = None


class SpillScope:
    """Per-query spill directory, owned by the ExecutionContext so nested
    executions (AQE stages) never delete each other's files.

    File slots are RECYCLED: a consumed spill file's path returns to a
    free-list and the next spill overwrites it. Overwriting a recently
    written path reuses pages the guest already owns, while a fresh file
    faults brand-new pages — measured on this (ballooned) host: 534 MB of
    IPC spill writes take 4.7 s to fresh names vs 0.5-1.1 s over reused
    names. Safety: recycled slots are only handed out after the one
    materialization copied the bytes out (see _SpillSlotTask)."""

    def __init__(self):
        self._dir: Optional[str] = None
        self._free_slots: List[str] = []
        self._slot_gen: dict = {}
        self._lock = threading.Lock()

    def take_slot(self) -> Optional[str]:
        with self._lock:
            if not self._free_slots:
                return None
            path = self._free_slots.pop()
            # a new generation of bytes will own this path: readers holding
            # the previous generation must not re-read it (they check
            # generation() against the value they observed at recycle time)
            self._slot_gen[path] = self._slot_gen.get(path, 0) + 1
            return path

    def recycle(self, path: str) -> None:
        with self._lock:
            # drop paths from a cleaned/rotated directory: a late task GC
            # after cleanup() must not feed dead paths to the next query
            if self._dir is not None and path.startswith(self._dir + os.sep):
                self._free_slots.append(path)

    def generation(self, path: str) -> int:
        with self._lock:
            return self._slot_gen.get(path, 0)

    def dir(self) -> str:
        with self._lock:
            if self._dir is None or not os.path.isdir(self._dir):
                self._dir = tempfile.mkdtemp(prefix="daft_tpu_spill_")
            return self._dir

    def cleanup(self) -> None:
        with self._lock:
            if self._dir is not None:
                shutil.rmtree(self._dir, ignore_errors=True)
                self._dir = None
            self._free_slots.clear()
            self._slot_gen.clear()


class _SpillSlotTask:
    """Scan task for a recycled-slot spill file: ONE file materialization,
    by copy. The read goes through plain file reads (page-cache warm, no
    mmap) so no live buffer can alias the slot, then the path returns to
    the scope's free-list for the next spill to overwrite.

    The slot returns to the free-list when the TASK is garbage-collected
    (weakref.finalize in _try_spill), i.e. when no MicroPartition can
    reach it anymore — so a live reference always implies an un-reused
    slot, and re-reads are always safe. In the normal single-consumer
    flow the consuming MicroPartition drops its task reference at load,
    which recycles at exactly the hand-off point; forked references
    (e.g. `p.head(n)` narrows the task while `p` still points at it)
    keep the slot pinned until the last of them loads or dies. The read
    result is additionally held by WEAKREF so forked consumers share one
    file read without the cache pinning memory past its consumers (the
    spill budget is never silently defeated by a hidden strong cache)."""

    def __init__(self, path: str, schema, num_rows: int, size_bytes: int,
                 scope: SpillScope):
        self.path = path
        self.schema = schema
        self.num_rows_exact = num_rows
        # captured at spill time: the live file stops describing THIS
        # partition the moment the slot recycles
        self.size_bytes_exact = size_bytes
        self.stats = None
        self._scope = scope
        self._cached_ref = None
        # generation observed when the slot was taken for THIS partition:
        # read() asserts it is unchanged (a re-take while we are alive
        # would mean the free-list violated the GC-recycle invariant)
        self._slot_gen: int = scope.generation(path)
        self._read_lock = threading.Lock()

    # --- ScanTask metadata surface used by MicroPartition ----------------
    @property
    def materialized_schema(self):
        return self.schema

    def num_rows(self) -> Optional[int]:
        return self.num_rows_exact

    def size_bytes(self) -> Optional[int]:
        return self.size_bytes_exact

    def read(self):
        import pyarrow as pa
        import weakref

        from .io.readers import IO_STATS
        from .table import Table

        with self._read_lock:
            if self._cached_ref is not None:
                tbl = self._cached_ref()
                if tbl is not None:
                    return tbl
            # invariant: this task is alive (we are in its method), so its
            # slot has NOT been recycled — recycling happens only at task
            # GC (weakref.finalize in _try_spill). A generation mismatch
            # means the free-list handed the path out while a reference
            # still existed; make that loud, never silently another
            # partition's bytes.
            if self._scope.generation(self.path) != self._slot_gen:
                from .errors import DaftInternalError

                raise DaftInternalError(
                    f"spill slot {self.path} was re-taken while a live "
                    "reference could still read it; this is an engine bug")
            with pa.OSFile(self.path) as f:
                arrow_tbl = pa.ipc.open_file(f).read_all()
            IO_STATS.bump(files_opened=1, bytes_read=arrow_tbl.nbytes,
                          rows_read=arrow_tbl.num_rows,
                          columns_read=arrow_tbl.num_columns)
            tbl = Table.from_arrow(arrow_tbl)
            self._cached_ref = weakref.ref(tbl)
            return tbl

    # head() on an unloaded partition narrows the task's limit; spill tasks
    # support that surface by applying the pushdowns to the one read
    @property
    def pushdowns(self):
        from .io.scan import Pushdowns

        return Pushdowns()

    def with_pushdowns(self, pd):
        return _SpillSlotView(self, pd)

    def __repr__(self) -> str:
        return f"_SpillSlotTask({self.path}, rows={self.num_rows_exact})"


class _SpillSlotView:
    """A pushdown applied over a spill slot's single read."""

    def __init__(self, task: _SpillSlotTask, pd):
        self._task = task
        self.pushdowns = pd
        self.schema = task.schema
        self.stats = None

    @property
    def materialized_schema(self):
        if self.pushdowns.columns is None:
            return self._task.materialized_schema
        return self.schema.select(
            [c for c in self.pushdowns.columns if c in self.schema])

    def num_rows(self) -> Optional[int]:
        n = self._task.num_rows()
        if n is None:
            return None
        if self.pushdowns.filters is not None:
            return None
        if self.pushdowns.limit is not None:
            return min(n, self.pushdowns.limit)
        return n

    def size_bytes(self) -> Optional[int]:
        return self._task.size_bytes()

    def with_pushdowns(self, pd):
        return _SpillSlotView(self._task, pd)

    def read(self):
        tbl = self._task.read()
        pd = self.pushdowns
        if pd.columns is not None:
            # same order contract as ScanTask.materialized_schema: pushdown
            # column order wins
            keep = [c for c in pd.columns if c in tbl.schema.field_names()]
            tbl = tbl.select_columns(keep)
        if pd.filters is not None:
            from .expressions import Expression

            tbl = tbl.filter(Expression(pd.filters))
        if pd.limit is not None and len(tbl) > pd.limit:
            tbl = tbl.slice(0, pd.limit)
        return tbl


class PartitionBuffer:
    """Append MicroPartitions; past the budget they spill to arrow IPC files
    and come back lazy. Iterating yields partitions in append order (spilled ones as
    Unloaded MicroPartitions that re-read on demand)."""

    def __init__(self, budget_bytes: Optional[int], stats=None,
                 scope: Optional[SpillScope] = None):
        self.budget = budget_bytes
        self.stats = stats
        self.scope = scope or SpillScope()
        self._items: List[MicroPartition] = []
        self._held: List[int] = []

    def append(self, part: MicroPartition) -> None:
        size = part.size_bytes() or 0
        if (self.budget is not None and len(part)
                and MEMORY_LEDGER.current + size > self.budget):
            spilled = self._try_spill(part, size)
            if spilled is not None:
                self._items.append(spilled)
                self._held.append(0)
                return
        MEMORY_LEDGER.add(size)
        self._items.append(part)
        self._held.append(size)

    def _try_spill(self, part: MicroPartition, size: int) -> Optional[MicroPartition]:
        import pyarrow as pa

        path = self.scope.take_slot()
        if path is None:
            with _SPILL_LOCK:
                _SPILL_SEQ[0] += 1
                seq = _SPILL_SEQ[0]
            path = os.path.join(self.scope.dir(), f"spill_{seq}.arrow")
        # chunk-wise write: a multi-piece shuffle bucket (chained per-chunk
        # splits) streams each piece as its own record batch — the bucket is
        # never concatenated just to be spilled
        tbls = part.chunk_tables()
        nrows = 0
        try:
            from . import faults

            faults.check("spill.write", self.stats)
            # arrow IPC spills (codec per _SPILL_CODEC above): parquet spills
            # paid a full encode+decode round-trip per partition; IPC writes
            # land in the page cache at memcpy speed and the consumer reads
            # them back through warm page-cache file reads (_SpillSlotTask).
            atbls = [t.to_arrow() for t in tbls]
            schema = atbls[0].schema
            opts = pa.ipc.IpcWriteOptions(compression=_SPILL_CODEC)
            with pa.OSFile(path, "wb") as f, \
                    pa.ipc.new_file(f, schema, options=opts) as w:
                for at in atbls:
                    if at.schema != schema:
                        at = at.cast(schema)
                    w.write_table(at)
                    nrows += at.num_rows
        except Exception:
            # python-object columns have no arrow representation — and a
            # full/failing spill disk looks the same: hold in memory rather
            # than fail the query; the slot (with whatever partial bytes)
            # goes back on the free-list for the next spill to overwrite
            if self.stats is not None:
                self.stats.bump("spill_write_failures")
            self.scope.recycle(path)
            return None
        MEMORY_LEDGER.spilled(size)
        if self.stats is not None:
            self.stats.bump("spilled_partitions")
        try:
            file_bytes = os.path.getsize(path)
        except OSError:
            file_bytes = size
        task = _SpillSlotTask(path, tbls[0].schema, nrows, file_bytes,
                              self.scope)
        # the slot recycles when nothing can read it anymore: task GC, not
        # first-read, so forked references never race the free-list
        import weakref

        weakref.finalize(task, self.scope.recycle, path)
        return MicroPartition.from_scan_task(task)

    def __len__(self) -> int:
        return len(self._items)

    def __iter__(self):
        return iter(self._items)

    def parts(self) -> List[MicroPartition]:
        return list(self._items)

    def drain(self):
        """Yield partitions in append order, dropping each internal ref as it
        is handed out, so a spilled partition's re-materialized table lives
        only for the consumer's one iteration (out-of-core discipline: the
        buffer never re-pins the whole input)."""
        for i in range(len(self._items)):
            part, self._items[i] = self._items[i], None
            MEMORY_LEDGER.sub(self._held[i])
            self._held[i] = 0
            yield part
        self._items = []
        self._held = []

    def release(self) -> None:
        """Return held bytes to the ledger and drop partition refs (call when
        the buffer's contents have been consumed downstream)."""
        MEMORY_LEDGER.sub(sum(self._held))
        self._items = []
        self._held = []
