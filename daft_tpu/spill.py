"""Bounded-memory execution: spillable partition buffers with pipelined IO.

The reference completes TPC-H SF1000 on a single node at a 16x
data-to-memory ratio (docs/source/faq/benchmarks.rst:111-124) by keeping
MicroPartitions lazy and spilling pipeline-breaker state. Here, every
pipeline breaker that must hold many partitions (shuffle fanout buckets,
join builds, sort-merge buckets) accumulates into a PartitionBuffer: once
the process-wide in-memory budget (ExecutionConfig.memory_budget_bytes) is
exceeded, further partitions are written as arrow IPC files in a per-query
spill directory and handed back as UNLOADED MicroPartitions — the consumer
re-materializes them one at a time, so peak engine-held memory stays at
(budget + one working partition).

Pipelining (the BENCH_r05 out-of-core lesson — scan decode, spill writes
and unspill reads were all serialized with compute):

- **async spill writeback** (cfg.async_spill_writes): the arrow-IPC write
  runs on a bounded per-query writer thread, so a breaker appending past
  the budget keeps fanning out instead of stalling on disk; the partition's
  chunk tables stay resident (accounted in ``async_spill_inflight``) until
  the write lands, and a failed write degrades to the same hold-in-memory
  fallback the synchronous path has always had. Writer-internal errors
  (engine bugs, not write failures) surface at the next
  ``check_deadline``/drain barrier, never in a dead thread.
- **unspill readahead** (cfg.unspill_readahead): while the consumer works
  on partition i of a drain, partition i+1's read-back runs on the shared
  executor pool (one slot — classic double buffering); whole next buckets
  preload via ``preload()`` on the shuffle reduce side. Errors from a
  background read-back re-raise on the consumer thread at the hand-off.

Accounting is engine-level (sum of buffered partition byte sizes tracked by
a process-wide ledger with a high-water mark), which tests can assert
exactly — RSS would be dominated by the jax runtime. Scan-prefetch
readahead (io/prefetch.py) charges the same ledger so the two readahead
layers share one budget."""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
import time
from typing import Callable, List, Optional

from .errors import DaftTransientError
from .micropartition import MicroPartition
from .obs.log import current_query_id, get_logger, query_context

logger = get_logger("spill")

# marks pool threads running BACKGROUND IO (unspill readahead): a spill
# read-back on one of them is overlap, not consumer wait, so it must not
# count into io_wait_ns
_BG_IO = threading.local()


def _in_background_io() -> bool:
    return getattr(_BG_IO, "active", False)


class MemoryLedger:
    """Account of bytes held by partition buffers (plus the in-flight
    balances of the two readahead layers and spill write/read throughput
    totals, which bench.py reads per rung).

    The process-wide root (``MEMORY_LEDGER``) is the health/metrics view.
    A serving query gets a CHILD ledger (``MemoryLedger(parent=root)``)
    carved to its share of the global budget: budget decisions (spill
    thresholds, prefetch caps) read the child's balances, so one query's
    pressure can never spill — or OOM — another, while every mutation
    forwards its true delta to the parent so the process totals stay
    exact."""

    def __init__(self, parent: Optional["MemoryLedger"] = None):
        self._parent = parent
        self._lock = threading.Lock()
        self.current = 0
        self.high_water = 0
        self.spilled_bytes = 0
        self.spilled_partitions = 0
        # releases that would have driven `current` negative (double-release
        # bugs): clamped at 0, warned, and counted so leak tests can assert
        self.negative_releases = 0
        # scan-prefetch charges currently in flight. Deliberately NOT part
        # of `current`: the prefetcher caps itself against
        # current + prefetch_inflight (so readahead can never blow the
        # budget), but charging `current` would make every pipeline-breaker
        # append see a full ledger and spill its entire input — measured
        # 2x SLOWER at SF10 than no prefetch at all
        self.prefetch_inflight = 0
        # partitions handed to the async spill writer whose bytes are still
        # resident until the write lands (NOT in `current`: like the sync
        # writer's working copy, they are transient write-side state,
        # bounded by the writer queue depth)
        self.async_spill_inflight = 0
        # streaming-channel morsel bytes currently queued between producer
        # and consumer stages (stream/channel.py). NOT in `current` for the
        # prefetch_inflight reason: charging it there would make pipeline-
        # breaker appends see a full ledger and spill their whole input.
        # Bounded by channel capacity x producer window; the high-water
        # mark is the bench rung's streaming working-set peak.
        self.stream_inflight = 0
        self.stream_inflight_high_water = 0
        # coalesce-buffer bytes held by the dynamic-batching UDF executor
        # (batch/coalesce.py) between feed and flush. NOT in `current` for
        # the prefetch_inflight reason; bounded by batch_max_bytes per
        # live coalescer, settled at every flush — a nonzero balance after
        # a query is a leak (tests/test_batch.py pins zero)
        self.batch_inflight = 0
        self.batch_inflight_high_water = 0
        # fully-materialized map-task outputs parked in the scheduler's
        # dispatch window (completed, waiting behind the head-of-line task
        # for the consumer to pull): the partition-granular path's "whole
        # partitions between steps" working set, which the streaming path
        # replaces with bounded channel morsels. Charged by
        # scheduler.dispatch, released when the consumer pulls the result.
        # NOT in `current` for the prefetch_inflight reason.
        self.exec_inflight = 0
        self.exec_inflight_high_water = 0
        # partition bytes shipped to (or results awaited from) distributed
        # worker processes (dist/supervisor.py): the DRIVER's exact view of
        # payload held remotely on its behalf — cluster totals stay exact
        # even though the bytes are resident in another process. NOT in
        # `current` for the prefetch_inflight reason.
        self.dist_inflight = 0
        self.dist_inflight_high_water = 0
        # peak of current + stream_inflight + prefetch_inflight +
        # exec_inflight: the query's ledger-visible WORKING SET (buffers +
        # streaming channels + prefetched-but-unconsumed partitions +
        # parked whole-partition task outputs). The spill decision charges
        # all four against the budget, so this peak stays bounded by
        # memory_budget_bytes (+ the documented one-working-unit slack) —
        # the bench streaming rung's bounded-memory metric
        self.working_set_high_water = 0
        # spill write/read throughput totals (file bytes + wall ns)
        self.spill_write_bytes = 0
        self.spill_write_ns = 0
        self.unspill_bytes = 0
        self.unspill_ns = 0
        # ENOSPC spill writes classified as a full disk (permanent
        # DaftIOError class, degraded to hold-in-memory): the health/
        # metrics flag operators alert on — a full spill device turns a
        # bounded-memory engine back into an in-memory one
        self.disk_full_events = 0
        # process-level cache accounts (daft_tpu/adapt/): plan/program
        # cache and sub-plan result cache resident bytes. NOT in
        # `current` — they are process-lifetime state shed by their own
        # LRU caps, not per-query working set the spill machinery should
        # react to; the accounts exist so dt.health()/metrics expose
        # exactly where cache memory sits
        self.plan_cache_bytes = 0
        self.subplan_cache_bytes = 0
        # resident pinned-model weight bytes (batch/actors.ModelActorPool;
        # LRU-evicted past cfg.model_cache_bytes)
        self.model_cache_bytes = 0

    def cache_account(self, account: str, delta: int) -> None:
        """Charge/release one of the process cache accounts
        (``plan_cache_bytes`` / ``subplan_cache_bytes``); clamped at 0."""
        if account not in ("plan_cache_bytes", "subplan_cache_bytes",
                           "model_cache_bytes"):
            from .errors import DaftValueError

            raise DaftValueError(f"unknown cache account {account!r}")
        with self._lock:
            v = getattr(self, account) + delta
            setattr(self, account, max(0, v))  # daftlint: disable=DTL002
        if self._parent is not None:
            self._parent.cache_account(account, delta)

    def disk_full(self) -> None:
        with self._lock:
            self.disk_full_events += 1
        if self._parent is not None:
            self._parent.disk_full()

    def _note_working_set_locked(self) -> None:
        # runs under self._lock (every caller holds it); the lock-discipline
        # rule is lexical and cannot see through the helper
        ws = (self.current + self.stream_inflight
              + self.prefetch_inflight + self.exec_inflight
              + self.batch_inflight)
        if ws > self.working_set_high_water:
            self.working_set_high_water = ws  # daftlint: disable=DTL002

    def add(self, n: int) -> None:
        with self._lock:
            self.current += n
            self.high_water = max(self.high_water, self.current)
            self._note_working_set_locked()
        if self._parent is not None:
            self._parent.add(n)

    def sub(self, n: int) -> None:
        with self._lock:
            released = self._sub_locked(n)
        # forward only what was ACTUALLY released: a clamped double-release
        # in one query must not drain bytes other queries hold in the root
        if self._parent is not None and released:
            self._parent.sub(released)

    def _sub_locked(self, n: int) -> int:
        # runs under self._lock (every caller holds it); the lock-discipline
        # rule is lexical and cannot see through the helper
        if n > self.current:
            # double-release: clamp rather than poison every later budget
            # decision with a negative balance — but never silently
            # daftlint: disable=DTL002
            self.negative_releases += 1
            logger.warning("ledger_negative_release", released=n,
                           current=self.current)
            released, self.current = self.current, 0  # daftlint: disable=DTL002
            return released
        self.current -= n  # daftlint: disable=DTL002
        return n

    def spilled(self, n: int) -> None:
        with self._lock:
            self.spilled_bytes += n
            self.spilled_partitions += 1
        if self._parent is not None:
            self._parent.spilled(n)

    # --- scan-prefetch charges (io/prefetch.py) -------------------------
    def prefetch_started(self, n: int) -> None:
        with self._lock:
            self.prefetch_inflight += n
            self._note_working_set_locked()
        if self._parent is not None:
            self._parent.prefetch_started(n)

    def prefetch_done(self, n: int) -> None:
        with self._lock:
            done = min(n, self.prefetch_inflight)
            self.prefetch_inflight -= done
        if self._parent is not None and done:
            self._parent.prefetch_done(done)

    # --- streaming-channel charges (stream/channel.py) ------------------
    def stream_started(self, n: int) -> None:
        with self._lock:
            self.stream_inflight += n
            if self.stream_inflight > self.stream_inflight_high_water:
                self.stream_inflight_high_water = self.stream_inflight
            self._note_working_set_locked()
        if self._parent is not None:
            self._parent.stream_started(n)

    def stream_done(self, n: int) -> None:
        with self._lock:
            done = min(n, self.stream_inflight)
            self.stream_inflight -= done
        if self._parent is not None and done:
            self._parent.stream_done(done)

    # --- dynamic-batching coalesce buffers (batch/coalesce.py) ----------
    def batch_started(self, n: int) -> None:
        with self._lock:
            self.batch_inflight += n
            if self.batch_inflight > self.batch_inflight_high_water:
                self.batch_inflight_high_water = self.batch_inflight
            self._note_working_set_locked()
        if self._parent is not None:
            self._parent.batch_started(n)

    def batch_done(self, n: int) -> None:
        with self._lock:
            done = min(n, self.batch_inflight)
            self.batch_inflight -= done
        if self._parent is not None and done:
            self._parent.batch_done(done)

    # --- parked partition-task outputs (scheduler.dispatch) -------------
    def exec_started(self, n: int) -> None:
        with self._lock:
            self.exec_inflight += n
            if self.exec_inflight > self.exec_inflight_high_water:
                self.exec_inflight_high_water = self.exec_inflight
            self._note_working_set_locked()
        if self._parent is not None:
            self._parent.exec_started(n)

    def exec_done(self, n: int) -> None:
        with self._lock:
            done = min(n, self.exec_inflight)
            self.exec_inflight -= done
        if self._parent is not None and done:
            self._parent.exec_done(done)

    # --- distributed-worker in-flight payload (dist/supervisor.py) ------
    def dist_started(self, n: int) -> None:
        with self._lock:
            self.dist_inflight += n
            if self.dist_inflight > self.dist_inflight_high_water:
                self.dist_inflight_high_water = self.dist_inflight
        if self._parent is not None:
            self._parent.dist_started(n)

    def dist_done(self, n: int) -> None:
        with self._lock:
            done = min(n, self.dist_inflight)
            self.dist_inflight -= done
        if self._parent is not None and done:
            self._parent.dist_done(done)

    # --- async spill writeback ------------------------------------------
    def async_spill_started(self, n: int) -> None:
        with self._lock:
            self.async_spill_inflight += n
        if self._parent is not None:
            self._parent.async_spill_started(n)

    def async_spill_done(self, n: int) -> None:
        with self._lock:
            self.async_spill_inflight = max(0, self.async_spill_inflight - n)
            self.spilled_bytes += n
            self.spilled_partitions += 1
        if self._parent is not None:
            self._parent.async_spill_done(n)

    def async_spill_abandoned(self, n: int) -> None:
        """The write was never submitted (writer closed): nothing in flight."""
        with self._lock:
            self.async_spill_inflight = max(0, self.async_spill_inflight - n)
        if self._parent is not None:
            self._parent.async_spill_abandoned(n)

    def async_spill_failed(self, n: int) -> None:
        """Write failed -> the partition is genuinely held in memory after
        all: its bytes move from the in-flight balance into `current` (the
        holding task's finalizer returns them)."""
        with self._lock:
            self.async_spill_inflight = max(0, self.async_spill_inflight - n)
            self.current += n
            self.high_water = max(self.high_water, self.current)
        if self._parent is not None:
            self._parent.async_spill_failed(n)

    # --- spill IO throughput --------------------------------------------
    def record_spill_write(self, nbytes: int, ns: int) -> None:
        with self._lock:
            self.spill_write_bytes += nbytes
            self.spill_write_ns += ns
        if self._parent is not None:
            self._parent.record_spill_write(nbytes, ns)

    def record_unspill(self, nbytes: int, ns: int) -> None:
        with self._lock:
            self.unspill_bytes += nbytes
            self.unspill_ns += ns
        if self._parent is not None:
            self._parent.record_unspill(nbytes, ns)

    def reset(self) -> None:
        with self._lock:
            self.current = 0
            self.high_water = 0
            self.spilled_bytes = 0
            self.spilled_partitions = 0
            self.negative_releases = 0
            self.prefetch_inflight = 0
            self.async_spill_inflight = 0
            self.stream_inflight = 0
            self.stream_inflight_high_water = 0
            self.batch_inflight = 0
            self.batch_inflight_high_water = 0
            self.exec_inflight = 0
            self.exec_inflight_high_water = 0
            self.working_set_high_water = 0
            self.spill_write_bytes = 0
            self.spill_write_ns = 0
            self.unspill_bytes = 0
            self.unspill_ns = 0
            self.disk_full_events = 0

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "current": self.current,
                "high_water": self.high_water,
                "spilled_bytes": self.spilled_bytes,
                "spilled_partitions": self.spilled_partitions,
                "negative_releases": self.negative_releases,
                "prefetch_inflight": self.prefetch_inflight,
                "async_spill_inflight": self.async_spill_inflight,
                "stream_inflight": self.stream_inflight,
                "stream_inflight_high_water": self.stream_inflight_high_water,
                "batch_inflight": self.batch_inflight,
                "batch_inflight_high_water": self.batch_inflight_high_water,
                "exec_inflight": self.exec_inflight,
                "exec_inflight_high_water": self.exec_inflight_high_water,
                "dist_inflight": self.dist_inflight,
                "dist_inflight_high_water": self.dist_inflight_high_water,
                "working_set_high_water": self.working_set_high_water,
                "spill_write_bytes": self.spill_write_bytes,
                "spill_write_ns": self.spill_write_ns,
                "unspill_bytes": self.unspill_bytes,
                "unspill_ns": self.unspill_ns,
                "disk_full_events": self.disk_full_events,
                "plan_cache_bytes": self.plan_cache_bytes,
                "subplan_cache_bytes": self.subplan_cache_bytes,
                "model_cache_bytes": self.model_cache_bytes,
            }


MEMORY_LEDGER = MemoryLedger()

_SPILL_LOCK = threading.Lock()
_SPILL_SEQ = [0]
# IPC body codec for spill files. None = uncompressed: writes land in the
# page cache at memcpy speed and mmap re-reads are zero-copy; the kernel
# writes dirty pages back asynchronously. "lz4" trades one-core compress
# CPU for ~35% fewer dirty bytes — worth it only when spill volume outruns
# RAM so the disk itself gates. A/B at SF10 on this host (r5, two
# interleaved trials): uncompressed 34.8/32.2s vs lz4 46.4/34.3s.
_SPILL_CODEC: Optional[str] = None
# max arrow-IPC writes queued/in-flight on the async writer before append()
# exerts backpressure — bounds dirty not-yet-durable partition bytes to
# roughly this many working partitions
_SPILL_WRITER_DEPTH = 4


class AsyncSpillWriter:
    """Bounded single-thread writer for async spill writeback.

    ``submit`` blocks (backpressure) while _SPILL_WRITER_DEPTH jobs are
    already queued/in-flight — that wait is the breaker's only disk stall,
    and it is counted into io_wait_ns by the caller. Exceptions a job
    did not handle itself (engine bugs — write FAILURES are handled by the
    job's hold-in-memory fallback) are recorded and re-raised at the next
    check_deadline/drain barrier via ``raise_errors``."""

    def __init__(self, depth: int = _SPILL_WRITER_DEPTH):
        from concurrent.futures import ThreadPoolExecutor

        self._pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="daft-spill-writer")
        self._slots = threading.Semaphore(max(1, depth))
        self._lock = threading.Lock()
        self._errors: List[BaseException] = []
        self._closed = False

    def submit(self, job: Callable[[], None]) -> bool:
        """Queue a write job; blocks while the queue is full. False when the
        writer is already closed (caller falls back to a synchronous/held
        spill)."""
        with self._lock:
            if self._closed:
                return False
        self._slots.acquire()

        def run():
            try:
                job()
            except BaseException as e:  # job fallbacks failed: surface later
                with self._lock:
                    self._errors.append(e)
            finally:
                self._slots.release()

        try:
            self._pool.submit(run)
        except RuntimeError:  # closed between the check and the submit
            self._slots.release()
            return False
        return True

    def raise_errors(self) -> None:
        with self._lock:
            if not self._errors:
                return
            err = self._errors.pop(0)
        from .errors import DaftInternalError

        raise DaftInternalError(
            f"async spill writer failed: {err!r}") from err

    def close(self) -> None:
        """Wait for every queued write to finish, then stop the thread
        (called before the spill directory is removed)."""
        with self._lock:
            self._closed = True
        self._pool.shutdown(wait=True)


class SpillScope:
    """Per-query spill directory, owned by the ExecutionContext so nested
    executions (AQE stages) never delete each other's files.

    File slots are RECYCLED: a consumed spill file's path returns to a
    free-list and the next spill overwrites it. Overwriting a recently
    written path reuses pages the guest already owns, while a fresh file
    faults brand-new pages — measured on this (ballooned) host: 534 MB of
    IPC spill writes take 4.7 s to fresh names vs 0.5-1.1 s over reused
    names. Safety: recycled slots are only handed out after the one
    materialization copied the bytes out (see _SpillSlotTask).

    The scope also owns the query's AsyncSpillWriter (lazily created);
    cleanup() drains it before removing the directory, so no write ever
    races the rmtree."""

    def __init__(self):
        self._dir: Optional[str] = None
        self._free_slots: List[str] = []
        self._slot_gen: dict = {}
        self._writer: Optional[AsyncSpillWriter] = None
        self._lock = threading.Lock()

    def take_slot(self) -> Optional[str]:
        with self._lock:
            if not self._free_slots:
                return None
            path = self._free_slots.pop()
            # a new generation of bytes will own this path: readers holding
            # the previous generation must not re-read it (they check
            # generation() against the value they observed at recycle time)
            self._slot_gen[path] = self._slot_gen.get(path, 0) + 1
            return path

    def recycle(self, path: str) -> None:
        with self._lock:
            # drop paths from a cleaned/rotated directory: a late task GC
            # after cleanup() must not feed dead paths to the next query
            if self._dir is not None and path.startswith(self._dir + os.sep):
                self._free_slots.append(path)

    def generation(self, path: str) -> int:
        with self._lock:
            return self._slot_gen.get(path, 0)

    def dir(self) -> str:
        with self._lock:
            if self._dir is None or not os.path.isdir(self._dir):
                self._dir = tempfile.mkdtemp(prefix="daft_tpu_spill_")
            return self._dir

    def writer(self) -> AsyncSpillWriter:
        with self._lock:
            if self._writer is None:
                self._writer = AsyncSpillWriter()
            return self._writer

    def raise_async_errors(self) -> None:
        """Surface writer-internal errors at a barrier (check_deadline /
        drain). Cheap when no writer exists."""
        with self._lock:
            w = self._writer
        if w is not None:
            w.raise_errors()

    def cleanup(self) -> None:
        # drain the writer OUTSIDE the scope lock: write jobs are allowed
        # to touch scope bookkeeping, and close() waits for them
        with self._lock:
            w, self._writer = self._writer, None
        if w is not None:
            w.close()
        with self._lock:
            if self._dir is not None:
                shutil.rmtree(self._dir, ignore_errors=True)
                self._dir = None
            self._free_slots.clear()
            self._slot_gen.clear()


class _SpillSlotTask:
    """Scan task for a recycled-slot spill file: ONE file materialization,
    by copy. The read goes through plain file reads (page-cache warm, no
    mmap) so no live buffer can alias the slot, then the path returns to
    the scope's free-list for the next spill to overwrite.

    The slot returns to the free-list when the TASK is garbage-collected
    (weakref.finalize in _try_spill), i.e. when no MicroPartition can
    reach it anymore — so a live reference always implies an un-reused
    slot, and re-reads are always safe. In the normal single-consumer
    flow the consuming MicroPartition drops its task reference at load,
    which recycles at exactly the hand-off point; forked references
    (e.g. `p.head(n)` narrows the task while `p` still points at it)
    keep the slot pinned until the last of them loads or dies. The read
    result is additionally held by WEAKREF so forked consumers share one
    file read without the cache pinning memory past its consumers (the
    spill budget is never silently defeated by a hidden strong cache)."""

    def __init__(self, path: str, schema, num_rows: int, size_bytes: int,
                 scope: SpillScope, rt_stats=None, ledger=None,
                 expected_crc: Optional[int] = None,
                 lineage=None, lineage_key=None):
        self.path = path
        self.schema = schema
        self.num_rows_exact = num_rows
        # captured at spill time: the live file stops describing THIS
        # partition the moment the slot recycles
        self.size_bytes_exact = size_bytes
        # scan-task TableStats surface consumed by MicroPartition (none for
        # spill files); the per-query RuntimeStats handle lives separately
        self.stats = None
        self._rt_stats = rt_stats
        self._ledger = ledger if ledger is not None else MEMORY_LEDGER
        self._scope = scope
        self._cached_ref = None
        # generation observed when the slot was taken for THIS partition:
        # read() asserts it is unchanged (a re-take while we are alive
        # would mean the free-list violated the GC-recycle invariant)
        self._slot_gen: int = scope.generation(path)
        # serializes the one spill-file read per slot task — held
        # across that read by design (double-read = double IO)
        self._read_lock = threading.Lock()  # daftlint: io-lock
        # end-to-end integrity: crc32 of the file bytes as written (None =
        # checksums off); the read-back verifies before parsing, so a
        # rotted file raises DaftCorruptionError, never a garbled table
        self.expected_crc = expected_crc
        # lineage recovery handle: (LineageLog, recipe key) — a corrupted
        # or missing file recomputes through the recipe instead of failing
        self._lineage = lineage
        self._lineage_key = lineage_key

    # --- ScanTask metadata surface used by MicroPartition ----------------
    @property
    def materialized_schema(self):
        return self.schema

    def num_rows(self) -> Optional[int]:
        return self.num_rows_exact

    def size_bytes(self) -> Optional[int]:
        return self.size_bytes_exact

    def read(self):
        with self._read_lock:
            if self._cached_ref is not None:
                tbl = self._cached_ref()
                if tbl is not None:
                    return tbl
            from . import faults

            # each spill read-back is a fault site: injected failures must
            # reach the drain consumer, whether the read runs synchronously
            # or on the readahead pool (DTL004-covered)
            faults.check("spill.readback", self._rt_stats)
            tbl = self._materialize_locked()
            import weakref

            self._cached_ref = weakref.ref(tbl)
            return tbl

    def _materialize_locked(self):
        """File read-back (integrity-verified), called under the read
        lock. A corrupted, garbled, or missing file raises
        DaftCorruptionError — unless the lineage log still holds this
        partition's recipe, in which case the partition is RECOMPUTED
        from its source and served (``partitions_recomputed``) and the
        query never sees the damage."""
        # invariant: this task is alive (we are in its method), so its
        # slot has NOT been recycled — recycling happens only at task
        # GC (weakref.finalize in _try_spill). A generation mismatch
        # means the free-list handed the path out while a reference
        # still existed; make that loud, never silently another
        # partition's bytes.
        if self._scope.generation(self.path) != self._slot_gen:
            from .errors import DaftInternalError

            raise DaftInternalError(
                f"spill slot {self.path} was re-taken while a live "
                "reference could still read it; this is an engine bug")
        from .errors import DaftCorruptionError

        try:
            return self._read_file_locked()
        except DaftCorruptionError as e:
            tbl = self._recompute_locked(e)
            if tbl is not None:
                return tbl
            raise

    def _read_file_locked(self):
        """Verify + parse the spill file; every damage mode — checksum
        mismatch, truncated/garbled IPC stream, missing file — surfaces
        as DaftCorruptionError, never a deep arrow error."""
        import pyarrow as pa

        from .errors import DaftCorruptionError
        from .io.readers import IO_STATS
        from .table import Table

        t0 = time.perf_counter_ns()
        try:
            if self.expected_crc is not None:
                from .integrity.checksum import crc32_file

                got = crc32_file(self.path)
                if got != self.expected_crc:
                    if self._rt_stats is not None:
                        self._rt_stats.bump("corruption_detected")
                    raise DaftCorruptionError(
                        f"spill file {self.path} failed its integrity "
                        f"check (crc {got:#010x} != "
                        f"{self.expected_crc:#010x})")
            with pa.OSFile(self.path) as f:
                arrow_tbl = pa.ipc.open_file(f).read_all()
        except DaftCorruptionError:
            raise
        except FileNotFoundError as e:
            raise DaftCorruptionError(
                f"spill file {self.path} missing at unspill: {e!r}") from e
        except Exception as e:
            if self._rt_stats is not None:
                self._rt_stats.bump("corruption_detected")
            raise DaftCorruptionError(
                f"spill file {self.path} unreadable: {e!r}") from e
        dt = time.perf_counter_ns() - t0
        self._ledger.record_unspill(self.size_bytes_exact, dt)
        if self._rt_stats is not None:
            from .scheduler import on_pool_worker

            self._rt_stats.bump("spill_read_bytes", self.size_bytes_exact)
            self._rt_stats.bump("spill_read_ns", dt)
            if not _in_background_io() and not on_pool_worker():
                # the consumer thread itself blocked on this read; a read
                # on the readahead pool or inside a dispatched partition
                # task (parallel map / pooled fanout) is overlapped work,
                # not consumer wait
                self._rt_stats.io_wait(dt)
        IO_STATS.bump(files_opened=1, bytes_read=arrow_tbl.nbytes,
                      rows_read=arrow_tbl.num_rows,
                      columns_read=arrow_tbl.num_columns)
        return Table.from_arrow(arrow_tbl)

    def _recompute_locked(self, cause):
        """Lineage recovery: re-derive the partition through its recorded
        recipe. Returns the recomputed Table, or None when lineage is
        truncated (no/evicted recipe) or the recompute itself failed —
        the caller then raises the original corruption."""
        log = self._lineage
        recipe = log.get(self._lineage_key) if log is not None else None
        if recipe is None:
            if self._rt_stats is not None:
                self._rt_stats.bump("lineage_truncated")
            logger.warning("spill_lineage_truncated", path=self.path,
                           cause=repr(cause))
            return None
        try:
            tbl = _concat_chunk_tables(recipe())
        except Exception as e:
            logger.warning("lineage_recompute_failed", path=self.path,
                           error=repr(e), cause=repr(cause))
            return None
        if self._rt_stats is not None:
            self._rt_stats.bump("partitions_recomputed")
            if self._rt_stats.profiler.armed:
                self._rt_stats.profiler.event(
                    "partition_recomputed", path=self.path, rows=len(tbl))
        logger.warning("partition_recomputed", path=self.path,
                       rows=len(tbl), cause=repr(cause))
        return tbl

    # head() on an unloaded partition narrows the task's limit; spill tasks
    # support that surface by applying the pushdowns to the one read
    @property
    def pushdowns(self):
        from .io.scan import Pushdowns

        return Pushdowns()

    def with_pushdowns(self, pd):
        return _SpillSlotView(self, pd)

    def __repr__(self) -> str:
        return f"_SpillSlotTask({self.path}, rows={self.num_rows_exact})"


class _AsyncSpillSlotTask(_SpillSlotTask):
    """A spill slot whose IPC write is still in flight on the writer
    thread. Until the write lands, the partition's chunk tables stay
    resident on the task (accounted as async_spill_inflight) and a read
    serves them directly — the file is only read by consumers arriving
    after the hand-off dropped the memory copy. A failed write simply
    keeps the tables: the hold-in-memory fallback of the synchronous
    path, discovered late."""

    def __init__(self, path: str, schema, num_rows: int, size_bytes: int,
                 scope: SpillScope, tables, rt_stats=None, ledger=None,
                 reader=None, lineage=None, lineage_key=None):
        super().__init__(path, schema, num_rows, size_bytes, scope,
                         rt_stats=rt_stats, ledger=ledger,
                         lineage=lineage, lineage_key=lineage_key)
        # reader: pre-landing reads route through it instead of the tables
        # (encoded exchange payloads — `tables` then holds arrow tables the
        # engine-side concat below cannot serve, but the reader decodes)
        self._reader = reader
        self._tables = list(tables) if reader is None else None
        # keeps the encoded payload (referenced by the reader closure)
        # alive until the write lands, mirroring _tables' residency
        self._enc_tables = list(tables) if reader is not None else None
        # bytes this task holds in ledger `current` after a write failure;
        # shared with the finalizer so the charge settles exactly once
        self._held_cell = {"bytes": 0}

    def _write_done(self, file_bytes: int, crc: Optional[int] = None) -> None:
        with self._read_lock:
            self._tables = None
            self._reader = None
            self._enc_tables = None
            self.size_bytes_exact = file_bytes
            self.expected_crc = crc

    def _write_failed(self, size: int) -> None:
        with self._read_lock:
            self._held_cell["bytes"] = size

    def _materialize_locked(self):
        if self._reader is not None:
            # encoded payload still in flight (or its write failed): decode
            # from the resident encoded tables
            if self._rt_stats is not None:
                self._rt_stats.bump("spill_mem_reads")
            return self._reader()
        if self._tables is not None:
            if self._rt_stats is not None:
                self._rt_stats.bump("spill_mem_reads")
            return _concat_chunk_tables(self._tables)
        return super()._materialize_locked()

    def __repr__(self) -> str:
        return f"_AsyncSpillSlotTask({self.path}, rows={self.num_rows_exact})"


def _settle_sync_slot(scope: SpillScope, path: str, lineage,
                      lineage_key) -> None:
    """Finalizer for sync spill tasks: recycle the slot and drop the
    lineage recipe (an unreachable slot can never need recomputing)."""
    scope.recycle(path)
    if lineage is not None:
        lineage.forget(lineage_key)


def _settle_async_slot(scope: SpillScope, path: str, held_cell: dict,
                       ledger=None, lineage=None, lineage_key=None) -> None:
    """Finalizer for async spill tasks: recycle the slot, drop the lineage
    recipe, and return any hold-in-memory bytes a failed write left
    charged."""
    scope.recycle(path)
    if lineage is not None:
        lineage.forget(lineage_key)
    held = held_cell.get("bytes", 0)
    if held:
        held_cell["bytes"] = 0
        (ledger if ledger is not None else MEMORY_LEDGER).sub(held)


class _SpillSlotView:
    """A pushdown applied over a spill slot's single read."""

    def __init__(self, task: _SpillSlotTask, pd):
        self._task = task
        self.pushdowns = pd
        self.schema = task.schema
        self.stats = None

    @property
    def materialized_schema(self):
        if self.pushdowns.columns is None:
            return self._task.materialized_schema
        return self.schema.select(
            [c for c in self.pushdowns.columns if c in self.schema])

    def num_rows(self) -> Optional[int]:
        n = self._task.num_rows()
        if n is None:
            return None
        if self.pushdowns.filters is not None:
            return None
        if self.pushdowns.limit is not None:
            return min(n, self.pushdowns.limit)
        return n

    def size_bytes(self) -> Optional[int]:
        return self._task.size_bytes()

    def with_pushdowns(self, pd):
        return _SpillSlotView(self._task, pd)

    def read(self):
        tbl = self._task.read()
        pd = self.pushdowns
        if pd.columns is not None:
            # same order contract as ScanTask.materialized_schema: pushdown
            # column order wins
            keep = [c for c in pd.columns if c in tbl.schema.field_names()]
            tbl = tbl.select_columns(keep)
        if pd.filters is not None:
            from .expressions import Expression

            tbl = tbl.filter(Expression(pd.filters))
        if pd.limit is not None and len(tbl) > pd.limit:
            tbl = tbl.slice(0, pd.limit)
        return tbl


def _concat_chunk_tables(tbls):
    """Chunk list -> ONE Table, mirroring the IPC writer's chunk handling
    (every batch cast to the first chunk's schema) so memory-served and
    lineage-recomputed reads are byte-identical to the file round-trip."""
    from .table import Table

    if len(tbls) == 1:
        return tbls[0]
    s0 = tbls[0].schema
    tbls = [t if t.schema == s0 else t.cast_to_schema(s0) for t in tbls]
    return Table.concat(tbls)


def _is_disk_full(e: BaseException) -> bool:
    import errno

    return isinstance(e, OSError) and e.errno == errno.ENOSPC


def _classify_spill_failure(e: BaseException, path: str, mode: str,
                            ledger: "MemoryLedger", stats) -> None:
    """Shared failure classification for sync/async spill writes. A full
    disk is a PERMANENT condition (errors.DaftIOError class) distinct
    from a flaky write: it gets its own counter/health flag, and the
    partial file is removed so a later unspill can never read a
    truncated IPC stream off a recycled slot."""
    if _is_disk_full(e):
        from .errors import DaftIOError

        ledger.disk_full()
        if stats is not None:
            stats.bump("spill_disk_full")
        try:
            os.remove(path)
        except OSError:
            pass
        logger.warning("spill_disk_full", mode=mode, path=path,
                       error=repr(DaftIOError(
                           f"spill device full (ENOSPC): {e}")))
    else:
        logger.warning("spill_write_failed", mode=mode, path=path,
                       error=repr(e))
    if stats is not None:
        stats.bump("spill_write_failures")


def _write_spill_ipc(path: str, tbls) -> int:
    """Arrow-IPC spill write (codec per _SPILL_CODEC): parquet spills paid a
    full encode+decode round-trip per partition; IPC writes land in the
    page cache at memcpy speed and the consumer reads them back through
    warm page-cache file reads (_SpillSlotTask). Chunk-wise: a multi-piece
    shuffle bucket streams each piece as its own record batch — the bucket
    is never concatenated just to be spilled. Entries may be engine Tables
    OR already-arrow tables (the encoded-exchange payload hook: dictionary
    columns write natively, so spilled exchange bytes stay encoded and the
    read-back's Table.from_arrow decodes them). Returns bytes written."""
    import pyarrow as pa

    atbls = [t if isinstance(t, pa.Table) else t.to_arrow() for t in tbls]
    schema = atbls[0].schema
    opts = pa.ipc.IpcWriteOptions(compression=_SPILL_CODEC)
    with pa.OSFile(path, "wb") as f, \
            pa.ipc.new_file(f, schema, options=opts) as w:
        for at in atbls:
            if at.schema != schema:
                at = at.cast(schema)
            w.write_table(at)
    try:
        return os.path.getsize(path)
    except OSError:
        return sum(at.nbytes for at in atbls)


class PartitionBuffer:
    """Append MicroPartitions; past the budget they spill to arrow IPC files
    and come back lazy. Iterating yields partitions in append order (spilled
    ones as Unloaded MicroPartitions that re-read on demand).

    ``async_spill`` routes the IPC writes through the scope's bounded
    writer thread; ``readahead`` (a submit callable, normally the query
    pool's) pipelines drain()'s spill read-backs one partition ahead of
    the consumer. Both default OFF for directly-constructed buffers — the
    ExecutionContext wires them from the ExecutionConfig."""

    def __init__(self, budget_bytes: Optional[int], stats=None,
                 scope: Optional[SpillScope] = None,
                 async_spill: bool = False,
                 readahead: Optional[Callable] = None,
                 ledger: Optional[MemoryLedger] = None,
                 integrity: bool = False, lineage=None):
        self.budget = budget_bytes
        self.stats = stats
        self.scope = scope or SpillScope()
        self.async_spill = async_spill
        # the query's ledger share (child of MEMORY_LEDGER under the
        # serving runtime): budget decisions read THIS balance, so one
        # query's spill pressure never charges another's headroom
        self.ledger = ledger if ledger is not None else MEMORY_LEDGER
        # end-to-end integrity (cfg.partition_integrity): spill writes
        # record a crc32 of the landed file and read-backs verify it;
        # `lineage` (a LineageLog, cfg.lineage_recomputation) records how
        # spilled partitions were produced so corruption recomputes
        # instead of failing. Both default OFF for directly-constructed
        # buffers — the ExecutionContext wires them from the config.
        self.integrity = integrity
        self.lineage = lineage
        self._readahead = readahead
        self._items: List[Optional[MicroPartition]] = []
        self._held: List[int] = []

    def append(self, part: MicroPartition) -> None:
        size = part.size_bytes() or 0
        # the spill decision charges the query's full ledger-visible
        # WORKING SET, not just buffered bytes: streaming-channel morsels
        # and prefetched-but-unconsumed partitions are resident memory
        # eating the same budget headroom. When backpressure alone can't
        # bound the working set, the buffers spill earlier — spill is the
        # fallback, not a separate account (README "Streaming execution").
        # Streaming's bounded channels charge far less here than the
        # partition-granular path's whole-partition units (exec_inflight:
        # materialized task outputs parked in the dispatch window) — the
        # bench streaming rung's spill-reduction claim.
        if (self.budget is not None and len(part)
                and (self.ledger.current + self.ledger.stream_inflight
                     + self.ledger.prefetch_inflight
                     + self.ledger.exec_inflight
                     + self.ledger.batch_inflight
                     + size > self.budget)):
            spilled = self._try_spill(part, size)
            if spilled is not None:
                self._items.append(spilled)
                self._held.append(0)
                return
        self.ledger.add(size)
        self._items.append(part)
        self._held.append(size)

    def _take_path(self) -> str:
        path = self.scope.take_slot()
        if path is None:
            with _SPILL_LOCK:
                _SPILL_SEQ[0] += 1
                seq = _SPILL_SEQ[0]
            path = os.path.join(self.scope.dir(), f"spill_{seq}.arrow")
        return path

    def _lineage_key_for(self, part: MicroPartition):
        """Record this partition's recompute recipe (if it has one) in the
        query's bounded LineageLog; returns the recipe key or None
        (truncated lineage — corruption will degrade, not recompute)."""
        if self.lineage is None:
            return None
        recipe = getattr(part, "lineage_recipe", None)
        if recipe is None:
            # a partition that IS a re-readable scan task's output:
            # the source file is the recipe
            from .integrity.lineage import task_recipe, unwrap_source_task

            src = unwrap_source_task(part)
            if src is None:
                return None
            recipe = task_recipe(src)
        return self.lineage.record(recipe)

    def _try_spill(self, part: MicroPartition, size: int) -> Optional[MicroPartition]:
        import weakref

        path = self._take_path()
        # capture lineage BEFORE materialization: the recipe check reads
        # the partition's pre-spill lazy state
        lineage_key = self._lineage_key_for(part)
        task0 = part.scan_task()
        enc = (getattr(task0, "encoded_payload", None)
               if task0 is not None else None)
        if enc is not None:
            # encoded exchange piece (exchange/encode.py): spill the ENCODED
            # arrow payload as-is — dictionary columns survive IPC, so the
            # spilled exchange bytes stay encoded; the slot read-back's
            # Table.from_arrow decodes. Pre-landing reads (async path) serve
            # through the task's own decode.
            tbls = enc()
            schema = part.schema
            nrows = len(part)
            reader = task0.read
        else:
            # chunk-wise: a multi-piece shuffle bucket (chained per-chunk
            # splits) spills its pieces as separate record batches
            tbls = part.chunk_tables()
            schema = tbls[0].schema
            nrows = sum(len(t) for t in tbls)
            reader = None
        if self.async_spill:
            out = self._spill_async(path, tbls, size, schema, nrows, reader,
                                    lineage_key)
            if out is not None:
                return out
            # writer unavailable (closed scope): fall through to sync
        try:
            from . import faults

            faults.check("spill.write", self.stats)
            t0 = time.perf_counter_ns()
            file_bytes = _write_spill_ipc(path, tbls)
            dt = time.perf_counter_ns() - t0
        except Exception as e:
            # python-object columns have no arrow representation, flaky
            # disks happen, and ENOSPC is classified as a permanently full
            # device (its own counter/flag, partial file removed): in
            # every case hold in memory rather than fail the query; the
            # slot goes back on the free-list for the next spill
            _classify_spill_failure(e, path, "sync", self.ledger,
                                    self.stats)
            self.scope.recycle(path)
            return None
        crc = None
        if self.integrity:
            from .integrity.checksum import crc32_file

            crc = crc32_file(path)
        try:
            from . import faults

            # the deterministic disk-corruption hook: an armed plan flips
            # a real bit in the landed file AFTER its checksum was
            # recorded, so detection + recompute are testable end to end
            faults.check("spill.corrupt", self.stats)
        except DaftTransientError:
            from .integrity.checksum import flip_file_bits

            flip_file_bits(path)
        self.ledger.spilled(size)
        self.ledger.record_spill_write(file_bytes, dt)
        if self.stats is not None:
            self.stats.bump("spilled_partitions")
            self.stats.bump("spill_write_bytes", file_bytes)
            self.stats.bump("spill_write_ns", dt)
            # a synchronous spill stalls the breaker thread for the whole
            # write — exactly the wait async writeback removes
            self.stats.io_wait(dt)
            if self.stats.profiler.armed:
                self.stats.profiler.event("spill", mode="sync", rows=nrows,
                                          bytes=file_bytes)
        task = _SpillSlotTask(path, schema, nrows, file_bytes,
                              self.scope, rt_stats=self.stats,
                              ledger=self.ledger, expected_crc=crc,
                              lineage=self.lineage,
                              lineage_key=lineage_key)
        # the slot recycles when nothing can read it anymore: task GC, not
        # first-read, so forked references never race the free-list (and
        # the lineage recipe is dropped with it — nothing can need it)
        weakref.finalize(task, _settle_sync_slot, self.scope, path,
                         self.lineage, lineage_key)
        return MicroPartition.from_scan_task(task)

    def _spill_async(self, path: str, tbls, size: int, schema, nrows: int,
                     reader=None, lineage_key=None) -> Optional[MicroPartition]:
        """Hand the IPC write to the scope's bounded writer thread; the
        returned partition is immediately consumable (reads serve from the
        resident tables — or, for encoded exchange payloads, through
        ``reader``'s decode — until the write lands)."""
        import weakref

        import pyarrow as pa

        writer = self.scope.writer()
        mem_bytes = sum((t.nbytes if isinstance(t, pa.Table)
                         else t.size_bytes()) for t in tbls)
        task = _AsyncSpillSlotTask(path, schema, nrows,
                                   mem_bytes,
                                   self.scope, tbls, rt_stats=self.stats,
                                   ledger=self.ledger, reader=reader,
                                   lineage=self.lineage,
                                   lineage_key=lineage_key)
        stats = self.stats
        ledger = self.ledger
        integrity = self.integrity
        # capture the submitting thread's span AND query context so the
        # write — which runs on the writer thread — is attributed to the
        # op (and query) that spilled, not lost
        prof = stats.profiler if stats is not None else None
        token = prof.capture() if prof is not None and prof.armed else None
        qid = current_query_id()

        def job():
            from . import faults

            sp = None
            qctx = query_context(qid)
            qctx.__enter__()
            if token is not None:
                act = prof.activate(token)
                act.__enter__()
                sp = prof.begin("spill.write", kind="bg")
            try:
                try:
                    faults.check("spill.write", stats)
                    t0 = time.perf_counter_ns()
                    file_bytes = _write_spill_ipc(path, tbls)
                    dt = time.perf_counter_ns() - t0
                except Exception as e:
                    # same contract as the synchronous path, discovered
                    # late: hold the partition in memory instead of
                    # failing the query (ENOSPC classified as disk-full —
                    # counter/flag set, partial file removed)
                    _classify_spill_failure(e, path, "async", ledger,
                                            stats)
                    ledger.async_spill_failed(size)
                    task._write_failed(size)
                    return
                crc = None
                if integrity:
                    from .integrity.checksum import crc32_file

                    crc = crc32_file(path)
                try:
                    faults.check("spill.corrupt", stats)
                except DaftTransientError:
                    from .integrity.checksum import flip_file_bits

                    flip_file_bits(path)
                ledger.async_spill_done(size)
                ledger.record_spill_write(file_bytes, dt)
                task._write_done(file_bytes, crc)
                if stats is not None:
                    stats.bump("spilled_partitions")
                    stats.bump("spill_write_bytes", file_bytes)
                    stats.bump("spill_write_ns", dt)
                if sp is not None:
                    sp.set_attr("bytes", file_bytes)
                    prof.event("spill", mode="async", rows=nrows,
                               bytes=file_bytes)
            finally:
                if sp is not None:
                    prof.end(sp)
                    act.__exit__(None, None, None)
                qctx.__exit__(None, None, None)

        # daftlint: ledger-escape settled-by=job
        ledger.async_spill_started(size)
        t0 = time.perf_counter_ns()
        submitted = writer.submit(job)
        backpressure = time.perf_counter_ns() - t0
        if not submitted:
            ledger.async_spill_abandoned(size)
            return None
        if stats is not None and backpressure > 1_000_000:
            # the only disk stall left on the append path: a full writer
            # queue (>1ms counts; the fast path is lock-acquire noise)
            stats.io_wait(backpressure)
            stats.bump("spill_backpressure_ns", backpressure)
        weakref.finalize(task, _settle_async_slot, self.scope, path,
                         task._held_cell, self.ledger, self.lineage,
                         lineage_key)
        return MicroPartition.from_scan_task(task)

    def __len__(self) -> int:
        return len(self._items)

    def __iter__(self):
        return iter(self._items)

    def parts(self) -> List[MicroPartition]:
        return list(self._items)

    def preload(self) -> None:
        """Issue background read-backs for unloaded (spilled) items — the
        shuffle reduce side calls this on bucket i+1 while bucket i is
        being consumed downstream. Bounded by the spill budget: at least
        one load always submits (the consumer's own working-partition
        slack), further ones only while their estimated bytes fit within
        budget_bytes — a whole oversized bucket never preloads resident
        unthrottled (preload_throttled counts what waited for the
        consumer's sequential reads). Errors stay with the partition: a
        failed background load leaves it unloaded and the consumer's own
        read raises."""
        submit = self._readahead
        if submit is None:
            return
        submitted_bytes = 0
        for p in self._items:
            if p is None or p.is_loaded():
                continue
            est = p.size_bytes() or 0
            if (submitted_bytes and self.budget is not None
                    and submitted_bytes + est > self.budget):
                if self.stats is not None:
                    self.stats.bump("preload_throttled")
                    if self.stats.profiler.armed:
                        self.stats.profiler.event("throttle",
                                                  what="unspill_preload",
                                                  bytes=est)
                return
            self._submit_load(p)
            submitted_bytes += est

    def _submit_load(self, part: MicroPartition):
        submit = self._readahead
        prof = self.stats.profiler if self.stats is not None else None
        token = prof.capture() if prof is not None and prof.armed else None
        qid = current_query_id()

        def job():
            _BG_IO.active = True
            sp = None
            qctx = query_context(qid)
            qctx.__enter__()
            if token is not None:
                act = prof.activate(token)
                act.__enter__()
                sp = prof.begin("spill.read", kind="bg")
            try:
                return part.table()
            finally:
                _BG_IO.active = False
                if sp is not None:
                    prof.end(sp)
                    act.__exit__(None, None, None)
                qctx.__exit__(None, None, None)

        try:
            fut = submit(job)
        except RuntimeError:  # pool already shut down: consumer reads sync
            return None
        if fut is not None:
            # retrieve background exceptions even when nobody awaits (an
            # abandoned drain, preload): the partition stays unloaded, so
            # the consumer's own read raises the same error — result()
            # still re-raises for awaiting callers
            fut.add_done_callback(
                lambda f: None if f.cancelled() else f.exception())
            if self.stats is not None:
                self.stats.bump("unspill_readahead_submitted")
        return fut

    def drain(self):
        """Yield partitions in append order, dropping each internal ref as it
        is handed out, so a spilled partition's re-materialized table lives
        only for the consumer's one iteration (out-of-core discipline: the
        buffer never re-pins the whole input). With readahead wired, the
        next spilled partition's read-back runs on the pool while the
        consumer processes the current one; a background failure re-raises
        HERE, on the consumer thread, at that partition's hand-off."""
        # drain is a flush barrier: writer-internal errors surface before
        # the consumer starts pulling
        self.scope.raise_async_errors()
        pending_idx = -1
        pending_fut = None
        for i in range(len(self._items)):
            part, self._items[i] = self._items[i], None
            self.ledger.sub(self._held[i])
            self._held[i] = 0
            if pending_idx == i and pending_fut is not None:
                self._await_load(pending_fut)
                pending_fut = None
            if self._readahead is not None and pending_fut is None:
                j = i + 1
                while (j < len(self._items) and self._items[j] is not None
                       and self._items[j].is_loaded()):
                    j += 1
                if j < len(self._items) and self._items[j] is not None:
                    pending_fut = self._submit_load(self._items[j])
                    pending_idx = j
            yield part
        self._items = []
        self._held = []

    def _await_load(self, fut) -> None:
        """Resolve a readahead future before handing its partition out.
        Never waits on a fetch that hasn't started (a congested pool would
        deadlock a consumer that is itself a pool task): cancel and let the
        consumer read synchronously instead."""
        if fut.cancelled():
            # cancelled from outside (pool client closed at teardown): the
            # partition stays unloaded and the consumer reads synchronously
            if self.stats is not None:
                self.stats.bump("unspill_readahead_misses")
            return
        if fut.done():
            if self.stats is not None:
                self.stats.bump("unspill_readahead_hits")
            fut.result()  # re-raise a background failure to the consumer
            return
        if fut.cancel():
            if self.stats is not None:
                self.stats.bump("unspill_readahead_misses")
            return
        t0 = time.perf_counter_ns()
        try:
            fut.result()
        finally:
            if self.stats is not None:
                self.stats.bump("unspill_readahead_hits")
                self.stats.io_wait(time.perf_counter_ns() - t0)

    def release(self) -> None:
        """Return held bytes to the ledger and drop partition refs (call when
        the buffer's contents have been consumed downstream)."""
        self.ledger.sub(sum(self._held))
        self._items = []
        self._held = []
