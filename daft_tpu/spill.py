"""Bounded-memory execution: spillable partition buffers.

The reference completes TPC-H SF1000 on a single node at a 16x
data-to-memory ratio (docs/source/faq/benchmarks.rst:111-124) by keeping
MicroPartitions lazy and spilling pipeline-breaker state. Here, every
pipeline breaker that must hold many partitions (shuffle fanout buckets,
join builds, sort-merge buckets) accumulates into a PartitionBuffer: once
the process-wide in-memory budget (ExecutionConfig.memory_budget_bytes) is
exceeded, further partitions are written as arrow IPC files in a per-query
spill directory and handed back as UNLOADED MicroPartitions — the consumer
re-materializes them one at a time, so peak engine-held memory stays at
(budget + one working partition).

Accounting is engine-level (sum of buffered partition byte sizes tracked by
a process-wide ledger with a high-water mark), which tests can assert
exactly — RSS would be dominated by the jax runtime."""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
from typing import List, Optional

from .micropartition import MicroPartition


class MemoryLedger:
    """Process-wide account of bytes held by partition buffers."""

    def __init__(self):
        self._lock = threading.Lock()
        self.current = 0
        self.high_water = 0
        self.spilled_bytes = 0
        self.spilled_partitions = 0

    def add(self, n: int) -> None:
        with self._lock:
            self.current += n
            self.high_water = max(self.high_water, self.current)

    def sub(self, n: int) -> None:
        with self._lock:
            self.current -= n

    def spilled(self, n: int) -> None:
        with self._lock:
            self.spilled_bytes += n
            self.spilled_partitions += 1

    def reset(self) -> None:
        with self._lock:
            self.current = 0
            self.high_water = 0
            self.spilled_bytes = 0
            self.spilled_partitions = 0


MEMORY_LEDGER = MemoryLedger()

_SPILL_LOCK = threading.Lock()
_SPILL_SEQ = [0]
# IPC body codec for spill files. None = uncompressed: writes land in the
# page cache at memcpy speed and mmap re-reads are zero-copy; the kernel
# writes dirty pages back asynchronously. "lz4" trades one-core compress
# CPU for ~35% fewer dirty bytes — worth it only when spill volume outruns
# RAM so the disk itself gates. A/B at SF10 on this host (r5, two
# interleaved trials): uncompressed 34.8/32.2s vs lz4 46.4/34.3s.
_SPILL_CODEC: Optional[str] = None


class SpillScope:
    """Per-query spill directory, owned by the ExecutionContext so nested
    executions (AQE stages) never delete each other's files."""

    def __init__(self):
        self._dir: Optional[str] = None
        self._lock = threading.Lock()

    def dir(self) -> str:
        with self._lock:
            if self._dir is None or not os.path.isdir(self._dir):
                self._dir = tempfile.mkdtemp(prefix="daft_tpu_spill_")
            return self._dir

    def cleanup(self) -> None:
        with self._lock:
            if self._dir is not None:
                shutil.rmtree(self._dir, ignore_errors=True)
                self._dir = None


class PartitionBuffer:
    """Append MicroPartitions; past the budget they spill to arrow IPC files
    and come back lazy. Iterating yields partitions in append order (spilled ones as
    Unloaded MicroPartitions that re-read on demand)."""

    def __init__(self, budget_bytes: Optional[int], stats=None,
                 scope: Optional[SpillScope] = None):
        self.budget = budget_bytes
        self.stats = stats
        self.scope = scope or SpillScope()
        self._items: List[MicroPartition] = []
        self._held: List[int] = []

    def append(self, part: MicroPartition) -> None:
        size = part.size_bytes() or 0
        if (self.budget is not None and len(part)
                and MEMORY_LEDGER.current + size > self.budget):
            spilled = self._try_spill(part, size)
            if spilled is not None:
                self._items.append(spilled)
                self._held.append(0)
                return
        MEMORY_LEDGER.add(size)
        self._items.append(part)
        self._held.append(size)

    def _try_spill(self, part: MicroPartition, size: int) -> Optional[MicroPartition]:
        import pyarrow as pa

        from .io.scan import FileFormat, Pushdowns, ScanTask

        with _SPILL_LOCK:
            _SPILL_SEQ[0] += 1
            seq = _SPILL_SEQ[0]
        path = os.path.join(self.scope.dir(), f"spill_{seq}.arrow")
        tbl = part.table()
        try:
            # arrow IPC spills (codec per _SPILL_CODEC above): parquet spills
            # paid a full encode+decode round-trip per partition; IPC writes
            # land in the page cache at memcpy speed and re-reads are
            # memory-mapped.
            atbl = tbl.to_arrow()
            opts = pa.ipc.IpcWriteOptions(compression=_SPILL_CODEC)
            with pa.OSFile(path, "wb") as f, \
                    pa.ipc.new_file(f, atbl.schema, options=opts) as w:
                w.write_table(atbl)
        except Exception:
            # python-object columns have no arrow representation: hold in
            # memory rather than fail the query
            return None
        MEMORY_LEDGER.spilled(size)
        if self.stats is not None:
            self.stats.bump("spilled_partitions")
        task = ScanTask(path, FileFormat.ARROW_IPC, tbl.schema, Pushdowns(),
                        num_rows=len(tbl))
        return MicroPartition.from_scan_task(task)

    def __len__(self) -> int:
        return len(self._items)

    def __iter__(self):
        return iter(self._items)

    def parts(self) -> List[MicroPartition]:
        return list(self._items)

    def drain(self):
        """Yield partitions in append order, dropping each internal ref as it
        is handed out, so a spilled partition's re-materialized table lives
        only for the consumer's one iteration (out-of-core discipline: the
        buffer never re-pins the whole input)."""
        for i in range(len(self._items)):
            part, self._items[i] = self._items[i], None
            MEMORY_LEDGER.sub(self._held[i])
            self._held[i] = 0
            yield part
        self._items = []
        self._held = []

    def release(self) -> None:
        """Return held bytes to the ledger and drop partition refs (call when
        the buffer's contents have been consumed downstream)."""
        MEMORY_LEDGER.sub(sum(self._held))
        self._items = []
        self._held = []
