"""Global context + config system.

Role-equivalent to the reference's daft/context.py:295-351
(set_planning_config / set_execution_config, ~19 knobs backed by
common/daft-config) and the runner-selection logic of DaftContext. Config is a
frozen-ish dataclass swapped atomically on the singleton context; readers grab
a snapshot at plan/execute time.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Optional


@dataclasses.dataclass
class PlanningConfig:
    """Knobs consulted while building/optimizing logical plans
    (reference: DaftPlanningConfig)."""

    default_io_num_retries: int = 3
    enable_strict_filter_pushdown: bool = False


@dataclasses.dataclass
class ExecutionConfig:
    """Knobs consulted at physical planning / execution time
    (reference: DaftExecutionConfig, common/daft-config/src/lib.rs)."""

    scan_tasks_min_size_bytes: int = 96 * 1024 * 1024
    scan_tasks_max_size_bytes: int = 384 * 1024 * 1024
    broadcast_join_size_bytes_threshold: int = 10 * 1024 * 1024
    sort_merge_join_sort_with_aligned_boundaries: bool = False
    sample_size_for_sort: int = 20
    num_preview_rows: int = 8
    parquet_target_filesize: int = 512 * 1024 * 1024
    parquet_target_row_group_size: int = 128 * 1024 * 1024
    parquet_inflation_factor: float = 3.0
    csv_target_filesize: int = 512 * 1024 * 1024
    csv_inflation_factor: float = 0.5
    shuffle_aggregation_default_partitions: int = 200
    default_morsel_size: int = 128 * 1024
    # adaptive query execution: materialize join-input stages and re-plan with
    # real sizes (reference: AdaptivePlanner, planner.rs:288)
    enable_aqe: bool = False
    # AQE shuffle-count adaptation: a shuffle over a source of KNOWN size is
    # re-sized to ceil(bytes / this target) partitions (shrink-only), so a
    # 2KB input never fans out 200 ways (reference: stage-boundary re-planning
    # with materialized stats, planner.rs:288-351)
    shuffle_target_partition_bytes: int = 64 * 1024 * 1024
    # transient-IO retry at scan-task granularity (reference: s3_like.rs retry)
    scan_retry_attempts: int = 3
    scan_retry_backoff_s: float = 0.1
    # pipelined IO (README "Pipelined IO"): consumption-driven scan
    # readahead — materializing scan partition i issues the reads of the
    # next N tasks on the shared executor pool (io/prefetch.py), charged
    # against the MemoryLedger so readahead never blows memory_budget_bytes.
    # 0 disables (fully synchronous reads); results are byte-identical at
    # every depth.
    scan_prefetch_depth: int = 2
    # pipeline breakers hand spill IPC writes to a bounded background writer
    # thread instead of stalling on disk (spill.AsyncSpillWriter); write
    # failures keep the partition in memory exactly like the sync path, and
    # writer-internal errors surface at the next check_deadline barrier
    async_spill_writes: bool = True
    # draining a spilled buffer issues the NEXT unloaded partition's
    # read-back on the pool before the consumer needs it (double buffering);
    # the shuffle reduce side preloads bucket i+1 while bucket i is consumed
    unspill_readahead: bool = True
    # map-side shuffle fanout (decode + hash/split) runs as order-preserving
    # partition tasks on the worker pool — window min(4, workers) for
    # streams that may carry unloaded (out-of-core) partitions, the normal
    # workers+backlog window for resident ones — instead of inline on the
    # consumer thread (reference: FanoutInstruction partition tasks)
    parallel_shuffle_fanout: bool = True
    # morsel-parallel execution (reference: worker-per-core intermediate ops,
    # intermediate_op.rs:71): 0 = auto (one worker per core when the host has
    # >= 4 cores; sequential below that — oversubscription on tiny hosts
    # costs more than it buys), 1 = sequential, N = exactly N workers
    executor_threads: int = 0
    # extra tasks queued beyond the worker count in the dispatch loop
    # (reference: RayRunner's cores + max_task_backlog dynamic bound,
    # ray_runner.py:504-685); -1 = auto (one backlog slot per worker)
    max_task_backlog: int = -1
    # expression-pipeline fusion (daft_tpu/fuse/): maximal Project/Filter
    # chains collapse into single-pass FusedMapOp programs (one composed
    # host projection per partition; one jit program on the device path)
    # with hash-consing CSE and dead-column elimination. Results are
    # byte-identical with fusion on or off; False restores the per-op
    # interpreted chain (the bench.py laion fusion A/B axis).
    expr_fusion: bool = True
    # two-phase approximate aggregations (daft_tpu/sketch/): multi-partition
    # approx_count_distinct / approx_percentiles plan as sketch->merge stages
    # whose exchange ships serialized sketch bytes, O(sketch_size x
    # partitions). False restores the raw-row shuffle/gather path (the
    # before/after axis bench.py's sketch_exchange rung measures).
    sketch_aggregations: bool = True
    # --- exchange v2 (daft_tpu/exchange/, README "Exchange") --------------
    # runtime join filters (sideways information passing): the join build
    # side's exchange builds a Bloom + min-max filter from its keys and the
    # probe side's exchange (or the broadcast-join probe stream) prunes
    # non-qualifying rows BEFORE bucketing, spill, and merge. Semantics
    # gated per join type (inner/semi: either side; left: right side only;
    # right/anti/outer: decline); false-positive tolerant — the join
    # re-checks every surviving row, so results are byte-identical off.
    runtime_join_filters: bool = True
    # dictionary-encode low-cardinality columns of fanout bucket pieces
    # before they enter the spillable PartitionBuffer (per-column
    # cardinality sampling skips hostile columns; spilled exchange bytes
    # shrink too); decode happens once, at reduce-merge. Byte-identical off.
    exchange_payload_encoding: bool = True
    # hierarchical exchange: two-stage aggregations fold map-side pieces
    # headed to the same destination through the stage-2 combine BEFORE
    # the exchange buffers them (intra-host combine -> inter-host
    # all_to_all; mirrored on the mesh path ahead of the ICI collective).
    # Only schema-closed decomposable merges fold; byte-identical off.
    hierarchical_exchange_combine: bool = True
    # --- morsel-driven streaming executor (daft_tpu/stream/, README
    # "Streaming execution") ----------------------------------------------
    # streamable chains (Scan/InMemory -> Project/Filter/FusedMap ->
    # optional Limit) pull fixed-size morsels through bounded channels with
    # backpressure instead of materializing whole partitions between steps:
    # bounded working-set memory, first-row latency for limit/interactive
    # queries, and upstream early-termination when a limit is satisfied.
    # Results are byte-identical with streaming off (pipeline breakers keep
    # their partition-granular contract behind the driver's re-chunk
    # boundary). Declines automatically on the device-kernel and
    # mesh/multi-host paths.
    streaming_execution: bool = True
    # rows per morsel (the streaming unit; morsels never span reader-chunk
    # boundaries, so the effective size is min(this, chunk rows))
    morsel_size_rows: int = 128 * 1024
    # bounded-channel capacity in morsels, per in-flight source partition;
    # producers block (backpressure) past it
    stream_channel_capacity: int = 4
    # producer stages concurrently in flight; 0 = auto (one per worker —
    # the streaming path replaces _parallel_map's full worker fan-out and
    # must not cap map parallelism below it)
    stream_producer_window: int = 0
    # TPU-specific: route eligible projections/aggregations through the jax
    # device kernel layer (kernels/device.py); host pyarrow path otherwise.
    use_device_kernels: bool = False
    device_min_rows: int = 4096
    # whole-plan device residency (fuse/segment.py): compile eligible
    # project->filter->agg plan segments into one HBM-resident pipeline —
    # the map program's intermediate columns feed the fused aggregation as
    # DeviceArrays (one host->device stage at segment entry, one gather at
    # exit, zero Arrow materialization between). Results are byte-identical
    # with this off; any segment-compile or resident-run failure degrades
    # to the staged per-op device path. No effect without
    # use_device_kernels.
    device_residency: bool = True
    # result cache (PartitionSetCache): off when benchmarking so repeated runs
    # measure execution, not cache lookups
    enable_result_cache: bool = True
    # bounded-memory execution: pipeline breakers (shuffle buckets, join
    # builds) spill partitions to parquet past this engine-held byte budget;
    # None = unbounded (reference: the 16x data-to-memory SF1000 single-node
    # run, benchmarks.rst:111-124)
    memory_budget_bytes: Optional[int] = None
    # With x64 off (real TPUs are 32-bit), allow float64 data to execute as
    # float32 on device. Sums stay accurate: per-partition partials are
    # combined in float64 on the host. Set False to force exact float64
    # expressions onto the host path.
    device_reduced_precision: bool = True
    # 32-bit mode only: batch all float segment-SUMS of a fused grouped agg
    # through ONE pallas one-hot matmul on the MXU (kernels/pallas_ops.py)
    # instead of K scatter-based segment_sum lowerings. Same float32
    # accumulation contract as device_reduced_precision.
    use_pallas_segment_sums: bool = True
    # deep fusion: predicate + derived float-sum columns evaluated INSIDE
    # the pallas kernel (no pre-masked (n,K) HBM intermediate). Off by
    # default until the device measurement (bench q1_deep_pallas_vs_composed)
    # proves it wins — the r4 verdict's "keep it only if it wins" rule.
    use_pallas_deep_fusion: bool = False
    # query deadline: the runner converts this to an absolute deadline at
    # run start (ONE deadline across all AQE stages), checked cooperatively
    # in the morsel loop and at pipeline breakers; expiry raises
    # DaftTimeoutError carrying the partial RuntimeStats. None = no limit.
    execution_timeout_s: Optional[float] = None
    # structured query profiler (daft_tpu/profile/): arm span/event
    # recording for every query without passing collect(profile=True) each
    # time. Off by default — the disarmed hot path is a single flag check
    # (guard-tested zero-allocation), so q1 wall is unaffected.
    enable_profiling: bool = False
    # always-on flight recorder (daft_tpu/obs/): every completed plan
    # execution appends a QueryRecord to the bounded process query log
    # (dt.query_log() / df.last_query_record()). Built only from state the
    # stats stack already collects — one dict build per query, guard-tested
    # like the DISARMED profiler — so it stays on even in production.
    # False disables ONLY the ring/last_query_record; the diagnostics
    # capture below keeps working.
    enable_query_log: bool = True
    query_log_depth: int = 256
    # slow/failed-query auto-capture: a query slower than this (seconds)
    # counts as slow — it arms the profiler for the NEXT run of the same
    # plan fingerprint, and (with diagnostics_dir set) dumps a diagnostics
    # bundle. None disables the slow path; errored/deadline-killed queries
    # always capture when diagnostics_dir is set.
    slow_query_threshold_s: Optional[float] = None
    # where diagnostics bundles land (record.json + stats.txt + profile
    # when armed + log/trace tails); None = no bundles. Retention is
    # bounded: only the newest diagnostics_keep_last bundles survive.
    diagnostics_dir: Optional[str] = None
    diagnostics_keep_last: int = 20
    # --- serving runtime (daft_tpu/serve/) ---------------------------------
    # query-level admission control: how many queries may EXECUTE at once in
    # a ServingRuntime (per-task admission via ResourceAccountant still
    # applies inside each query)
    max_concurrent_queries: int = 4
    # queries allowed to WAIT for a slot beyond the active set; a submit
    # past (active slots + this queue) sheds immediately with
    # DaftOverloadedError instead of piling up unboundedly
    admission_queue_depth: int = 16
    # a queued query that cannot get a slot within this window is shed with
    # DaftOverloadedError; None = wait forever (not recommended for serving)
    admission_timeout_s: Optional[float] = 30.0
    # scheduler partition tasks that raise DaftTransientError (including
    # injected io.get/scan.read faults that exhausted the IO-layer retries)
    # are re-run through the shared RetryPolicy this many EXTRA times
    # before failing the query; 0 disables task-level retry
    task_retry_attempts: int = 2
    task_retry_backoff_s: float = 0.05
    # --- distributed runner (daft_tpu/dist/, README "Distributed
    # execution") -------------------------------------------------------
    # supervised worker PROCESSES the DistributedRunner ships map-class
    # partition tasks to over the length-prefixed socket transport.
    # 0 = off (single-process execution, the default); N > 0 spawns N
    # workers, each with a carved child memory budget
    # (memory_budget_bytes // (N + 1); the driver keeps one share).
    # Results are byte-identical to the local runner at every N.
    distributed_workers: int = 0
    # supervision cadence: the driver pings every worker at this interval
    # and declares a worker dead when no pong (or result) arrived within
    # the timeout — its in-flight tasks re-dispatch to surviving workers
    worker_heartbeat_interval_s: float = 0.5
    worker_heartbeat_timeout_s: float = 5.0
    # spawn-to-handshake deadline for one worker process
    worker_spawn_timeout_s: float = 60.0
    # total worker RESPAWNS the pool may spend across its lifetime
    # (initial spawns are free); exhausted = the pool degrades to local
    # in-process execution instead of cycling forever
    worker_restart_budget: int = 8
    # dispatch attempts per task across worker losses: a poison task that
    # kills every worker it touches fails the QUERY with a DaftError
    # naming the task once it exhausts this budget (or has excluded every
    # worker slot), instead of re-dispatching forever
    dist_task_max_attempts: int = 4
    # cluster-wide observability plane (daft_tpu/obs/cluster.py): workers
    # piggyback a bounded, versioned telemetry fragment (span subtree,
    # RuntimeStats delta, typed events, log tail) on every task reply;
    # the driver merges it into the query's span tree, counter rollups,
    # and log ring, so one query produces ONE truthful trace regardless
    # of how many processes ran it. Strictly fail-open: a dropped or
    # corrupt fragment costs a telemetry_dropped counter, never a task
    # failure. Off = replies carry result/error only (the bench
    # dist_telemetry_overhead_pct A/B axis).
    cluster_telemetry: bool = True
    # peer-to-peer shuffle data plane (daft_tpu/dist/peerplane.py, README
    # "Peer-to-peer shuffle & elasticity"): hash/random shuffles dispatch
    # fanout tasks that park their pieces ON the workers, and reduce
    # buckets carry only a piece-location map — whoever materializes a
    # bucket pulls its pieces straight from the hosting peers over the
    # token-authenticated crc-framed transport, so driver payload bytes
    # stay flat as the worker count grows. Results are byte-identical
    # with this off and at every N; a dead/corrupt/stale peer degrades to
    # lineage recompute of just the lost pieces (peer_refetches), never a
    # failed query.
    peer_shuffle: bool = True
    # elastic worker pool: when BOTH bounds are set, the supervisor scales
    # the live worker count inside [min, max] — up under pressure
    # (admission queue depth + dispatch waiters; warm FDO history jumps
    # straight toward max, a cold pool steps by one), down by gracefully
    # DRAINING an idle worker after elastic_idle_scale_down_s of fleet
    # idleness. Unset (the default) keeps the fixed-size pool semantics.
    distributed_workers_min: Optional[int] = None
    distributed_workers_max: Optional[int] = None
    elastic_scale_interval_s: float = 0.5
    elastic_idle_scale_down_s: float = 10.0
    # drain_worker()/SIGTERM grace: a draining worker stops taking tasks
    # but keeps serving hosted shuffle pieces for this window, so spot
    # preemption costs bounded recompute, never a failed query; a worker
    # whose in-flight task outlives drain_timeout is killed and the task
    # re-dispatches through the normal loss path
    worker_drain_grace_s: float = 2.0
    worker_drain_timeout_s: float = 10.0
    # --- self-healing data plane (daft_tpu/integrity/, README "Data
    # integrity & speculation") ----------------------------------------
    # end-to-end partition integrity: payloads leaving compute (spill IPC
    # files, transport frames, encoded exchange pieces) carry a crc32
    # recorded at production and verified at re-entry; a mismatch raises
    # DaftCorruptionError (transient — lineage recompute / task re-dispatch
    # own recovery) instead of a garbled table. Results are byte-identical
    # with this off; off also skips the checksum computation (the bench
    # integrity_overhead_pct A/B axis).
    partition_integrity: bool = True
    # lineage-based recomputation: a bounded per-query LineageLog records
    # how spilled partitions were produced (scan task ref, or fanout op +
    # source partition ref); a corrupted or missing spill artifact is
    # recomputed from its recipe (partitions_recomputed) instead of
    # failing the query, degrading to a query-level DaftError only when
    # lineage is truncated or the recompute itself fails
    lineage_recomputation: bool = True
    lineage_log_depth: int = 4096
    # speculative straggler mitigation (distributed runner): a remote task
    # exceeding speculation_quantile_factor x the running p75 task wall
    # for its op (floor speculation_min_s) gets a duplicate dispatched to
    # a different worker; first result wins through the exactly-once ack
    # ledger, the loser is cancelled, and concurrent duplicates are
    # bounded by speculation_max_inflight so a sick fleet cannot double
    # its own load (tasks_speculated / speculation_wins counters)
    speculative_execution: bool = True
    speculation_quantile_factor: float = 3.0
    speculation_min_s: float = 1.0
    speculation_max_inflight: int = 2
    # --- query-velocity subsystem (daft_tpu/adapt/, README "Plan &
    # program cache") ---------------------------------------------------
    # plan/program cache: repeated plan shapes serve their optimized
    # logical plan, translated physical plan, and compiled FusedPrograms
    # from a bounded process cache keyed by a canonical fingerprint
    # (literals parameterized out) — warm traffic performs zero
    # optimize()/translate()/fuse-compile calls, byte-identical to a
    # cold plan. Invalidated on any config change, source mtime change,
    # cache-version bump, or FDO revalidation/demotion; fails open.
    plan_cache: bool = True
    # total estimated plan bytes held before LRU shedding (charged to the
    # MemoryLedger's plan_cache_bytes account)
    plan_cache_bytes: int = 64 * 1024 * 1024
    # feedback-directed optimization: the planner consults the recorded
    # history of this plan shape (flight-recorder rollups folded per
    # canonical fingerprint) — broadcast-vs-hash join flips, aggregate-
    # exchange fan-out resizes, and streaming-segment hints land on the
    # FIRST run of a repeated shape instead of after an AQE
    # materialization. Decisions are typed profiler events; a runtime
    # mispredict demotes the cached plan and reverts the decision.
    history_fdo: bool = True
    # sub-plan result cache: scan+project/filter prefixes shared across
    # queries memoize their materialized partitions, keyed by the exact
    # prefix fingerprint + source mtime (the _PARTITION_SET_CACHE
    # invalidation discipline); bytes LRU-shed under the cap below and
    # charged to the ledger's subplan_cache_bytes account
    subplan_result_cache: bool = True
    subplan_cache_bytes: int = 64 * 1024 * 1024
    # --- dynamic-batching UDF executor (daft_tpu/batch/, README "Batched
    # inference") --------------------------------------------------------
    # batch-declared UDFs (@daft_tpu.batch_udf / udf(..., batching=...))
    # route through the BatchingExecutor: morsels/partitions coalesce
    # across their boundaries into device-friendly batches under the
    # row/byte budget below, results re-split to exact source boundaries.
    # Results are byte-identical with this off (per-partition UDF path) —
    # the standing hard invariant, and the bench laion batching A/B axis.
    dynamic_batching: bool = True
    # per-batch coalesce budget: a batch closes when EITHER bound is
    # reached (declaration-site values override per UDF)
    batch_max_rows: int = 4096
    batch_max_bytes: int = 32 * 1024 * 1024
    # max-latency flush: a batch older than this flushes even when under
    # budget, so sparse streams never stall behind the coalescer
    batch_flush_ms: float = 25.0
    # batch shape policy: "ragged" concatenates as-is (row-offset vector
    # kept for the re-split); "padded" pads to the next power-of-two
    # bucket (repeating the last valid row; pad rows are sliced away
    # after the apply) so a jit'd apply sees few distinct shapes
    batch_padding: str = "ragged"
    # pinned-model LRU cap (batch/actors.ModelActorPool): resident weight
    # bytes across all pinned actor pools, charged to the ledger's
    # model_cache_bytes account; least-recently-used pools evict past it
    model_cache_bytes: int = 512 * 1024 * 1024
    # device circuit breaker (execution.DeviceHealth): after this many
    # CONSECUTIVE device-kernel failures the breaker opens and every
    # device-eligible partition routes straight to the host path (one trip,
    # not one failure tax per partition — the BENCH_r05 tpu_unreachable
    # lesson) ...
    device_breaker_threshold: int = 3
    # ... until the cooldown elapses, after which ONE probe partition tries
    # the device again: success re-closes the breaker, failure re-opens it.
    device_breaker_cooldown_s: float = 30.0
    # --- persistent cache store (daft_tpu/persist/) ------------------------
    # Directory for durable, cluster-shared cache artifacts. None (the
    # default) disables ALL persistence — the three legs below only engage
    # once a cache_dir is set, so the in-process cold/warm contracts stay
    # exactly as they were. Every leg fails open: any artifact defect
    # reads as a cold miss, never a query failure.
    cache_dir: Optional[str] = None
    # leg 1 — warm-start artifacts: the plan/program cache + FDO history
    # serialize to versioned, crc-verified files (written on query
    # completion / dt.shutdown(), loaded lazily at first planning), so a
    # fresh process serves warm plan-cache hits with zero optimize/
    # translate/fuse-compile calls
    persist_artifacts: bool = True
    # leg 2 — cluster-shared result tier: the sub-plan result cache gains
    # a spill-IPC on-disk tier (addressed by scan-task key + chain
    # fingerprint) served worker-to-worker through the PieceServer plane
    persist_result_store: bool = True
    # leg 3 — incremental refresh: when a source file's mtime/size moves,
    # recompute ONLY the affected partitions of a disk-tier entry and
    # splice them in, instead of discarding the whole entry
    persist_refresh: bool = True
    # artifact-directory hygiene: keep only the newest K artifact files
    # per family (concurrent drivers append, the pruner bounds the dir)
    persist_keep_last: int = 3
    # disk-tier byte cap (results/ subdirectory; oldest entries pruned
    # past it, counted as persist evictions)
    persist_result_bytes: int = 256 * 1024 * 1024


def resolve_executor_threads(cfg: "ExecutionConfig") -> int:
    n = cfg.executor_threads
    if n == 0:
        try:  # cgroup/affinity-aware, not raw host cores
            cores = len(os.sched_getaffinity(0))
        except AttributeError:
            cores = os.cpu_count() or 1
        n = cores if cores >= 4 else 1
    return max(1, n)


class DaftContext:
    """Process-global context: configs + runner (reference: daft/context.py)."""

    _instance: Optional["DaftContext"] = None
    _lock = threading.Lock()

    def __init__(self):
        self.planning_config = PlanningConfig()
        self.execution_config = ExecutionConfig()
        self._runner = None
        # most recent QueryProfile built by a profiled collect()
        self._last_profile = None
        self._runner_name = os.environ.get("DAFT_TPU_RUNNER", "native")
        if os.environ.get("DAFT_TPU_PROGRESS") == "1":
            from . import tracing

            tracing.progress_bars(True)

    @classmethod
    def get(cls) -> "DaftContext":
        with cls._lock:
            if cls._instance is None:
                cls._instance = DaftContext()
            return cls._instance

    def runner(self):
        if self._runner is None:
            from .runners import MeshRunner, NativeRunner

            if self._runner_name == "mesh":
                self._runner = MeshRunner()
            elif self._runner_name == "distributed":
                from .dist.runner import DistributedRunner

                self._runner = DistributedRunner()
            else:
                self._runner = NativeRunner()
        if self._runner_name == "native":
            # cfg.distributed_workers alone turns the multi-process runner
            # on/off; an explicitly-installed runner (mesh, or a test's
            # hand-built MeshRunner) is never clobbered
            from .runners import NativeRunner

            dw = self.execution_config.distributed_workers
            if dw > 0 and type(self._runner) is NativeRunner:
                from .dist.runner import DistributedRunner

                self._runner = DistributedRunner()
            elif dw == 0 and type(self._runner).__name__ == "DistributedRunner":
                self._runner = NativeRunner()
        return self._runner

    def last_profile(self):
        """The QueryProfile of the most recent profiled query in this
        process (``df.collect(profile=True)`` / cfg ``enable_profiling``),
        or None."""
        return self._last_profile

    def set_runner(self, name: str) -> None:
        from .errors import DaftValueError

        if name not in ("native", "mesh", "distributed"):
            raise DaftValueError(f"unknown runner {name!r}")
        self._runner_name = name
        self._runner = None


def get_context() -> DaftContext:
    return DaftContext.get()


def set_planning_config(**kwargs) -> DaftContext:
    ctx = get_context()
    cfg = dataclasses.replace(ctx.planning_config, **kwargs)
    ctx.planning_config = cfg
    return ctx


def set_execution_config(**kwargs) -> DaftContext:
    ctx = get_context()
    cfg = dataclasses.replace(ctx.execution_config, **kwargs)
    ctx.execution_config = cfg
    return ctx


def set_runner_native() -> DaftContext:
    ctx = get_context()
    ctx.set_runner("native")
    return ctx


def set_runner_mesh() -> DaftContext:
    ctx = get_context()
    ctx.set_runner("mesh")
    return ctx
