"""Device (jit'd) apply path for batch-declared UDFs, behind the breaker.

A model opts in by defining ``apply_jax`` — a jax-traceable staticmethod /
classmethod taking the same column arrays as ``__call__``. The batched apply
then runs ``jax.jit(apply_jax)`` under the query's device breaker
(ExecutionContext._device_attempt: fault site ``device.kernel``, failures
recorded, breaker-open routes straight to the host instance). Without the
opt-in — or without a live execution context on this thread — the path
declines (returns None) and run_udf falls back to the pinned host instance,
so host and device-breaker-tripped runs are byte-identical by construction.

The execution context rides a thread-local set by the batching executor /
BatchedUdfOp while UDF expressions evaluate; run_udf itself has no ctx
argument (expression evaluation is context-free by design).
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional

import numpy as np

_tl = threading.local()

_jit_cache: dict = {}
_jit_lock = threading.Lock()


class exec_ctx_scope:
    """``with exec_ctx_scope(ctx): ...`` — publish the ExecutionContext to
    UDF evaluation on this thread (re-entrant: restores the prior one)."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx):
        self._ctx = ctx

    def __enter__(self):
        self._prev = getattr(_tl, "ctx", None)
        _tl.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc):
        _tl.ctx = self._prev
        return False


def current_exec_ctx():
    return getattr(_tl, "ctx", None)


def _jitted(fn):
    with _jit_lock:
        j = _jit_cache.get(fn)
        if j is None:
            import jax

            j = jax.jit(fn)
            _jit_cache[fn] = j
        return j


def device_apply(pool, args: List[Any], n: int) -> Optional[Any]:
    """One breaker-gated device attempt for a batch. None = decline/fall
    back to the host instance (the device layer's standard convention)."""
    ctx = current_exec_ctx()
    if ctx is None or not getattr(ctx.cfg, "use_device_kernels", False):
        return None
    fn = pool.jax_callable()
    if fn is None:
        return None
    if not ctx.device_health.allow(ctx.stats):
        ctx.stats.bump("batch_device_fallbacks")
        return None

    def attempt():
        try:
            import jax  # noqa: F401
        except Exception:
            return None  # decline, not a breaker failure: no toolchain
        np_args = [a.to_numpy() if hasattr(a, "to_numpy") else a for a in args]
        out = _jitted(fn)(*np_args)
        return np.asarray(out)

    out = ctx._device_attempt(attempt)
    if out is None:
        ctx.stats.bump("batch_device_fallbacks")
    else:
        ctx.stats.bump("batch_device_applies")
    return out
