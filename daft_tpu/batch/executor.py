"""BatchingExecutor: coalesce → (pad) → apply → re-split.

The shared engine behind BatchedUdfOp (whole partitions, non-streaming) and
the stream adapter (morsels from the bounded channels). Both feed source
pieces in order and get back OUTPUT pieces re-split to exactly the source
boundaries — so every downstream consumer (further maps, _rechunk, the
sink) sees the same piece boundaries as the unbatched path, which is what
makes batching byte-invisible.

Failure semantics: a fault at ``batch.coalesce`` permanently degrades THIS
executor to the per-piece UDF path (each source piece evaluated alone —
still correct, just unbatched) after settling the buffered ledger charge;
it never fails the query. Model-load failures surface from the apply as the
typed error raised by batch/actors.py.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

import numpy as np

from ..micropartition import MicroPartition
from ..series import Series
from .coalesce import Coalescer, Flush
from .device import exec_ctx_scope

# process-wide flush accounting for dt.health()["batching"] (per-query
# counts live on RuntimeStats; health wants the engine-wide view)
_proc_lock = threading.Lock()
_proc_counts = {"batches_formed": 0, "flushes_budget": 0, "flushes_timer": 0,
                "flushes_end": 0, "coalesce_faults": 0}


def _proc_bump(key: str, n: int = 1) -> None:
    with _proc_lock:
        _proc_counts[key] += n


def process_counters() -> dict:
    with _proc_lock:
        return dict(_proc_counts)


def _next_bucket(n: int, floor: int = 8) -> int:
    """Next power-of-two batch bucket ≥ n (min `floor`): stable shapes so a
    jit'd apply recompiles O(log max_rows) times, not once per batch."""
    b = floor
    while b < n:
        b <<= 1
    return b


class BatchSettings:
    """Effective knobs: declaration-site overrides over ExecutionConfig."""

    __slots__ = ("max_rows", "max_bytes", "flush_ms", "mode")

    def __init__(self, max_rows: int, max_bytes: int, flush_ms: float,
                 mode: str):
        self.max_rows = max(1, int(max_rows))
        self.max_bytes = max(1, int(max_bytes))
        self.flush_ms = float(flush_ms)
        self.mode = mode

    @classmethod
    def resolve(cls, declaration: Optional[dict], cfg) -> "BatchSettings":
        d = declaration or {}
        return cls(d.get("max_rows", getattr(cfg, "batch_max_rows", 4096)),
                   d.get("max_bytes", getattr(cfg, "batch_max_bytes",
                                              32 * 1024 * 1024)),
                   d.get("flush_ms", getattr(cfg, "batch_flush_ms", 25.0)),
                   d.get("mode", getattr(cfg, "batch_padding", "ragged")))


class BatchingExecutor:
    """One per producer (stream producer thread / op execute call). Feed
    source pieces in order; outputs come back re-split to those boundaries,
    possibly several pieces per feed (timer + budget both firing) or zero
    (still buffering) — ``finish()`` drains the tail."""

    def __init__(self, op_name: str, exprs, ctx,
                 settings: Optional[BatchSettings] = None, clock=time.monotonic):
        self.op_name = op_name
        self.exprs = exprs
        self.ctx = ctx
        self.settings = settings or BatchSettings.resolve(None, ctx.cfg)
        self._coalescer = Coalescer(self.settings.max_rows,
                                    self.settings.max_bytes,
                                    self.settings.flush_ms,
                                    ledger=getattr(ctx, "ledger", None),
                                    clock=clock)
        self._degraded = False

    # ------------------------------------------------------------ pieces
    def _apply_one(self, part: MicroPartition) -> MicroPartition:
        """The per-piece UDF path (the degrade target and the byte-identity
        oracle): evaluate the projection on one source piece alone."""
        with exec_ctx_scope(self.ctx):
            return part.eval_expression_list(self.exprs)

    def _pad(self, part: MicroPartition, rows: int):
        """Pad to the next power-of-two bucket by repeating the last valid
        row (any real row works — padding is sliced off after apply; the
        last row keeps the gather contiguous)."""
        bucket = _next_bucket(rows)
        pad_n = bucket - rows
        if pad_n <= 0 or rows == 0:
            return part, 0
        idx = np.concatenate([np.arange(rows, dtype=np.int64),
                              np.full(pad_n, rows - 1, dtype=np.int64)])
        return part.take(Series.from_numpy(idx, "idx")), pad_n

    def _run_flush(self, f: Flush) -> List[MicroPartition]:
        from .. import faults

        ctx, stats = self.ctx, self.ctx.stats
        prof = stats.profiler
        try:
            with prof.span("batch.coalesce", op=self.op_name, kind="phase",
                           rows=f.rows, pieces=len(f.parts)):
                faults.check("batch.coalesce", stats)
                batch = (f.parts[0] if len(f.parts) == 1
                         else MicroPartition.concat(f.parts))
                pad_n = 0
                capacity = max(self.settings.max_rows, f.rows)
                if self.settings.mode == "padded" and f.rows:
                    batch, pad_n = self._pad(batch, f.rows)
                    capacity = f.rows + pad_n
        except Exception as e:
            # coalesce failed (injected or real): degrade this executor to
            # the per-piece UDF path — byte-identical, never a query failure
            stats.bump("batch_coalesce_faults")
            _proc_bump("coalesce_faults")
            self._degraded = True
            self._coalescer.settle(f)
            from ..obs.log import get_logger

            get_logger("batch").warning("batch_coalesce_degraded",
                                        op=self.op_name, error=repr(e))
            return [self._apply_one(p) for p in f.parts]

        stats.bump("batches_formed")
        stats.bump("batch_rows", f.rows)
        stats.bump("batch_capacity_rows", capacity)
        if pad_n:
            stats.bump("batch_rows_padded", pad_n)
        stats.bump(f"batch_flushes_{f.reason}")
        _proc_bump("batches_formed")
        _proc_bump(f"flushes_{f.reason}")

        try:
            with prof.span("actor.apply", op=self.op_name, kind="phase",
                           rows=f.rows):
                with exec_ctx_scope(ctx):
                    out = batch.eval_expression_list(self.exprs)
            if pad_n:
                out = out.slice(0, f.rows)

            # re-split to EXACT source boundaries (prefix sums over feed
            # order)
            pieces: List[MicroPartition] = []
            off = 0
            for p in f.parts:
                n = len(p)
                pieces.append(out.slice(off, off + n))
                off += n
            return pieces
        finally:
            # settle even when the apply raises (e.g. a typed model-load
            # failure) — the error may fail the query, but a handed-out
            # flush must never leave its ledger charge outstanding
            self._coalescer.settle(f)

    # ------------------------------------------------------------ driver
    def feed(self, part: MicroPartition) -> List[MicroPartition]:
        if self._degraded:
            return [self._apply_one(part)]
        outs: List[MicroPartition] = []
        for f in self._coalescer.feed(part):
            outs.extend(self._run_flush(f))
        return outs

    def finish(self) -> List[MicroPartition]:
        outs: List[MicroPartition] = []
        for f in self._coalescer.finish():
            outs.extend(self._run_flush(f))
        return outs

    def abort(self) -> None:
        """Teardown without apply: settle any still-buffered ledger charge
        (idempotent; a clean finish leaves nothing buffered)."""
        for f in self._coalescer.finish():
            self._coalescer.settle(f)
