"""Dynamic-batching UDF executor (ISSUE 18, ROADMAP item 4).

Decouples UDF batch size from partition size: morsels (streaming path) and
whole partitions (non-streaming path) are coalesced across boundaries into
device-friendly batches under a byte/row budget with a max-latency flush
timer, applied once, and re-split to exact source boundaries — so outputs
are byte-identical to the per-partition path (the standing invariant).

Modules:
  coalesce.py  — the budget/timer flush machine (fault site batch.coalesce)
  actors.py    — ModelActorPool: pinned per-process model instances, LRU
                 under the ledger's model_cache_bytes account (actor.load)
  executor.py  — BatchingExecutor: coalesce → pad → apply → re-split
  device.py    — jit'd apply behind the device breaker with host fallback
"""

from .actors import (ModelActorPool, get_model_pool, model_pools_snapshot,
                     pinned_model_count, shutdown_all_models)
from .coalesce import Coalescer, Flush
from .executor import BatchingExecutor, BatchSettings

__all__ = [
    "BatchSettings",
    "BatchingExecutor",
    "Coalescer",
    "Flush",
    "ModelActorPool",
    "get_model_pool",
    "model_pools_snapshot",
    "pinned_model_count",
    "shutdown_all_models",
]
