"""Pinned model actors for batch-declared class UDFs.

A ModelActorPool extends the actor-pool machinery (actor_pool.ActorPool)
with the pinning semantics batched inference needs:

  - ONE instance per process per model fingerprint (class + init args +
    device slot): weights load exactly once, then stay resident ACROSS
    queries — the serving runtime's back-to-back queries hit a warm model.
  - Residency is charged to the process ledger's ``model_cache_bytes``
    account (a class may declare ``weight_bytes``; undeclared models charge
    0 and are still LRU-tracked). When resident bytes exceed the
    ``model_cache_bytes`` config budget, least-recently-used pools are
    evicted (shut down, charge released) — never the one just admitted.
  - Construction passes the ``actor.load`` fault site; ANY load failure
    (injected or real) surfaces as a typed DaftResourceError naming the
    model, with no half-initialized pool left registered — never a hang.

Worker threads come from ActorPool and carry its ``daft-actor`` name prefix,
so the serving runtime's thread-leak accounting already covers them.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, List, Optional, Tuple

from ..actor_pool import ActorPool
from ..errors import DaftResourceError
from ..obs.log import get_logger

logger = get_logger("batch.actors")

_lock = threading.Lock()
# fingerprint -> ModelActorPool, ordered oldest-use first (move_to_end on use)
_model_pools: "OrderedDict[str, ModelActorPool]" = OrderedDict()


def model_fingerprint(cls: type, init_args: Optional[tuple],
                      device: int = 0) -> str:
    return f"{cls.__module__}.{cls.__qualname__}|{init_args!r}|dev{device}"


class ModelActorPool:
    """One pinned model instance behind a single-worker ActorPool."""

    def __init__(self, cls: type, init_args: Optional[tuple], device: int = 0):
        from .. import faults

        self.cls = cls
        self.fingerprint = model_fingerprint(cls, init_args, device)
        self.device = device
        self.weight_bytes = int(getattr(cls, "weight_bytes", 0) or 0)
        self.applies = 0
        self.last_used = time.monotonic()
        try:
            faults.check("actor.load")
            self._pool = ActorPool(cls, init_args, concurrency=1)
        except Exception as e:
            raise DaftResourceError(
                f"model load failed for {cls.__qualname__} "
                f"(fingerprint {self.fingerprint}): {e!r}") from e

    def apply(self, args: List[Any], n: int) -> Any:
        """Run instance(*args) on the pinned worker (serialized per model)."""
        self.applies += 1
        self.last_used = time.monotonic()
        return self._pool.map_batches([tuple(args)])[0]

    def jax_callable(self):
        """The model's opt-in jax-traceable apply (``apply_jax`` attribute),
        or None — the device path (batch/device.py) declines without it."""
        return getattr(self.cls, "apply_jax", None)

    def shutdown(self) -> None:
        self._pool.shutdown()


def _charge(delta: int) -> None:
    if not delta:
        return
    try:
        from ..spill import MEMORY_LEDGER

        MEMORY_LEDGER.cache_account("model_cache_bytes", delta)
    except Exception as e:  # ledger unavailable during teardown
        logger.warning("model_cache_ledger_charge_failed", error=repr(e))


def _budget_bytes() -> int:
    from ..context import get_context

    return int(get_context().execution_config.model_cache_bytes)


def get_model_pool(cls: type, init_args: Optional[tuple],
                   device: int = 0) -> ModelActorPool:
    """The pinned pool for this model, constructing (and LRU-evicting past
    the model_cache_bytes budget) on first use."""
    fp = model_fingerprint(cls, init_args, device)
    evicted: List[ModelActorPool] = []
    with _lock:
        pool = _model_pools.get(fp)
        if pool is not None:
            _model_pools.move_to_end(fp)
            return pool
        pool = ModelActorPool(cls, init_args, device)  # raises typed on failure
        _model_pools[fp] = pool
        _model_pools.move_to_end(fp)
        _charge(pool.weight_bytes)
        budget = _budget_bytes()
        while (len(_model_pools) > 1
               and sum(p.weight_bytes for p in _model_pools.values()) > budget):
            _, lru = _model_pools.popitem(last=False)
            evicted.append(lru)
    for lru in evicted:
        logger.info("model_pool_evicted", fingerprint=lru.fingerprint,
                    weight_bytes=lru.weight_bytes)
        lru.shutdown()
        _charge(-lru.weight_bytes)
    return pool


def pinned_model_count() -> int:
    with _lock:
        return len(_model_pools)


def resident_weight_bytes() -> int:
    with _lock:
        return sum(p.weight_bytes for p in _model_pools.values())


def model_pools_snapshot() -> List[dict]:
    """Per-pool view for dt.health()['batching'] / the smoke tool."""
    with _lock:
        return [{"fingerprint": p.fingerprint, "weight_bytes": p.weight_bytes,
                 "applies": p.applies, "device": p.device}
                for p in _model_pools.values()]


def shutdown_all_models() -> None:
    with _lock:
        pools = list(_model_pools.values())
        _model_pools.clear()
    for p in pools:
        p.shutdown()
        _charge(-p.weight_bytes)
