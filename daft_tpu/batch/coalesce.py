"""Morsel coalescing for the dynamic-batching executor.

A Coalescer buffers whole input morsels/partitions (never splitting one
across batches — re-split then falls out of simple prefix sums) and decides
when the buffered run becomes a batch:

  - ``budget``: buffered rows reach ``max_rows`` or bytes reach ``max_bytes``
  - ``timer``:  the oldest buffered morsel has waited ≥ ``flush_ms`` by the
                time the next feed arrives (no background thread: flush
                latency is bounded by the stream's own cadence, and the
                partition-end flush below bounds the tail)
  - ``end``:    the source is exhausted (``finish()``)

Buffered bytes are charged to the query ledger's ``batch_inflight`` account
at feed and settled when the flush is handed to the executor — a nonzero
account after a query is a leak (tests/test_batch.py pins zero).
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional

from ..micropartition import MicroPartition


def _part_bytes(p: MicroPartition) -> int:
    try:
        return int(p.size_bytes() or 0)
    except Exception:
        return 0


class Flush:
    """One completed batch: the buffered source morsels in feed order plus
    the bookkeeping the executor needs to apply-and-re-split."""

    __slots__ = ("parts", "rows", "bytes", "reason")

    def __init__(self, parts: List[MicroPartition], rows: int, nbytes: int,
                 reason: str):
        self.parts = parts
        self.rows = rows
        self.bytes = nbytes
        self.reason = reason  # "budget" | "timer" | "end"


class Coalescer:
    """Single-producer flush machine (one per stream producer / per op
    execute). Not thread-safe by design — each producer owns its own."""

    def __init__(self, max_rows: int, max_bytes: int, flush_ms: float,
                 ledger=None, clock: Callable[[], float] = time.monotonic):
        self.max_rows = max(1, int(max_rows))
        self.max_bytes = max(1, int(max_bytes))
        self.flush_ms = float(flush_ms)
        self._ledger = ledger
        self._clock = clock
        self._parts: List[MicroPartition] = []
        self._rows = 0
        self._bytes = 0
        self._oldest: Optional[float] = None

    @property
    def buffered_rows(self) -> int:
        return self._rows

    @property
    def buffered_bytes(self) -> int:
        return self._bytes

    def _take(self, reason: str) -> Flush:
        f = Flush(self._parts, self._rows, self._bytes, reason)
        self._parts, self._rows, self._bytes, self._oldest = [], 0, 0, None
        return f

    def settle(self, f: Flush) -> None:
        """Release the ledger charge for a handed-out flush (the executor
        calls this once the batch's outputs exist — or on the degrade path)."""
        if self._ledger is not None and f.bytes:
            self._ledger.batch_done(f.bytes)

    def feed(self, part: MicroPartition) -> List[Flush]:
        """Buffer one morsel; return every batch that became due (a timer
        flush of the old run can precede a budget flush of the new one)."""
        out: List[Flush] = []
        now = self._clock()
        if (self._parts and self.flush_ms >= 0
                and (now - self._oldest) * 1000.0 >= self.flush_ms):
            out.append(self._take("timer"))
        nb = _part_bytes(part)
        if self._ledger is not None and nb:
            self._ledger.batch_started(nb)
        if not self._parts:
            self._oldest = now
        self._parts.append(part)
        self._rows += len(part)
        self._bytes += nb
        if self._rows >= self.max_rows or self._bytes >= self.max_bytes:
            out.append(self._take("budget"))
        return out

    def finish(self) -> List[Flush]:
        """Flush whatever remains (source exhausted)."""
        if not self._parts:
            return []
        return [self._take("end")]
