"""DataType system for the TPU-native dataframe engine.

Covers the full logical type lattice of the reference engine
(`src/daft-core/src/datatypes/dtype.rs:14-99` in the reference tree), including the
multimodal types (Embedding / Image / FixedShapeImage / Tensor / FixedShapeTensor /
Python). Backed by Apache Arrow on the host; numeric / temporal types additionally have
a device (jax) representation used by the jit'd kernel path.
"""

from __future__ import annotations

import enum
from typing import Any, Optional, Tuple

import pyarrow as pa


class TypeKind(enum.Enum):
    NULL = "null"
    BOOL = "bool"
    INT8 = "int8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    UINT16 = "uint16"
    UINT32 = "uint32"
    UINT64 = "uint64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    DECIMAL128 = "decimal128"
    STRING = "string"
    BINARY = "binary"
    FIXED_SIZE_BINARY = "fixed_size_binary"
    DATE = "date"
    TIME = "time"
    TIMESTAMP = "timestamp"
    DURATION = "duration"
    INTERVAL = "interval"
    LIST = "list"
    FIXED_SIZE_LIST = "fixed_size_list"
    STRUCT = "struct"
    MAP = "map"
    EXTENSION = "extension"
    EMBEDDING = "embedding"
    IMAGE = "image"
    FIXED_SHAPE_IMAGE = "fixed_shape_image"
    TENSOR = "tensor"
    FIXED_SHAPE_TENSOR = "fixed_shape_tensor"
    SPARSE_TENSOR = "sparse_tensor"
    PYTHON = "python"
    UNKNOWN = "unknown"


_INTEGER_KINDS = {
    TypeKind.INT8,
    TypeKind.INT16,
    TypeKind.INT32,
    TypeKind.INT64,
    TypeKind.UINT8,
    TypeKind.UINT16,
    TypeKind.UINT32,
    TypeKind.UINT64,
}
_FLOAT_KINDS = {TypeKind.FLOAT32, TypeKind.FLOAT64}
_TEMPORAL_KINDS = {TypeKind.DATE, TypeKind.TIME, TypeKind.TIMESTAMP, TypeKind.DURATION}

_SIGNED_INTS = [TypeKind.INT8, TypeKind.INT16, TypeKind.INT32, TypeKind.INT64]
_UNSIGNED_INTS = [TypeKind.UINT8, TypeKind.UINT16, TypeKind.UINT32, TypeKind.UINT64]

_BIT_WIDTH = {
    TypeKind.BOOL: 1,
    TypeKind.INT8: 8,
    TypeKind.INT16: 16,
    TypeKind.INT32: 32,
    TypeKind.INT64: 64,
    TypeKind.UINT8: 8,
    TypeKind.UINT16: 16,
    TypeKind.UINT32: 32,
    TypeKind.UINT64: 64,
    TypeKind.FLOAT32: 32,
    TypeKind.FLOAT64: 64,
}

# Image modes supported by the image type (reference: ImageMode in
# src/daft-core/src/datatypes/image_mode.rs).
IMAGE_MODES = ("L", "LA", "RGB", "RGBA", "L16", "LA16", "RGB16", "RGBA16", "RGB32F", "RGBA32F")
_IMAGE_MODE_CHANNELS = {
    "L": 1, "LA": 2, "RGB": 3, "RGBA": 4,
    "L16": 1, "LA16": 2, "RGB16": 3, "RGBA16": 4,
    "RGB32F": 3, "RGBA32F": 4,
}


class DataType:
    """A logical data type. Immutable and hashable."""

    __slots__ = ("kind", "params")

    def __init__(self, kind: TypeKind, params: Tuple = ()):  # params: hashable tuple
        object.__setattr__(self, "kind", kind)
        object.__setattr__(self, "params", params)

    def __setattr__(self, *a):  # pragma: no cover
        raise AttributeError("DataType is immutable")

    def __reduce__(self):
        # default unpickling would go through the blocked __setattr__;
        # rebuilding through __init__ keeps the immutability contract while
        # letting types cross process boundaries (dist/ worker transport)
        return (DataType, (self.kind, self.params))

    # --- constructors -----------------------------------------------------
    @staticmethod
    def null() -> "DataType":
        return DataType(TypeKind.NULL)

    @staticmethod
    def bool() -> "DataType":
        return DataType(TypeKind.BOOL)

    @staticmethod
    def int8() -> "DataType":
        return DataType(TypeKind.INT8)

    @staticmethod
    def int16() -> "DataType":
        return DataType(TypeKind.INT16)

    @staticmethod
    def int32() -> "DataType":
        return DataType(TypeKind.INT32)

    @staticmethod
    def int64() -> "DataType":
        return DataType(TypeKind.INT64)

    @staticmethod
    def uint8() -> "DataType":
        return DataType(TypeKind.UINT8)

    @staticmethod
    def uint16() -> "DataType":
        return DataType(TypeKind.UINT16)

    @staticmethod
    def uint32() -> "DataType":
        return DataType(TypeKind.UINT32)

    @staticmethod
    def uint64() -> "DataType":
        return DataType(TypeKind.UINT64)

    @staticmethod
    def float32() -> "DataType":
        return DataType(TypeKind.FLOAT32)

    @staticmethod
    def float64() -> "DataType":
        return DataType(TypeKind.FLOAT64)

    @staticmethod
    def decimal128(precision: int, scale: int) -> "DataType":
        if not 1 <= precision <= 38:
            raise ValueError(f"decimal128 precision must be in [1, 38], got {precision}")
        return DataType(TypeKind.DECIMAL128, (precision, scale))

    @staticmethod
    def string() -> "DataType":
        return DataType(TypeKind.STRING)

    @staticmethod
    def binary() -> "DataType":
        return DataType(TypeKind.BINARY)

    @staticmethod
    def fixed_size_binary(size: int) -> "DataType":
        return DataType(TypeKind.FIXED_SIZE_BINARY, (size,))

    @staticmethod
    def date() -> "DataType":
        return DataType(TypeKind.DATE)

    @staticmethod
    def time(timeunit: str = "us") -> "DataType":
        _check_timeunit(timeunit, allowed=("us", "ns"))
        return DataType(TypeKind.TIME, (timeunit,))

    @staticmethod
    def timestamp(timeunit: str = "us", timezone: Optional[str] = None) -> "DataType":
        _check_timeunit(timeunit)
        return DataType(TypeKind.TIMESTAMP, (timeunit, timezone))

    @staticmethod
    def duration(timeunit: str = "us") -> "DataType":
        _check_timeunit(timeunit)
        return DataType(TypeKind.DURATION, (timeunit,))

    @staticmethod
    def interval() -> "DataType":
        return DataType(TypeKind.INTERVAL)

    @staticmethod
    def list(inner: "DataType") -> "DataType":
        return DataType(TypeKind.LIST, (inner,))

    @staticmethod
    def fixed_size_list(inner: "DataType", size: int) -> "DataType":
        return DataType(TypeKind.FIXED_SIZE_LIST, (inner, size))

    @staticmethod
    def struct(fields: dict) -> "DataType":
        return DataType(TypeKind.STRUCT, tuple(fields.items()))

    @staticmethod
    def map(key: "DataType", value: "DataType") -> "DataType":
        return DataType(TypeKind.MAP, (key, value))

    @staticmethod
    def extension(name: str, storage: "DataType", metadata: Optional[str] = None) -> "DataType":
        return DataType(TypeKind.EXTENSION, (name, storage, metadata))

    @staticmethod
    def embedding(inner: "DataType", size: int) -> "DataType":
        if not (inner.is_numeric()):
            raise ValueError(f"embedding inner type must be numeric, got {inner}")
        return DataType(TypeKind.EMBEDDING, (inner, size))

    @staticmethod
    def image(mode: Optional[str] = None, height: Optional[int] = None, width: Optional[int] = None) -> "DataType":
        if mode is not None and mode not in IMAGE_MODES:
            raise ValueError(f"unknown image mode {mode!r}; expected one of {IMAGE_MODES}")
        if height is not None or width is not None:
            if mode is None or height is None or width is None:
                raise ValueError("fixed-shape image requires mode, height and width")
            return DataType(TypeKind.FIXED_SHAPE_IMAGE, (mode, height, width))
        return DataType(TypeKind.IMAGE, (mode,))

    @staticmethod
    def tensor(inner: "DataType", shape: Optional[Tuple[int, ...]] = None) -> "DataType":
        if shape is not None:
            return DataType(TypeKind.FIXED_SHAPE_TENSOR, (inner, tuple(shape)))
        return DataType(TypeKind.TENSOR, (inner,))

    @staticmethod
    def sparse_tensor(inner: "DataType") -> "DataType":
        return DataType(TypeKind.SPARSE_TENSOR, (inner,))

    @staticmethod
    def python() -> "DataType":
        return DataType(TypeKind.PYTHON)

    # --- predicates -------------------------------------------------------
    def is_null(self) -> bool:
        return self.kind == TypeKind.NULL

    def is_boolean(self) -> bool:
        return self.kind == TypeKind.BOOL

    def is_integer(self) -> bool:
        return self.kind in _INTEGER_KINDS

    def is_signed_integer(self) -> bool:
        return self.kind in _SIGNED_INTS

    def is_unsigned_integer(self) -> bool:
        return self.kind in _UNSIGNED_INTS

    def is_floating(self) -> bool:
        return self.kind in _FLOAT_KINDS

    def is_numeric(self) -> bool:
        return self.is_integer() or self.is_floating() or self.kind == TypeKind.DECIMAL128

    def is_temporal(self) -> bool:
        return self.kind in _TEMPORAL_KINDS

    def is_string(self) -> bool:
        return self.kind == TypeKind.STRING

    def is_binary(self) -> bool:
        return self.kind in (TypeKind.BINARY, TypeKind.FIXED_SIZE_BINARY)

    def is_list(self) -> bool:
        return self.kind in (TypeKind.LIST, TypeKind.FIXED_SIZE_LIST)

    def is_nested(self) -> bool:
        return self.kind in (
            TypeKind.LIST, TypeKind.FIXED_SIZE_LIST, TypeKind.STRUCT, TypeKind.MAP,
            TypeKind.EMBEDDING, TypeKind.IMAGE, TypeKind.FIXED_SHAPE_IMAGE,
            TypeKind.TENSOR, TypeKind.FIXED_SHAPE_TENSOR, TypeKind.SPARSE_TENSOR,
        )

    def is_python(self) -> bool:
        return self.kind == TypeKind.PYTHON

    def is_comparable(self) -> bool:
        return (
            self.is_numeric() or self.is_boolean() or self.is_string()
            or self.is_binary() or self.is_temporal() or self.is_null()
        )

    def is_hashable(self) -> bool:
        return self.is_comparable() or self.is_list()

    def is_device_representable(self) -> bool:
        """True if the physical values can live on a TPU as a dense jax array."""
        if self.kind in _BIT_WIDTH or self.is_temporal():
            return True
        if self.kind in (TypeKind.FIXED_SIZE_LIST, TypeKind.EMBEDDING):
            return self.params[0].is_device_representable()
        if self.kind in (TypeKind.FIXED_SHAPE_TENSOR,):
            return self.params[0].is_device_representable()
        if self.kind == TypeKind.FIXED_SHAPE_IMAGE:
            return True
        return False

    def bit_width(self) -> int:
        try:
            return _BIT_WIDTH[self.kind]
        except KeyError:
            raise ValueError(f"{self} has no fixed bit width") from None

    # --- nested accessors -------------------------------------------------
    @property
    def inner(self) -> "DataType":
        if self.kind in (TypeKind.LIST, TypeKind.TENSOR, TypeKind.SPARSE_TENSOR):
            return self.params[0]
        if self.kind in (TypeKind.FIXED_SIZE_LIST, TypeKind.EMBEDDING):
            return self.params[0]
        if self.kind == TypeKind.FIXED_SHAPE_TENSOR:
            return self.params[0]
        if self.kind == TypeKind.MAP:
            return DataType.struct({"key": self.params[0], "value": self.params[1]})
        raise ValueError(f"{self} has no inner type")

    @property
    def size(self) -> int:
        if self.kind in (TypeKind.FIXED_SIZE_LIST, TypeKind.EMBEDDING):
            return self.params[1]
        if self.kind == TypeKind.FIXED_SIZE_BINARY:
            return self.params[0]
        raise ValueError(f"{self} has no fixed size")

    @property
    def fields(self) -> dict:
        if self.kind != TypeKind.STRUCT:
            raise ValueError(f"{self} is not a struct")
        return dict(self.params)

    @property
    def image_mode(self) -> Optional[str]:
        if self.kind == TypeKind.IMAGE:
            return self.params[0]
        if self.kind == TypeKind.FIXED_SHAPE_IMAGE:
            return self.params[0]
        raise ValueError(f"{self} is not an image type")

    @property
    def tensor_shape(self) -> Tuple[int, ...]:
        if self.kind == TypeKind.FIXED_SHAPE_TENSOR:
            return self.params[1]
        if self.kind == TypeKind.FIXED_SHAPE_IMAGE:
            mode, h, w = self.params
            return (h, w, _IMAGE_MODE_CHANNELS[mode])
        raise ValueError(f"{self} has no static shape")

    # --- conversions ------------------------------------------------------
    def to_arrow(self) -> pa.DataType:
        return _to_arrow(self)

    @staticmethod
    def from_arrow(t: pa.DataType) -> "DataType":
        return _from_arrow(t)

    def to_physical(self) -> "DataType":
        """The physical (storage) type of a logical type."""
        k = self.kind
        if k == TypeKind.DATE:
            return DataType.int32()
        if k in (TypeKind.TIME, TypeKind.TIMESTAMP, TypeKind.DURATION):
            return DataType.int64()
        if k == TypeKind.EMBEDDING:
            return DataType.fixed_size_list(self.params[0].to_physical(), self.params[1])
        if k == TypeKind.IMAGE:
            return DataType.struct(
                {
                    "data": DataType.list(DataType.uint8()),
                    "channel": DataType.uint16(),
                    "height": DataType.uint32(),
                    "width": DataType.uint32(),
                    "mode": DataType.uint8(),
                }
            )
        if k == TypeKind.FIXED_SHAPE_IMAGE:
            mode, h, w = self.params
            dt = DataType.uint8() if not mode.endswith(("16", "32F")) else (
                DataType.uint16() if mode.endswith("16") else DataType.float32()
            )
            return DataType.fixed_size_list(dt, h * w * _IMAGE_MODE_CHANNELS[mode])
        if k == TypeKind.TENSOR:
            return DataType.struct({"data": DataType.list(self.params[0]), "shape": DataType.list(DataType.uint64())})
        if k == TypeKind.FIXED_SHAPE_TENSOR:
            inner, shape = self.params
            n = 1
            for s in shape:
                n *= s
            return DataType.fixed_size_list(inner.to_physical(), n)
        return self

    def to_numpy_dtype(self):
        import numpy as np

        m = {
            TypeKind.BOOL: np.bool_, TypeKind.INT8: np.int8, TypeKind.INT16: np.int16,
            TypeKind.INT32: np.int32, TypeKind.INT64: np.int64, TypeKind.UINT8: np.uint8,
            TypeKind.UINT16: np.uint16, TypeKind.UINT32: np.uint32, TypeKind.UINT64: np.uint64,
            TypeKind.FLOAT32: np.float32, TypeKind.FLOAT64: np.float64,
        }
        if self.kind in m:
            return np.dtype(m[self.kind])
        if self.is_temporal():
            return np.dtype(np.int64) if self.kind != TypeKind.DATE else np.dtype(np.int32)
        raise ValueError(f"{self} has no numpy dtype")

    # --- dunder -----------------------------------------------------------
    def __eq__(self, other: Any) -> bool:
        return isinstance(other, DataType) and self.kind == other.kind and self.params == other.params

    def __hash__(self) -> int:
        return hash((self.kind, self.params))

    def __repr__(self) -> str:
        k = self.kind
        if not self.params:
            return k.value
        if k == TypeKind.DECIMAL128:
            return f"decimal128({self.params[0]}, {self.params[1]})"
        if k == TypeKind.TIMESTAMP:
            tu, tz = self.params
            return f"timestamp[{tu}]" if tz is None else f"timestamp[{tu}, {tz}]"
        if k in (TypeKind.TIME, TypeKind.DURATION):
            return f"{k.value}[{self.params[0]}]"
        if k == TypeKind.LIST:
            return f"list[{self.params[0]!r}]"
        if k == TypeKind.FIXED_SIZE_LIST:
            return f"fixed_size_list[{self.params[0]!r}; {self.params[1]}]"
        if k == TypeKind.STRUCT:
            inner = ", ".join(f"{n}: {t!r}" for n, t in self.params)
            return f"struct[{inner}]"
        if k == TypeKind.MAP:
            return f"map[{self.params[0]!r}: {self.params[1]!r}]"
        if k == TypeKind.EMBEDDING:
            return f"embedding[{self.params[0]!r}; {self.params[1]}]"
        if k == TypeKind.IMAGE:
            return "image" if self.params[0] is None else f"image[{self.params[0]}]"
        if k == TypeKind.FIXED_SHAPE_IMAGE:
            return f"image[{self.params[0]}, {self.params[1]}x{self.params[2]}]"
        if k == TypeKind.TENSOR:
            return f"tensor[{self.params[0]!r}]"
        if k == TypeKind.FIXED_SHAPE_TENSOR:
            return f"tensor[{self.params[0]!r}; {self.params[1]}]"
        if k == TypeKind.SPARSE_TENSOR:
            return f"sparse_tensor[{self.params[0]!r}]"
        if k == TypeKind.EXTENSION:
            return f"extension[{self.params[0]}]"
        if k == TypeKind.FIXED_SIZE_BINARY:
            return f"fixed_size_binary[{self.params[0]}]"
        return f"{k.value}{self.params!r}"


def _check_timeunit(tu: str, allowed=("s", "ms", "us", "ns")) -> None:
    if tu not in allowed:
        raise ValueError(f"invalid time unit {tu!r}; expected one of {allowed}")


# ---------------------------------------------------------------------------
# Arrow conversion
# ---------------------------------------------------------------------------

_ARROW_EXT_PREFIX = "daft_tpu."


def _to_arrow(dt: DataType) -> pa.DataType:
    k = dt.kind
    simple = {
        TypeKind.NULL: pa.null(), TypeKind.BOOL: pa.bool_(),
        TypeKind.INT8: pa.int8(), TypeKind.INT16: pa.int16(),
        TypeKind.INT32: pa.int32(), TypeKind.INT64: pa.int64(),
        TypeKind.UINT8: pa.uint8(), TypeKind.UINT16: pa.uint16(),
        TypeKind.UINT32: pa.uint32(), TypeKind.UINT64: pa.uint64(),
        TypeKind.FLOAT32: pa.float32(), TypeKind.FLOAT64: pa.float64(),
        TypeKind.STRING: pa.large_string(), TypeKind.BINARY: pa.large_binary(),
        TypeKind.DATE: pa.date32(), TypeKind.INTERVAL: pa.month_day_nano_interval(),
    }
    if k in simple:
        return simple[k]
    if k == TypeKind.DECIMAL128:
        return pa.decimal128(*dt.params)
    if k == TypeKind.FIXED_SIZE_BINARY:
        return pa.binary(dt.params[0])
    if k == TypeKind.TIME:
        return pa.time64(dt.params[0])
    if k == TypeKind.TIMESTAMP:
        return pa.timestamp(dt.params[0], tz=dt.params[1])
    if k == TypeKind.DURATION:
        return pa.duration(dt.params[0])
    if k == TypeKind.LIST:
        return pa.large_list(_to_arrow(dt.params[0]))
    if k == TypeKind.FIXED_SIZE_LIST:
        return pa.list_(_to_arrow(dt.params[0]), dt.params[1])
    if k == TypeKind.STRUCT:
        return pa.struct([pa.field(n, _to_arrow(t)) for n, t in dt.params])
    if k == TypeKind.MAP:
        return pa.map_(_to_arrow(dt.params[0]), _to_arrow(dt.params[1]))
    # Multimodal/logical types are stored as their physical arrow type; the logical
    # DataType is carried by the Series/Schema, not by arrow metadata.
    if k in (
        TypeKind.EMBEDDING, TypeKind.IMAGE, TypeKind.FIXED_SHAPE_IMAGE,
        TypeKind.TENSOR, TypeKind.FIXED_SHAPE_TENSOR, TypeKind.SPARSE_TENSOR,
    ):
        return _to_arrow(dt.to_physical())
    if k == TypeKind.EXTENSION:
        return _to_arrow(dt.params[1])
    if k == TypeKind.PYTHON:
        raise ValueError("Python type has no arrow representation")
    raise ValueError(f"cannot convert {dt} to arrow")


def _from_arrow(t: pa.DataType) -> DataType:
    if pa.types.is_null(t):
        return DataType.null()
    if pa.types.is_boolean(t):
        return DataType.bool()
    for name in ("int8", "int16", "int32", "int64", "uint8", "uint16", "uint32", "uint64"):
        if getattr(pa.types, f"is_{name}")(t):
            return DataType(TypeKind(name))
    if pa.types.is_float16(t):
        return DataType.float32()  # promoted: f16 unsupported like reference (dtype.rs:38)
    if pa.types.is_float32(t):
        return DataType.float32()
    if pa.types.is_float64(t):
        return DataType.float64()
    if pa.types.is_decimal(t):
        return DataType.decimal128(t.precision, t.scale)
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return DataType.string()
    if pa.types.is_fixed_size_binary(t):
        return DataType.fixed_size_binary(t.byte_width)
    if pa.types.is_binary(t) or pa.types.is_large_binary(t):
        return DataType.binary()
    if pa.types.is_date32(t) or pa.types.is_date64(t):
        return DataType.date()
    if pa.types.is_time32(t) or pa.types.is_time64(t):
        return DataType.time(t.unit if t.unit in ("us", "ns") else "us")
    if pa.types.is_timestamp(t):
        return DataType.timestamp(t.unit, t.tz)
    if pa.types.is_duration(t):
        return DataType.duration(t.unit)
    if pa.types.is_interval(t):
        return DataType.interval()
    if pa.types.is_fixed_size_list(t):
        return DataType.fixed_size_list(_from_arrow(t.value_type), t.list_size)
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        return DataType.list(_from_arrow(t.value_type))
    if pa.types.is_struct(t):
        return DataType.struct({t.field(i).name: _from_arrow(t.field(i).type) for i in range(t.num_fields)})
    if pa.types.is_map(t):
        return DataType.map(_from_arrow(t.key_type), _from_arrow(t.item_type))
    if pa.types.is_dictionary(t):
        return _from_arrow(t.value_type)
    raise ValueError(f"unsupported arrow type: {t}")


def infer_datatype(value: Any) -> DataType:
    """Infer a DataType from a single Python value (None → null)."""
    import datetime

    import numpy as np

    if value is None:
        return DataType.null()
    if isinstance(value, bool):
        return DataType.bool()
    if isinstance(value, int):
        return DataType.int64()
    if isinstance(value, float):
        return DataType.float64()
    if isinstance(value, str):
        return DataType.string()
    if isinstance(value, (bytes, bytearray)):
        return DataType.binary()
    if isinstance(value, datetime.datetime):
        return DataType.timestamp("us")
    if isinstance(value, datetime.date):
        return DataType.date()
    if isinstance(value, datetime.timedelta):
        return DataType.duration("us")
    if isinstance(value, np.generic):
        # numpy SCALARS (np.int64, np.float32, np.datetime64, np.bool_, ...)
        # are not python int/float/datetime subclasses; map through their
        # dtype so a list of them infers like the equivalent python values
        if isinstance(value, (np.datetime64, np.timedelta64)) \
                and np.isnat(value):
            # NaT is a null, whatever its unit — a unit-less NaT's dtype
            # ('M8') has no arrow mapping and must not poison the column
            return DataType.null()
        try:
            return _from_arrow(pa.from_numpy_dtype(value.dtype))
        except (pa.ArrowNotImplementedError, ValueError, TypeError,
                NotImplementedError):
            return DataType.python()
    if isinstance(value, np.ndarray):
        if value.ndim == 1:
            return DataType.list(_from_arrow(pa.from_numpy_dtype(value.dtype)))
        return DataType.tensor(_from_arrow(pa.from_numpy_dtype(value.dtype)))
    if isinstance(value, (list, tuple)):
        inner = DataType.null()
        for v in value:
            inner = try_unify(inner, infer_datatype(v)) or DataType.python()
        return DataType.list(inner)
    if isinstance(value, dict):
        return DataType.struct({k: infer_datatype(v) for k, v in value.items()})
    return DataType.python()


def try_unify(a: DataType, b: DataType) -> Optional[DataType]:
    """The common supertype of two types, or None if incompatible.

    Mirrors the reference's `try_get_supertype` semantics
    (src/daft-core/src/utils/supertype.rs): null promotes to anything, ints widen,
    int+float → float, anything+python → python.
    """
    if a == b:
        return a
    if a.is_null():
        return b
    if b.is_null():
        return a
    if a.is_python() or b.is_python():
        return DataType.python()
    if a.is_numeric() and b.is_numeric():
        return _numeric_supertype(a, b)
    if a.is_boolean() and b.is_numeric():
        return b
    if b.is_boolean() and a.is_numeric():
        return a
    if a.is_string() and b.is_string():
        return DataType.string()
    if a.kind == TypeKind.LIST and b.kind == TypeKind.LIST:
        inner = try_unify(a.params[0], b.params[0])
        return DataType.list(inner) if inner is not None else None
    if a.kind == TypeKind.TIMESTAMP and b.kind == TypeKind.TIMESTAMP:
        units = ["s", "ms", "us", "ns"]
        tu = units[max(units.index(a.params[0]), units.index(b.params[0]))]
        tz = a.params[1] if a.params[1] == b.params[1] else None
        return DataType.timestamp(tu, tz)
    if a.kind == TypeKind.DURATION and b.kind == TypeKind.DURATION:
        units = ["s", "ms", "us", "ns"]
        return DataType.duration(
            units[max(units.index(a.params[0]), units.index(b.params[0]))])
    if a.kind == TypeKind.DATE and b.kind == TypeKind.TIMESTAMP:
        return b
    if b.kind == TypeKind.DATE and a.kind == TypeKind.TIMESTAMP:
        return a
    return None


def _numeric_supertype(a: DataType, b: DataType) -> DataType:
    if a.kind == TypeKind.DECIMAL128 or b.kind == TypeKind.DECIMAL128:
        return DataType.float64()
    if a.is_floating() or b.is_floating():
        if DataType.float64() in (a, b) or (a.is_integer() and a.bit_width() > 32) or (
            b.is_integer() and b.bit_width() > 32
        ):
            return DataType.float64()
        return DataType.float32()
    aw, bw = a.bit_width(), b.bit_width()
    if a.is_signed_integer() == b.is_signed_integer():
        wide = max(aw, bw)
        kinds = _SIGNED_INTS if a.is_signed_integer() else _UNSIGNED_INTS
        return DataType(kinds[{8: 0, 16: 1, 32: 2, 64: 3}[wide]])
    # mixed signedness: need a signed type wider than the unsigned one; signed+uint64
    # has no such integer, so follow numpy (and the reference's supertype.rs): float64
    uw = aw if a.is_unsigned_integer() else bw
    sw = aw if a.is_signed_integer() else bw
    if uw >= 64:
        return DataType.float64()
    target = max(sw, uw * 2)
    return DataType(_SIGNED_INTS[{8: 0, 16: 1, 32: 2, 64: 3}[target]])
