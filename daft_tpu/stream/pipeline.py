# daftlint: migrated
"""The morsel-driven pipeline driver (README "Streaming execution").

``try_stream`` inspects a physical op during the executor's tree build and,
when it roots a *streamable segment* — ``[Limit?] -> {Project | Filter |
FusedMap}* -> source`` on the host path — replaces the whole segment with
one pipelined stream:

- **producer stages** (one shared-pool task per source partition, a
  bounded window of them in flight — one per worker by default, the same
  fan-out ``_parallel_map`` gives the partition-granular path) morselize
  the partition
  (``iter_morsels``: chunk-wise decode, zero-copy slices) and run every
  map op of the segment per morsel, pushing results into that partition's
  :class:`BoundedChannel`;
- the **consumer** (the pulling thread — the downstream op) drains
  channels in source-partition order and re-chunks morsels back into
  partitions at the segment boundary, so pipeline breakers above keep
  their partition-granular contract and results are byte-identical with
  ``cfg.streaming_execution`` off;
- a **Limit sink** consumes morsels directly: the first output partition
  leaves as soon as enough morsels exist (time-to-first-row no longer
  waits for a whole partition decode), and hitting the limit closes every
  channel — producers stop scanning/decoding work nobody will read
  (``morsels_short_circuited`` counts what was abandoned).

Eligibility (the *morsel contract*): an op streams iff it declares
``morsel_streamable = True`` AND implements ``map_partition`` (daftlint
DTL006 pins the pair), is row-local (UDFs decline: a batch-dependent UDF
applied per morsel could change results), and requests no resources. The
device-kernel path and mesh/multi-host contexts decline entirely — their
execution units are whole resident partitions by design.

Error contract: a producer failure (including injected ``scan.read`` /
``fuse.compile``-site faults) parks on the channel and re-raises on the
CONSUMER thread at the next pull — never a hung channel; consumer-side
teardown (limit, cancellation, deadline, GeneratorExit) closes every
channel, waking blocked producers into an immediate stop.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Iterator, List, Optional

from ..micropartition import MicroPartition
from .channel import WAIT, BoundedChannel, ChannelClosed
from .morsel import iter_morsels

__all__ = ["try_stream", "extract_segment", "StreamSegment"]

# how long the consumer sleeps on an empty channel before re-checking
# deadline/cancellation and producer liveness (a cancelled future must
# surface as query cancellation, never a hang)
_POLL_S = 0.05


class _StopSignal(threading.Event):
    """Cooperative stop for producer stages. ``short_circuit`` tells an
    unwinding producer whether the stop was deliberate early termination
    (limit hit / upstream close — avoided work counts as
    ``morsels_short_circuited``) or error/cancel/deadline teardown (NOT
    counted: a failed query's record must not read as if a limit fired)."""

    short_circuit = False


def _map_streamable(op, ctx) -> bool:
    """The morsel contract: declared streamable (``morsel_streamable``),
    map-class, row-local (no UDFs — they see whole partitions on the
    partition-granular path and may be batch-dependent), and no resource
    requests (accountant admission is per partition task, not per morsel)."""
    from ..execution import op_resource_request
    from ..expressions import expr_has_udf

    if not getattr(op, "morsel_streamable", False) \
            or op.map_partition is None:
        return False
    if len(op.children) != 1:
        return False
    if any(expr_has_udf(e) for e in op._map_exprs()) \
            and not getattr(op, "batch_declared", False):
        # batch-declared UDFs (physical.BatchedUdfOp) lift the decline:
        # the batching declaration IS a row-locality + concurrency
        # contract, and the producer loop gives each one a per-producer
        # BatchingExecutor (see _produce_once)
        return False
    if op_resource_request(op):
        return False
    return True


class StreamSegment:
    """One streamable chain: ``maps`` bottom-up over ``source``, with an
    optional row ``limit`` sink on top. ``count_source`` marks a bypassed
    Scan/InMemory source whose read time the producer must attribute
    (a generic source is pulled through its own traced stream instead)."""

    __slots__ = ("maps", "limit", "source", "count_source")

    def __init__(self, maps: List, limit: Optional[int], source,
                 count_source: bool):
        self.maps = maps
        self.limit = limit
        self.source = source
        self.count_source = count_source


def extract_segment(op, ctx) -> Optional[StreamSegment]:
    """The maximal streamable segment rooted at ``op``, or None when
    streaming would not change anything (no maps and no limit over a
    direct source — the plain lazy pull is already optimal there)."""
    from ..physical import InMemoryOp, LimitOp, ScanOp

    limit = None
    cur = op
    if isinstance(cur, LimitOp) and type(cur) is LimitOp:
        limit = cur.limit
        cur = cur.children[0]
    maps: List = []
    while _map_streamable(cur, ctx):
        maps.append(cur)
        cur = cur.children[0]
    maps.reverse()  # bottom-up application order
    source = cur
    direct = isinstance(source, (ScanOp, InMemoryOp))
    if not maps and not (limit is not None and direct):
        return None
    return StreamSegment(maps, limit, source, count_source=direct)


def try_stream(op, ctx, build, trace: bool = True):
    """Return a pipelined partition stream replacing the segment rooted at
    ``op``, or None when the op/context does not stream. ``build`` is the
    executor's recursive stream builder, used for generic (non-source)
    segment bases."""
    cfg = ctx.cfg
    if not getattr(cfg, "streaming_execution", True):
        return None
    if getattr(cfg, "use_device_kernels", False):
        # The device path wants whole resident partitions: one fused kernel
        # over one big buffer beats many small dispatches, and morsel
        # slices would orphan the HBM residency caches. EXCEPT in
        # device-morsel mode (cfg.device_residency): for segment-shaped
        # chains — every map device-pipelinable — each morsel stages to a
        # device batch feeding its own resident program (per-morsel stage
        # caches, same size-bucketed executables), so streaming composes
        # with residency instead of standing it down. Mixed chains still
        # decline: one host-only map would force every morsel through an
        # Arrow round-trip the partition path avoids.
        if not getattr(cfg, "device_residency", True):
            return None
        probe = extract_segment(op, ctx)
        if probe is None or not probe.maps or not all(
                m.device_pipelinable(ctx) for m in probe.maps):
            return None
        # only when slicing actually subdivides: a partition at or under
        # the morsel size already IS one device batch, and the partition-
        # granular double-buffered dispatch path pipelines it better than
        # a one-morsel stream would
        from ..physical import InMemoryOp as _InMem
        msz = max(1, int(getattr(cfg, "morsel_size_rows", 128 * 1024)))
        src = probe.source
        if not (isinstance(src, _InMem)
                and any((p.num_rows_or_none() or 0) > msz
                        for p in src.parts)):
            return None
    if getattr(ctx, "try_device_shuffle", None) is not None \
            or getattr(ctx, "scan_owner", None) is not None:
        # mesh / multi-host: partitions are pinned to devices/processes;
        # morselizing would force foreign reads
        return None
    if getattr(ctx, "dist_backend", None) is not None:
        # distributed runner: map-class work ships to worker PROCESSES at
        # partition granularity through the dispatch backend — in-process
        # morsel channels would keep that work on the driver
        return None
    seg = extract_segment(op, ctx)
    if seg is None:
        return None
    from ..physical import InMemoryOp, ScanOp

    src = seg.source
    if isinstance(src, ScanOp):
        def parts_fn():
            prof = ctx.stats.profiler
            with prof.span("scan.plan", kind="phase"):
                parts = src.plan_parts(ctx)
            return iter(parts), True
    elif isinstance(src, InMemoryOp):
        def parts_fn():
            return iter(src.parts), True
    else:
        def parts_fn():
            # generic base: partitions pulled through the normally-built
            # (traced) upstream stream on the consumer thread
            return build(src), False
    top = seg.maps[-1] if seg.maps else op
    return _run_segment(seg, parts_fn, ctx, top, trace)


def _run_segment(seg: StreamSegment, parts_fn, ctx, top_op,
                 trace: bool) -> Iterator[MicroPartition]:
    """The consumer generator: windowed producer dispatch, in-order channel
    drain, morsel->partition re-chunk (or the limit sink), teardown."""
    from .. import tracing
    from ..execution import QueryCancelledError, _tl

    cfg = ctx.cfg
    stats = ctx.stats
    prof = stats.profiler
    morsel_rows = max(1, int(getattr(cfg, "morsel_size_rows", 128 * 1024)))
    capacity = max(1, int(getattr(cfg, "stream_channel_capacity", 4)))
    window = int(getattr(cfg, "stream_producer_window", 0))
    if window <= 0:
        # one producer stage per worker: the streaming path replaces
        # _parallel_map's full worker fan-out and must not cap the map
        # parallelism below it (memory stays bounded — the per-channel
        # byte cap below divides the budget share by the window)
        window = max(1, ctx.num_workers)
    budget = ctx.memory_budget
    # byte cap per channel: a slice of the query budget split across the
    # producer window, so total streaming working set stays a bounded
    # fraction of memory_budget_bytes (one morsel always admitted)
    max_bytes = None if budget is None else max(1, budget // (4 * window))
    out_schema = seg.maps[-1].schema if seg.maps else seg.source.schema
    top_name = top_op.name()
    stop = _StopSignal()
    pool = ctx.pool()
    pending: deque = deque()  # (channel, future)
    src_iter, skippable = parts_fn()
    state = {"exhausted": False, "closed": False}

    from ..obs.log import current_query_id

    qid = current_query_id()

    def submit_next() -> bool:
        if state["exhausted"]:
            return False
        part = next(src_iter, None)
        if part is None:
            state["exhausted"] = True
            return False
        chan = BoundedChannel(capacity, max_bytes=max_bytes,
                              ledger=ctx.ledger, stats=stats)
        token = prof.capture() if prof.armed else None
        fut = pool.submit(_produce_partition, seg, part, chan, ctx, stop,
                          morsel_rows, token, qid)
        pending.append((chan, fut))
        return True

    def shutdown(short_circuit: bool) -> None:
        # first close wins (and fixes the short-circuit attribution):
        # execute_plan's teardown may shut an orphaned segment down via
        # close_streams() before GC closes the suspended generator, whose
        # GeneratorExit path would then re-enter with short_circuit=True
        if state["closed"]:
            return
        state["closed"] = True
        if short_circuit:
            stop.short_circuit = True
        stop.set()
        while pending:
            chan, fut = pending.popleft()
            if fut.cancel() and short_circuit:
                # the producer never ran: its whole partition was skipped
                stats.bump("morsels_short_circuited")
            chan.close()
        if short_circuit and skippable and not state["exhausted"]:
            # count the source partitions the early stop never read
            # (metadata-only iteration over the remaining scan/in-memory
            # parts list — never materializes)
            n = sum(1 for _ in src_iter)
            if n:
                stats.bump("morsels_short_circuited", n)
            state["exhausted"] = True
        elif not skippable:
            close = getattr(src_iter, "close", None)
            if close is not None:
                close()

    def drain_head(remaining):
        """Drain the head channel into a morsel list; returns (morsels,
        rows, new_remaining, hit_limit). Blocked-on-channel time is
        attributed like dispatch waits (queue_wait phase), so the
        io_wait-vs-compute split still tells a starved pipeline from a
        compute-bound one. Every ``get`` is timed — including slices that
        END with a morsel: a producer-bound pipeline blocks tens of ms
        per get without ever hitting the WAIT timeout and must still
        show as starved (a ready channel costs ~µs, which is noise)."""
        chan, fut = pending[0]
        morsels: List[MicroPartition] = []
        rows = 0
        hit = False
        waited_ns = 0
        while True:
            t0g = time.perf_counter_ns()
            got = chan.get(timeout=_POLL_S)
            waited_ns += time.perf_counter_ns() - t0g
            if got is WAIT:
                if stats.is_cancelled():
                    raise QueryCancelledError(
                        f"query cancelled (at {top_name})")
                ctx.check_deadline()
                if fut.cancelled():
                    raise QueryCancelledError(
                        "query cancelled (stream producer cancelled)")
                if fut.done():
                    # a producer that died without fail()-ing (engine bug)
                    # must surface, never hang the channel
                    exc = fut.exception()
                    if exc is not None:
                        raise exc
                continue
            if got is None:
                break
            m = got
            n = len(m)
            if remaining is not None and rows + n >= remaining:
                if rows + n > remaining:
                    m = m.head(remaining - rows)
                    n = len(m)
                hit = True
            morsels.append(m)
            rows += n
            if hit:
                break
        pending.popleft()
        if hit:
            # the head producer may still be running (or blocked in put()):
            # close ITS channel too — shutdown() only sees channels still
            # in `pending`, and a producer parked on an unclosed channel
            # would hold a pool worker until process exit. Flag the stop
            # as limit-driven FIRST so the unwinding producer counts its
            # abandoned work as short-circuited.
            stop.short_circuit = True
            chan.close()
        stats.bump_max("stream_channel_high_water", chan.high_water)
        if waited_ns:
            stats.dispatch_wait(waited_ns)
        if remaining is not None:
            remaining -= rows
        return morsels, rows, remaining, hit

    remaining = seg.limit
    seq = 0
    short_circuit = False
    # teardown reachability: while this generator is suspended at a yield,
    # only the registry can shut it down if the chain above dies (plain
    # `for` loops never close their inputs, and an exception traceback
    # keeps the suspended frame alive past the pool's lifetime)
    token = ctx.register_stream(shutdown)
    try:
        if remaining is not None and remaining <= 0:
            return
        while True:
            if stats.is_cancelled():
                raise QueryCancelledError(f"query cancelled (at {top_name})")
            ctx.check_deadline()
            # consumer-side op span: covers the windowed submits and the
            # head-channel drain, so producer "morsel" spans captured at
            # submit time parent to THIS op (cross-thread propagation).
            # trace=False mirrors execute_plan skipping the _traced
            # wrapper: no span, no self-time stack, no progress report
            # (producer-side record_op stays, matching _parallel_map's
            # in-worker instrumentation on the partition-granular path)
            sp = (prof.begin(top_name, op=top_name, part=seq)
                  if trace and prof.armed else None)
            t0 = time.perf_counter_ns()
            stack = None
            if trace:
                # mirror _traced's self-time stack so the parent op's
                # explain_analyze self time excludes this pull
                stack = getattr(_tl, "stack", None)
                if stack is None:
                    stack = _tl.stack = []
                stack.append(0)
            pulled = False
            try:
                while len(pending) < window and submit_next():
                    pass
                if not pending:
                    return
                morsels, rows, remaining, hit = drain_head(remaining)
                pulled = True
            finally:
                if stack is not None:
                    dt = time.perf_counter_ns() - t0
                    stack.pop()
                    if stack:
                        stack[-1] += dt
                if sp is not None:
                    if pulled:
                        sp.set_attr("rows", rows)
                        prof.end(sp)
                    else:
                        prof.cancel(sp)
            out = _rechunk(morsels, out_schema)
            seq += 1
            if trace:
                tracing.report_progress(top_name, rows)
            yield out
            if hit:
                # limit satisfied: stop every producer before they decode
                # partitions nobody will read
                short_circuit = True
                shutdown(short_circuit=True)
                return
    except GeneratorExit:
        # deliberate early close from above (LimitOp's partition-granular
        # early-termination, or an abandoned iterator): the avoided scan/
        # decode work IS a short-circuit. Errors/cancel/deadline fall to
        # the bare finally and are never counted — a failed query's
        # record must not read as if a limit fired.
        short_circuit = True
        raise
    finally:
        shutdown(short_circuit=short_circuit)
        ctx.unregister_stream(token)


def _part_bytes(part: MicroPartition) -> int:
    b = part.size_bytes()
    return b if b is not None else 0


def _rechunk(morsels: List[MicroPartition], out_schema) -> MicroPartition:
    """Morsel -> partition re-chunk boundary: ONE concrete Table, exactly
    what the partition-granular map would have produced. A multi-table
    partition here would silently change downstream kernel routing (e.g.
    the chunked-acero grouped agg reassociates float sums differently than
    the collapsed path) and break the byte-identity invariant."""
    from ..table import Table

    tables = [t for m in morsels for t in m._tables if len(t)]
    if not tables:
        return MicroPartition.empty(out_schema)
    if len(tables) == 1:
        return MicroPartition.from_table(tables[0])
    return MicroPartition.from_table(Table.concat(tables))


def _produce_partition(seg: StreamSegment, part: MicroPartition, chan,
                       ctx, stop: threading.Event, morsel_rows: int,
                       token, qid) -> None:
    """Producer stage body (one source partition, runs on the shared
    executor pool): morselize, run the segment's maps per morsel, push
    into the bounded channel. Each morsel's work is a ``morsel`` span
    parented — via the captured ``token`` — to the consumer-side op span,
    and per-op rows/wall feed RuntimeStats so explain_analyze keeps real
    per-op attribution. Any failure parks on the channel for the consumer;
    a close (limit early-stop) unwinds quietly as a short-circuit."""
    from .. import scheduler
    from ..obs.log import query_context

    stats = ctx.stats
    prof = stats.profiler
    scheduler._WORKER_TL.active = True
    act = prof.activate(token) if prof.armed else None
    if act is not None:
        act.__enter__()
    try:
        with query_context(qid):
            try:
                _produce_with_retry(seg, part, chan, ctx, stop, morsel_rows)
                chan.finish()
            except ChannelClosed:
                if stop.short_circuit:
                    stats.bump("morsels_short_circuited")
            except BaseException as e:
                chan.fail(e)
    finally:
        if act is not None:
            act.__exit__(None, None, None)
        scheduler._WORKER_TL.active = False


def _produce_with_retry(seg: StreamSegment, part: MicroPartition, chan,
                        ctx, stop: threading.Event,
                        morsel_rows: int) -> None:
    """The producer's morselize+map loop, with the scheduler's per-task
    transient-retry contract (cfg ``task_retry_attempts``): a
    DaftTransientError — e.g. an injected ``scan.read`` fault that
    exhausted the IO layer's own retries, which leaves the partition
    unloaded and re-readable — re-runs the partition up to the same retry
    budget, but ONLY while nothing has been pushed yet (a mid-stream
    retry would duplicate rows the consumer already drained; that rare
    case fails the query exactly like a non-retryable error)."""
    from ..errors import DaftTransientError
    from ..execution import QueryCancelledError
    from ..obs.log import get_logger

    stats = ctx.stats
    retries_left = max(0, getattr(ctx.cfg, "task_retry_attempts", 0))
    while True:
        try:
            _produce_once(seg, part, chan, ctx, stop, morsel_rows)
            return
        except DaftTransientError:
            if chan.pushed or retries_left <= 0:
                raise
            if stats.is_cancelled():
                raise QueryCancelledError(
                    f"query cancelled (retrying {seg.source.name()})")
            ctx.check_deadline()
            retries_left -= 1
            stats.bump("task_retries")
            get_logger("stream").warning(
                "stream_task_retry", op=seg.source.name(),
                attempts_left=retries_left)
            time.sleep(max(0.0, getattr(ctx.cfg, "task_retry_backoff_s",
                                        0.05)))


def _batch_executors(seg: StreamSegment, ctx) -> dict:
    """One BatchingExecutor per batch-declared map stage, owned by THIS
    producer call (one partition): morsels coalesce across morsel
    boundaries within the partition, outputs re-split to the exact morsel
    boundaries the unbatched path would have produced."""
    execs: dict = {}
    if not getattr(ctx.cfg, "dynamic_batching", True):
        return execs
    for i, mop in enumerate(seg.maps):
        if getattr(mop, "batch_declared", False):
            from ..batch.executor import BatchingExecutor

            execs[i] = BatchingExecutor(mop.name(), mop.exprs, ctx,
                                        settings=mop._settings(ctx))
    return execs


def _produce_once(seg: StreamSegment, part: MicroPartition, chan, ctx,
                  stop: threading.Event, morsel_rows: int) -> None:
    stats = ctx.stats
    prof = stats.profiler
    src_name = seg.source.name()
    execs = _batch_executors(seg, ctx)

    def apply_maps(ms, i0):
        """Run output morsels through maps[i0:]. A batch stage may hold
        morsels back (still coalescing) or release several at once; every
        released morsel keeps its source-boundary identity."""
        for i in range(i0, len(seg.maps)):
            mop = seg.maps[i]
            bx = execs.get(i)
            nxt = []
            for m in ms:
                t0 = time.perf_counter_ns()
                outs = bx.feed(m) if bx is not None \
                    else [mop.map_partition(m, ctx)]
                stats.record_op(mop.name(), sum(len(o) for o in outs),
                                time.perf_counter_ns() - t0,
                                sum(_part_bytes(o) for o in outs))
                nxt.extend(outs)
            ms = nxt
        return ms

    try:
        t_read = time.perf_counter_ns()
        for m in iter_morsels(part, morsel_rows):
            read_ns = time.perf_counter_ns() - t_read
            if stop.is_set():
                if getattr(stop, "short_circuit", False):
                    stats.bump("morsels_short_circuited")
                return
            sp = (prof.begin("morsel", kind="bg")
                  if prof.armed else None)
            outs = []
            try:
                if seg.count_source:
                    # chunk decode happened inside iter_morsels'
                    # pull: attribute it to the (bypassed) source
                    stats.record_op(src_name, len(m), read_ns,
                                    _part_bytes(m))
                outs = apply_maps([m], 0)
            finally:
                if sp is not None:
                    sp.set_attr("rows", sum(len(o) for o in outs))
                    prof.end(sp)
            stats.bump("stream_morsels")
            for o in outs:
                chan.put(o, _part_bytes(o))
            t_read = time.perf_counter_ns()
        # partition end: drain each batch stage bottom-up — a lower
        # stage's tail still flows through every stage above it
        for i in sorted(execs):
            if stop.is_set():
                return
            t0 = time.perf_counter_ns()
            tail = execs[i].finish()
            stats.record_op(seg.maps[i].name(),
                            sum(len(o) for o in tail),
                            time.perf_counter_ns() - t0,
                            sum(_part_bytes(o) for o in tail))
            for o in apply_maps(tail, i + 1):
                chan.put(o, _part_bytes(o))
    finally:
        # stop/error teardown with morsels still buffered: settle their
        # ledger charge (a leaked batch_inflight account fails the leak
        # tests) without running the apply
        for bx in execs.values():
            bx.abort()
