# daftlint: migrated
"""Morsels: the fixed-size unit of streaming execution.

A morsel is a loaded :class:`MicroPartition` wrapping ``Table.slice`` views
of its source partition's reader chunks — zero-copy where Arrow allows
(slices share the backing buffers; only the offsets differ). Morsels never
span chunk boundaries, so a multi-chunk scan partition is morselized
without ever paying ``table()``'s full concat.
"""

from __future__ import annotations

from typing import Iterator

from ..micropartition import MicroPartition

__all__ = ["iter_morsels"]


def iter_morsels(part: MicroPartition,
                 rows: int) -> Iterator[MicroPartition]:
    """Slice ``part`` into loaded morsels of at most ``rows`` rows each.

    An unloaded partition reads through ``iter_chunk_tables()`` — the
    LAZY chunk path (parquet decodes one row group at a time, behind the
    same retry + ``scan.read`` fault contract as the eager read), so the
    first morsel flows after one chunk decode instead of a whole
    partition, and streaming changes WHERE/WHEN the decode runs, never
    what it returns. An empty partition yields exactly ONE empty morsel:
    the driver's re-chunk sink rebuilds source partitions 1:1, empty ones
    included, keeping partition boundaries byte-identical with the
    partition-granular path.
    """
    rows = max(1, int(rows))
    emitted = False
    for t in part.iter_chunk_tables():
        n = len(t)
        for s in range(0, n, rows):
            m = MicroPartition.from_table(t.slice(s, min(s + rows, n)))
            m.owner_process = part.owner_process
            emitted = True
            yield m
    if not emitted:
        m = MicroPartition.empty(part.schema)
        m.owner_process = part.owner_process
        yield m
