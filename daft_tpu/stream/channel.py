# daftlint: migrated
"""Bounded MPSC morsel channel with backpressure and error propagation.

One channel carries one source partition's mapped morsels from its
producer stage (a shared-pool task) to the pipeline's consumer. The bound
is two-dimensional — a morsel-count capacity and an optional byte cap
carved from the query's memory budget — and every queued morsel's bytes
are charged to the query ledger's ``stream_inflight`` balance, so
``dt.health()`` and the bench peak metric see streaming working-set bytes
the same way they see prefetch in-flight bytes. One morsel is always
admitted regardless of the caps (liveness: a morsel larger than the cap
must still flow).

Failure contract: a producer error is stored and re-raised by ``get()`` on
the CONSUMER thread — never a hung channel; ``close()`` (consumer side:
limit early-stop, query error, teardown) drains the queue, returns its
ledger charge, and wakes every blocked producer with
:class:`ChannelClosed` so upstream work stops instead of producing output
nobody will read.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Optional

from ..errors import DaftError

__all__ = ["BoundedChannel", "ChannelClosed", "channels_snapshot"]

# get(timeout=...) expired without an item (distinct from "stream ended",
# which is None): the consumer re-checks deadline/cancel/producer health
WAIT = object()

_registry_lock = threading.Lock()
# live channels, weakly held — the dt.health() channel-occupancy view
_channels: "weakref.WeakSet" = weakref.WeakSet()


def channels_snapshot() -> dict:
    """Process-wide channel occupancy for ``dt.health()``: live (not yet
    drained/closed) channels and their queued morsels/bytes."""
    with _registry_lock:
        chans = list(_channels)
    active = morsels = qbytes = 0
    for ch in chans:
        n, b, done = ch._occupancy()
        if done and n == 0:
            continue
        active += 1
        morsels += n
        qbytes += b
    return {"active_channels": active, "queued_morsels": morsels,
            "queued_bytes": qbytes}


class ChannelClosed(DaftError):
    """Raised out of ``put()`` after the consumer closed the channel; the
    producer unwinds (counted as a short-circuit) instead of blocking on a
    queue nobody drains."""


class BoundedChannel:
    """Bounded MPSC channel of ``(morsel, nbytes)`` pairs (see module
    docstring for the backpressure/close/error contract)."""

    def __init__(self, capacity: int, max_bytes: Optional[int] = None,
                 ledger=None, stats=None):
        self._cond = threading.Condition()
        self._q: deque = deque()
        self._qbytes = 0
        self.capacity = max(1, int(capacity))
        self.max_bytes = max_bytes
        self._finished = False
        self._error: Optional[BaseException] = None
        self.closed = False
        # peak queued morsels, read by the driver into the
        # stream_channel_high_water counter at drain time
        self.high_water = 0
        # morsels successfully put (the producer's retry gate: a partition
        # may only re-run while nothing has been handed downstream)
        self.pushed = 0
        self._ledger = ledger
        self._stats = stats
        with _registry_lock:
            _channels.add(self)

    # ------------------------------------------------------------ producer
    def _has_room(self) -> bool:
        if not self._q:
            return True  # one in-flight always allowed
        if len(self._q) >= self.capacity:
            return False
        if self.max_bytes is not None and self._qbytes >= self.max_bytes:
            return False
        return True

    def put(self, item, nbytes: int) -> None:
        """Enqueue a morsel, blocking (backpressure) while the channel is
        at capacity. Blocked time is counted as a backpressure stall."""
        stalled_ns = 0
        with self._cond:
            if not self._has_room() and not self.closed:
                t0 = time.perf_counter_ns()
                while not self._has_room() and not self.closed:
                    self._cond.wait()
                stalled_ns = time.perf_counter_ns() - t0
            if self.closed:
                raise ChannelClosed("stream channel closed by consumer")
            # charge under the channel lock, BEFORE the morsel is visible:
            # the consumer (or close()) releases a morsel's bytes only
            # after popping it here, so the release can never outrun the
            # charge (an out-of-order stream_done would be clamp-dropped
            # by the ledger and the charge would leak forever)
            if self._ledger is not None and nbytes:
                # daftlint: ledger-escape settled-by=get,close
                self._ledger.stream_started(nbytes)
            self._q.append((item, nbytes))
            self._qbytes += nbytes
            self.pushed += 1
            if len(self._q) > self.high_water:
                self.high_water = len(self._q)
            self._cond.notify_all()
        if stalled_ns and self._stats is not None:
            self._stats.bump("stream_backpressure_stalls")
            self._stats.bump("stream_backpressure_ns", stalled_ns)

    def finish(self) -> None:
        """Producer completed this partition normally."""
        with self._cond:
            self._finished = True
            self._cond.notify_all()

    def fail(self, exc: BaseException) -> None:
        """Producer died: park the error for the consumer's next get()."""
        with self._cond:
            if self._error is None:
                self._error = exc
            self._finished = True
            self._cond.notify_all()

    # ------------------------------------------------------------ consumer
    def get(self, timeout: Optional[float] = None):
        """Next morsel; ``None`` when the producer finished and the queue
        drained; the module-level ``WAIT`` sentinel when ``timeout``
        expired (caller re-checks deadline/cancel/producer liveness). A
        producer error re-raises HERE, on the consumer thread."""
        with self._cond:
            while True:
                if self._error is not None:
                    err, self._error = self._error, None
                    raise err
                if self._q:
                    item, nbytes = self._q.popleft()
                    self._qbytes -= nbytes
                    self._cond.notify_all()
                    break
                if self._finished or self.closed:
                    return None
                if not self._cond.wait(timeout):
                    return WAIT
        if self._ledger is not None and nbytes:
            self._ledger.stream_done(nbytes)
        return item

    def close(self) -> None:
        """Consumer-side close: drop queued morsels (returning their
        ledger charge) and wake every blocked producer into
        ChannelClosed."""
        with self._cond:
            if self.closed:
                return
            self.closed = True
            dropped = self._qbytes
            self._q.clear()
            self._qbytes = 0
            self._cond.notify_all()
        if self._ledger is not None and dropped:
            self._ledger.stream_done(dropped)

    # ------------------------------------------------------------- misc
    def _occupancy(self):
        with self._cond:
            return len(self._q), self._qbytes, (self._finished or self.closed)

    def __len__(self) -> int:
        with self._cond:
            return len(self._q)

    @property
    def queued_bytes(self) -> int:
        with self._cond:
            return self._qbytes
