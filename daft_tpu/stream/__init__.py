"""Morsel-driven streaming execution (README "Streaming execution").

Streamable chains — Scan/InMemory source -> Project/Filter/FusedMap maps
-> optional Limit sink — pull fixed-size morsels (``cfg.morsel_size_rows``)
through bounded channels with backpressure instead of materializing whole
partitions at every step boundary. Pipeline breakers keep their
partition-granular contract behind the driver's morsel->partition re-chunk
boundary, so results are byte-identical with ``cfg.streaming_execution``
off (the hard invariant every test in tests/test_streaming.py pins).

- :mod:`daft_tpu.stream.morsel`   — zero-copy slice views over a
  MicroPartition's reader chunks
- :mod:`daft_tpu.stream.channel`  — bounded MPSC channel charged to the
  query's MemoryLedger share, with close/error propagation
- :mod:`daft_tpu.stream.pipeline` — segment extraction + the
  producer/consumer driver over the shared executor pool
"""

from .channel import BoundedChannel, ChannelClosed, channels_snapshot
from .morsel import iter_morsels
from .pipeline import try_stream

__all__ = ["BoundedChannel", "ChannelClosed", "channels_snapshot",
           "iter_morsels", "try_stream"]
