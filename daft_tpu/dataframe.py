"""DataFrame: the lazy user-facing API over a LogicalPlan.

Role-equivalent to the reference's daft/dataframe/dataframe.py:71. A DataFrame
wraps a logical plan; transformations build new plans; collect()/show()
optimize + translate + execute through the context's runner. Materialized
results are cached on the DataFrame (reference: _result/_preview discipline).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Union

from .context import get_context
from .datatypes import DataType
from .execution import RuntimeStats
from .expressions import AggExpr, Expression, col, lit
from .logical import (
    Aggregate,
    Concat,
    Distinct,
    Explode,
    Filter,
    InMemorySource,
    Join,
    Limit,
    LogicalPlan,
    MonotonicallyIncreasingId,
    Pivot,
    Project,
    Repartition,
    Sample,
    Sort,
    Unpivot,
    Write,
)
from .micropartition import MicroPartition
from .optimizer import optimize
from .runners import PartitionSet
from .schema import Schema

ColumnInput = Union[str, Expression]


def _to_expr(c: ColumnInput) -> Expression:
    return col(c) if isinstance(c, str) else c


def _to_exprs(cols) -> List[Expression]:
    if isinstance(cols, (str, Expression)):
        return [_to_expr(cols)]
    return [_to_expr(c) for c in cols]


def _norm_bools(v, k: int, default=False):
    if v is None:
        return [default] * k
    if isinstance(v, bool):
        return [v] * k
    out = list(v)
    if len(out) != k:
        raise ValueError(f"expected {k} flags, got {len(out)}")
    return out


class DataFrame:
    def __init__(self, plan: LogicalPlan, result: Optional[PartitionSet] = None):
        self._plan = plan
        self._result = result
        self.stats = RuntimeStats()
        self._profile = None  # QueryProfile from a profiled collect()

    # ------------------------------------------------------------------ metadata
    @property
    def schema(self) -> Schema:
        return self._plan.schema

    @property
    def column_names(self) -> List[str]:
        return self._plan.schema.field_names()

    @property
    def columns(self) -> List[Expression]:
        return [col(n) for n in self.column_names]

    def __getitem__(self, item) -> Expression:
        if isinstance(item, str):
            if item != "*" and item not in self.schema:
                raise ValueError(f"unknown column {item!r}")
            return col(item)
        raise TypeError(f"cannot index DataFrame with {type(item).__name__}")

    def __contains__(self, name: str) -> bool:
        return name in self.schema

    def num_partitions(self) -> int:
        return self._plan.num_partitions()

    def explain(self, show_all: bool = False) -> str:
        """Logical plan (and optimized + physical when show_all)."""
        out = ["== Unoptimized Logical Plan ==", self._plan.display_tree()]
        if show_all:
            ctx = get_context()
            opt = optimize(self._plan)
            out += ["", "== Optimized Logical Plan ==", opt.display_tree()]
            from .physical import translate

            phys = translate(opt, ctx.execution_config)
            out += ["", "== Physical Plan ==", phys.display_tree()]
        text = "\n".join(out)
        print(text)
        return text

    def explain_analyze(self) -> str:
        """Execute (if needed, with the profiler armed) and render
        per-operator rows + wall-time, plus the per-op timeline /
        critical-path section from the QueryProfile.

        Reference: the native executor's explain-analyze output
        (DAFT_DEV_ENABLE_EXPLAIN_ANALYZE, run.rs:106-115) backed by per-node
        RuntimeStatsContext counters (runtime_stats.rs:16-27)."""
        from .obs.capture import render_runtime_stats

        self.collect(profile=True)
        lines = [render_runtime_stats(self.stats)]
        if self._profile is not None and self._profile.ops:
            lines.append("")
            lines.append(self._profile.render_timeline())
        text = "\n".join(lines)
        print(text)
        return text

    # ------------------------------------------------------------------ projection
    def select(self, *columns: ColumnInput) -> "DataFrame":
        exprs = []
        for c in columns:
            if isinstance(c, str) and c == "*":
                exprs.extend(col(n) for n in self.column_names)
            else:
                exprs.append(_to_expr(c))
        return DataFrame(Project(self._plan, exprs))

    def exclude(self, *names: str) -> "DataFrame":
        drop = set(names)
        keep = [col(n) for n in self.column_names if n not in drop]
        return DataFrame(Project(self._plan, keep))

    def with_column(self, name: str, expr: Expression) -> "DataFrame":
        return self.with_columns({name: expr})

    def with_columns(self, columns: Dict[str, Expression]) -> "DataFrame":
        exprs: List[Expression] = []
        for n in self.column_names:
            if n in columns:
                exprs.append(_to_expr(columns[n]).alias(n))
            else:
                exprs.append(col(n))
        for n, e in columns.items():
            if n not in self.schema:
                exprs.append(_to_expr(e).alias(n))
        return DataFrame(Project(self._plan, exprs))

    def with_column_renamed(self, existing: str, new: str) -> "DataFrame":
        return self.with_columns_renamed({existing: new})

    def with_columns_renamed(self, mapping: Dict[str, str]) -> "DataFrame":
        exprs = [col(n).alias(mapping.get(n, n)) for n in self.column_names]
        return DataFrame(Project(self._plan, exprs))

    def transform(self, func: Callable[["DataFrame"], "DataFrame"], *args, **kwargs) -> "DataFrame":
        out = func(self, *args, **kwargs)
        if not isinstance(out, DataFrame):
            raise ValueError(f"transform function must return a DataFrame, got {type(out)}")
        return out

    # ------------------------------------------------------------------ filtering
    def where(self, predicate: Union[Expression, str]) -> "DataFrame":
        if isinstance(predicate, str):
            from .sql import sql_expr

            predicate = sql_expr(predicate)
        return DataFrame(Filter(self._plan, predicate))

    filter = where

    def drop_null(self, *columns: ColumnInput) -> "DataFrame":
        exprs = _to_exprs(columns) if columns else [col(n) for n in self.column_names]
        pred = exprs[0].not_null()
        for e in exprs[1:]:
            pred = pred & e.not_null()
        return self.where(pred)

    def drop_nan(self, *columns: ColumnInput) -> "DataFrame":
        if columns:
            exprs = _to_exprs(columns)
        else:
            exprs = [col(f.name) for f in self.schema if f.dtype.is_floating()]
        if not exprs:
            return self
        pred = None
        for e in exprs:
            p = e.is_null() | e.float.not_nan()
            pred = p if pred is None else (pred & p)
        return self.where(pred)

    def distinct(self, *subset: ColumnInput) -> "DataFrame":
        return DataFrame(Distinct(self._plan, _to_exprs(subset) if subset else None))

    unique = distinct

    def sample(self, fraction: float, with_replacement: bool = False,
               seed: Optional[int] = None) -> "DataFrame":
        if fraction < 0.0 or fraction > 1.0:
            raise ValueError(f"fraction must be in [0, 1], got {fraction}")
        return DataFrame(Sample(self._plan, fraction, with_replacement, seed))

    def limit(self, num: int) -> "DataFrame":
        if num < 0:
            raise ValueError(f"limit must be non-negative, got {num}")
        return DataFrame(Limit(self._plan, num))

    head = limit

    # ------------------------------------------------------------------ ordering
    def sort(self, by, desc: Union[bool, List[bool]] = False,
             nulls_first=None) -> "DataFrame":
        by = _to_exprs(by)
        desc = _norm_bools(desc, len(by))
        nf = _norm_bools(nulls_first, len(by), None) if nulls_first is not None else [None] * len(by)
        return DataFrame(Sort(self._plan, by, desc, nf))

    # ------------------------------------------------------------------ partitioning
    def repartition(self, num: Optional[int], *partition_by: ColumnInput) -> "DataFrame":
        if partition_by:
            return DataFrame(Repartition(self._plan, "hash", num, _to_exprs(partition_by)))
        return DataFrame(Repartition(self._plan, "random", num))

    def into_partitions(self, num: int) -> "DataFrame":
        return DataFrame(Repartition(self._plan, "into", num))

    # ------------------------------------------------------------------ combining
    def join(self, other: "DataFrame", on=None, left_on=None, right_on=None,
             how: str = "inner", strategy: Optional[str] = None,
             suffix: str = "right.") -> "DataFrame":
        if on is not None:
            left_on = right_on = on
        if how != "cross" and (left_on is None or right_on is None):
            raise ValueError("join requires on= or left_on=/right_on=")
        lo = _to_exprs(left_on) if left_on is not None else []
        ro = _to_exprs(right_on) if right_on is not None else []
        return DataFrame(Join(self._plan, other._plan, lo, ro, how, strategy, suffix))

    def concat(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(Concat(self._plan, other._plan))

    # ------------------------------------------------------------------ reshaping
    def explode(self, *columns: ColumnInput) -> "DataFrame":
        return DataFrame(Explode(self._plan, _to_exprs(columns)))

    def unpivot(self, ids, values=None, variable_name: str = "variable",
                value_name: str = "value") -> "DataFrame":
        ids = _to_exprs(ids)
        if values is None:
            id_names = {e.name() for e in ids}
            values = [col(n) for n in self.column_names if n not in id_names]
        else:
            values = _to_exprs(values)
        return DataFrame(Unpivot(self._plan, ids, values, variable_name, value_name))

    melt = unpivot

    def pivot(self, group_by, pivot_col: ColumnInput, value_col: ColumnInput,
              agg_fn: str, names: Optional[List[str]] = None) -> "DataFrame":
        group_by = _to_exprs(group_by)
        pivot_e = _to_expr(pivot_col)
        value_e = _to_expr(value_col)
        if names is None:
            names_df = DataFrame(self._plan).select(pivot_e).distinct().collect()
            names = [v for v in names_df.to_pydict()[pivot_e.name()] if v is not None]
        return DataFrame(Pivot(self._plan, group_by, pivot_e, value_e, agg_fn, names))

    def _add_monotonic_id(self, column_name: str = "id") -> "DataFrame":
        return DataFrame(MonotonicallyIncreasingId(self._plan, column_name))

    with_monotonically_increasing_id = _add_monotonic_id

    # ------------------------------------------------------------------ aggregation
    def _agg_all(self, kind: str, cols, **extra) -> "DataFrame":
        exprs = _to_exprs(cols) if cols else [
            col(f.name) for f in self.schema if f.dtype.is_numeric()]
        aggs = [Expression(AggExpr(kind, e._node, extra or None)).alias(e.name()) for e in exprs]
        return DataFrame(Aggregate(self._plan, aggs, []))

    def sum(self, *cols: ColumnInput) -> "DataFrame":
        return self._agg_all("sum", cols)

    def mean(self, *cols: ColumnInput) -> "DataFrame":
        return self._agg_all("mean", cols)

    def min(self, *cols: ColumnInput) -> "DataFrame":
        return self._agg_all("min", cols)

    def max(self, *cols: ColumnInput) -> "DataFrame":
        return self._agg_all("max", cols)

    def stddev(self, *cols: ColumnInput) -> "DataFrame":
        return self._agg_all("stddev", cols)

    def any_value(self, *cols: ColumnInput) -> "DataFrame":
        return self._agg_all("any_value", cols)

    def count(self, *cols: ColumnInput) -> "DataFrame":
        exprs = _to_exprs(cols) if cols else [col(n) for n in self.column_names]
        aggs = [Expression(AggExpr("count", e._node)).alias(e.name()) for e in exprs]
        return DataFrame(Aggregate(self._plan, aggs, []))

    def agg_list(self, *cols: ColumnInput) -> "DataFrame":
        exprs = _to_exprs(cols) if cols else [col(n) for n in self.column_names]
        aggs = [Expression(AggExpr("list", e._node)).alias(e.name()) for e in exprs]
        return DataFrame(Aggregate(self._plan, aggs, []))

    def agg_concat(self, *cols: ColumnInput) -> "DataFrame":
        exprs = _to_exprs(cols)
        aggs = [Expression(AggExpr("concat", e._node)).alias(e.name()) for e in exprs]
        return DataFrame(Aggregate(self._plan, aggs, []))

    def agg(self, *to_agg) -> "DataFrame":
        aggs = self._normalize_aggs(to_agg)
        return DataFrame(Aggregate(self._plan, aggs, []))

    @staticmethod
    def _normalize_aggs(to_agg) -> List[Expression]:
        flat: List[Any] = []
        for a in to_agg:
            if isinstance(a, (list, tuple)) and not (
                isinstance(a, tuple) and len(a) == 2 and isinstance(a[1], str)
            ):
                flat.extend(a)
            else:
                flat.append(a)
        out: List[Expression] = []
        for a in flat:
            if isinstance(a, tuple):
                e, fn = a
                e = _to_expr(e)
                out.append(getattr(e, {"sum": "sum", "mean": "mean", "min": "min",
                                       "max": "max", "count": "count", "list": "agg_list",
                                       "concat": "agg_concat", "stddev": "stddev"}[fn])())
            else:
                out.append(_to_expr(a))
        for e in out:
            if not e._node.is_aggregation():
                raise ValueError(f"agg() expects aggregation expressions, got {e!r}")
        return out

    def groupby(self, *group_by: ColumnInput) -> "GroupedDataFrame":
        exprs = []
        for g in group_by:
            if isinstance(g, (list, tuple)):
                exprs.extend(_to_exprs(g))
            else:
                exprs.append(_to_expr(g))
        if not exprs:
            raise ValueError("groupby requires at least one column")
        return GroupedDataFrame(self, exprs)

    def count_rows(self) -> int:
        if not self.column_names:
            return 0
        cnt = DataFrame(Aggregate(
            self._plan,
            [Expression(AggExpr("count", col(self.column_names[0])._node,
                                {"mode": "all"})).alias("count")], []))
        return cnt.to_pydict()["count"][0]

    def __len__(self) -> int:
        return self.count_rows()

    # ------------------------------------------------------------------ writes
    def write_parquet(self, root_dir: str, compression: str = "snappy",
                      partition_cols=None) -> "DataFrame":
        pc = _to_exprs(partition_cols) if partition_cols else None
        return DataFrame(Write(self._plan, root_dir, "parquet", compression, pc)).collect()

    def write_csv(self, root_dir: str, partition_cols=None) -> "DataFrame":
        pc = _to_exprs(partition_cols) if partition_cols else None
        return DataFrame(Write(self._plan, root_dir, "csv", None, pc)).collect()

    def write_json(self, root_dir: str, partition_cols=None) -> "DataFrame":
        pc = _to_exprs(partition_cols) if partition_cols else None
        return DataFrame(Write(self._plan, root_dir, "json", None, pc)).collect()

    def write_iceberg(self, table_uri: str, mode: str = "append") -> "DataFrame":
        """Write this DataFrame as an Iceberg v2 snapshot commit (reference:
        daft/dataframe/dataframe.py write_iceberg; no client library — the
        avro manifests are encoded natively by io/avro.py). mode: append |
        overwrite | error. Returns a DataFrame of the added file paths."""
        from .io.catalogs import write_iceberg_table

        self.collect()
        arrow_tables = [p.to_arrow() for p in self._result.partitions]
        added = write_iceberg_table(table_uri, arrow_tables, mode=mode)
        from .api import from_pydict

        return from_pydict({"path": added})

    def write_deltalake(self, table_uri: str, mode: str = "append") -> "DataFrame":
        """Write this DataFrame as a Delta Lake table commit (reference:
        daft/dataframe/dataframe.py write_deltalake). mode: append |
        overwrite | error. The commit is atomic: parquet data files land
        first, then one put-if-absent JSON transaction publishes them.
        Returns a DataFrame of the added file paths."""
        from .io.catalogs import write_deltalake_table

        self.collect()
        arrow_tables = [p.to_arrow() for p in self._result.partitions]
        added = write_deltalake_table(table_uri, arrow_tables, mode=mode)
        from .api import from_pydict

        return from_pydict({"path": added})

    def write_lance(self, table_uri: str, mode: str = "append") -> "DataFrame":
        """Write this DataFrame as a lance dataset (reference:
        daft/dataframe/dataframe.py write_lance via lance.write_dataset —
        requires the optional `lance` package, as in the reference). mode:
        append | overwrite | error. Returns a DataFrame of data-file paths."""
        from .io.catalogs import write_lance_table

        self.collect()
        arrow_tables = [p.to_arrow() for p in self._result.partitions]
        added = write_lance_table(table_uri, arrow_tables, mode=mode)
        from .api import from_pydict

        return from_pydict({"path": added})

    # ------------------------------------------------------------------ execution
    def cancel(self) -> None:
        """Stop this DataFrame's in-flight execution at the next partition
        boundary (reference: stop_plan / MaterializedResult.cancel)."""
        self.stats.cancel()

    def collect(self, profile: Union[bool, str, None] = None) -> "DataFrame":
        """Materialize the plan. ``profile`` arms the structured query
        profiler for this execution: ``True`` records a QueryProfile
        (``df.profile()`` / ``daft_tpu.last_profile()``), a string path
        additionally writes the profile JSON there. ``None`` defers to
        ``ExecutionConfig.enable_profiling``. An already-materialized
        DataFrame cannot re-execute: its existing profile (if any) is
        served — and written to a requested path — instead of silently
        ignoring the argument."""
        if self._result is not None:
            if isinstance(profile, str) and self._profile is not None:
                self._profile.to_json(profile)
            return self
        self.stats.reset_cancel()  # a cancelled DataFrame stays retryable
        from .runners import partition_set_cache, plan_cache_key

        cfg = get_context().execution_config
        want = profile if profile is not None else cfg.enable_profiling
        if want:
            from .profile import Profiler

            self.stats.profiler = Profiler(query_id=f"q-{id(self._plan):x}")
        cache = partition_set_cache()
        key = (plan_cache_key(self._plan)
               if cfg.enable_result_cache else None)
        hit = cache.get(key) if key is not None else None
        if hit is not None:
            self.stats.bump("result_cache_hits")
            if self.stats.profiler.armed:
                self.stats.profiler.event("result_cache_hit")
            self._result = hit
        else:
            runner = get_context().runner()
            self._result = runner.run(self._plan, stats=self.stats)
            if key is not None:
                import weakref

                cache.put(key, self._result)
                # the entry lives exactly as long as some DataFrame owns it
                weakref.finalize(self, cache.release, key)
        if want:
            from .profile import build_profile

            qp = build_profile(self.stats.profiler, self.stats)
            self._profile = qp
            get_context()._last_profile = qp
            if isinstance(want, str):
                qp.to_json(want)
        self._plan = InMemorySource(self._result.schema, self._result.partitions)
        return self

    def profile(self):
        """The QueryProfile recorded by a profiled collect(), or None."""
        return self._profile

    def last_query_record(self):
        """The flight recorder's QueryRecord for this DataFrame's most
        recent plan execution (None before any execution, or when the
        result was served from the plan cache). The same record is in
        ``daft_tpu.query_log()``."""
        return self.stats.last_record

    def iter_partitions(self) -> Iterator[MicroPartition]:
        if self._result is not None:
            yield from self._result.partitions
            return
        self.stats.reset_cancel()
        runner = get_context().runner()
        yield from runner.run_iter(self._plan, stats=self.stats)

    def to_arrow_iter(self):
        for part in self.iter_partitions():
            if len(part):
                yield from part.to_arrow().to_batches()

    def iter_rows(self) -> Iterator[dict]:
        for part in self.iter_partitions():
            yield from part.to_pylist()

    def _materialized(self) -> PartitionSet:
        self.collect()
        return self._result

    def to_pydict(self) -> Dict[str, list]:
        return self._materialized().to_table().to_pydict()

    def to_pylist(self) -> List[dict]:
        return self._materialized().to_table().to_pylist()

    def to_arrow(self):
        return self._materialized().to_table().to_arrow()

    def to_pandas(self):
        return self._materialized().to_table().to_pandas()

    def to_table(self):
        return self._materialized().to_table()

    def to_torch_map_dataset(self):
        from .integrations.torch_data import MapDataset

        return MapDataset(self)

    def to_torch_iter_dataset(self):
        from .integrations.torch_data import IterDataset

        return IterDataset(self)

    def to_ray_dataset(self):
        """Reference: dataframe.py to_ray_dataset — needs the ray runtime,
        which is not part of this image (the mesh runner is the distributed
        backend here)."""
        try:
            import ray.data  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "to_ray_dataset requires ray, which is not installed; "
                "distributed execution here runs on the jax mesh (MeshRunner)") from e
        import ray.data as rd

        return rd.from_arrow(self.to_arrow())

    def to_dask_dataframe(self):
        """Reference: dataframe.py to_dask_dataframe — needs dask."""
        try:
            import dask.dataframe as dd
        except ImportError as e:
            raise ImportError("to_dask_dataframe requires dask, which is not installed") from e
        return dd.from_pandas(self.to_pandas(), npartitions=max(self.num_partitions(), 1))

    # ------------------------------------------------------------------ display
    def show(self, n: int = 8) -> None:
        print(self.limit(n)._preview_str(n))

    def _preview_str(self, n: int) -> str:
        tbl = self.limit(n).to_table()
        d = tbl.to_pydict()
        names = list(d)
        widths = {}
        dtypes = {f.name: repr(f.dtype) for f in tbl.schema}
        for nm in names:
            vals = [_cell(v) for v in d[nm]]
            widths[nm] = min(30, max([len(nm), len(dtypes[nm])] + [len(v) for v in vals] + [4]))
            d[nm] = vals
        def row(cells):
            return "| " + " | ".join(c[:widths[nm]].ljust(widths[nm]) for nm, c in zip(names, cells)) + " |"
        sep = "+" + "+".join("-" * (widths[nm] + 2) for nm in names) + "+"
        lines = [sep, row(names), row([dtypes[nm] for nm in names]), sep]
        nrows = len(d[names[0]]) if names else 0
        for i in range(nrows):
            lines.append(row([d[nm][i] for nm in names]))
        lines.append(sep)
        return "\n".join(lines)

    def __repr__(self) -> str:
        n = get_context().execution_config.num_preview_rows
        if self._result is not None:
            try:
                return self._preview_str(n)
            except Exception:
                pass
        return f"DataFrame({self.schema!r})"

    def _repr_html_(self) -> str:
        """Notebook preview table with registered viz hooks applied to
        Python-object cells (reference: daft/dataframe/display.py +
        daft/viz/html_viz_hooks.py)."""
        import html as _h

        from .viz import html_table

        n = get_context().execution_config.num_preview_rows
        # same discipline as __repr__: never execute the plan at display time,
        # never let a preview error break notebook rendering
        if self._result is not None:
            try:
                total = sum(len(p) for p in self._result.partitions)
                preview = self.limit(n).to_table()
                return html_table(preview.schema, preview.to_pydict(), n, total)
            except Exception:
                pass
        return f"<pre>DataFrame({_h.escape(repr(self.schema))})</pre>"


def _cell(v) -> str:
    if v is None:
        return "None"
    s = str(v)
    return s if len(s) <= 30 else s[:27] + "..."


class GroupedDataFrame:
    """Result of df.groupby(...) (reference: daft/dataframe/dataframe.py
    GroupedDataFrame)."""

    def __init__(self, df: DataFrame, group_by: List[Expression]):
        self.df = df
        self.group_by = group_by

    def _agg_all(self, kind: str, cols, **extra) -> DataFrame:
        keys = {e.name() for e in self.group_by}
        if cols:
            exprs = _to_exprs(cols)
        else:
            exprs = [col(f.name) for f in self.df.schema
                     if f.name not in keys and (f.dtype.is_numeric() or kind in ("count", "any_value"))]
        aggs = [Expression(AggExpr(kind, e._node, extra or None)).alias(e.name()) for e in exprs]
        return DataFrame(Aggregate(self.df._plan, aggs, self.group_by))

    def sum(self, *cols: ColumnInput) -> DataFrame:
        return self._agg_all("sum", cols)

    def mean(self, *cols: ColumnInput) -> DataFrame:
        return self._agg_all("mean", cols)

    def min(self, *cols: ColumnInput) -> DataFrame:
        return self._agg_all("min", cols)

    def max(self, *cols: ColumnInput) -> DataFrame:
        return self._agg_all("max", cols)

    def stddev(self, *cols: ColumnInput) -> DataFrame:
        return self._agg_all("stddev", cols)

    def any_value(self, *cols: ColumnInput) -> DataFrame:
        return self._agg_all("any_value", cols)

    def count(self, *cols: ColumnInput) -> DataFrame:
        return self._agg_all("count", cols)

    def agg_list(self, *cols: ColumnInput) -> DataFrame:
        return self._agg_all("list", cols)

    def agg_concat(self, *cols: ColumnInput) -> DataFrame:
        return self._agg_all("concat", cols)

    def agg(self, *to_agg) -> DataFrame:
        aggs = DataFrame._normalize_aggs(to_agg)
        return DataFrame(Aggregate(self.df._plan, aggs, self.group_by))

    def map_groups(self, udf_expr: Expression) -> DataFrame:
        """Run a UDF once per group (reference: GroupedDataFrame.map_groups).
        Executed by materializing group partitions; the UDF sees each group's
        rows as full columns."""
        df = self.df.collect()
        mp = df._result.to_micropartition()
        parts, uniq = mp.partition_by_value(self.group_by)
        from .table import Table

        outs = []
        key_names = uniq.column_names
        for i, part in enumerate(parts):
            res = part.table().eval_expression_list([udf_expr])
            key_row = uniq.slice(i, i + 1)
            n = len(res)
            key_cols = {}
            for kn in key_names:
                v = key_row.get_column(kn).to_pylist()[0]
                key_cols[kn] = [v] * n
            merged = Table.from_pydict({**key_cols, **res.to_pydict()})
            outs.append(merged)
        if not outs:
            schema = Schema(list(uniq.schema))
            out_tbl = Table.empty(schema)
        else:
            out_tbl = Table.concat(outs)
        return from_partitions([MicroPartition.from_table(out_tbl)], out_tbl.schema)


# ---------------------------------------------------------------------------
# constructors (used by api.py)
# ---------------------------------------------------------------------------

def from_partitions(parts: List[MicroPartition], schema: Schema) -> DataFrame:
    ps = PartitionSet(schema, parts)
    return DataFrame(InMemorySource(schema, parts), result=ps)
