"""Public API: constructors, read_* functions, top-level names.

Role-equivalent to the reference's daft/__init__.py:97-136 (public surface)
and daft/io/ constructor family. Everything here is re-exported from the
package root.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Union

from .context import (
    DaftContext,
    get_context,
    set_execution_config,
    set_planning_config,
    set_runner_mesh,
    set_runner_native,
)
from .dataframe import DataFrame, GroupedDataFrame, from_partitions
from .datatypes import DataType
from .expressions import Expression, col, element, interval, lit
from .io.readers import file_size
from .io.scan import (FileFormat, Pushdowns, ScanTask, glob_paths,
                      merge_scan_tasks_by_size)
from .logical import InMemorySource, ScanSource
from .micropartition import MicroPartition
from .schema import Field, Schema
from .serve import QueryHandle, ServingRuntime
from .series import Series
from .table import Table
from .udf import UDF


# ---------------------------------------------------------------------------
# in-memory constructors
# ---------------------------------------------------------------------------

def from_pydict(data: Dict[str, Any]) -> DataFrame:
    mp = MicroPartition.from_pydict(data)
    return from_partitions([mp], mp.schema)


def from_pylist(rows: List[dict]) -> DataFrame:
    mp = MicroPartition.from_table(Table.from_pylist(rows))
    return from_partitions([mp], mp.schema)


def from_arrow(data) -> DataFrame:
    import pyarrow as pa

    if isinstance(data, (pa.Table, pa.RecordBatch)):
        mp = MicroPartition.from_arrow(data)
        return from_partitions([mp], mp.schema)
    if isinstance(data, (list, tuple)):
        parts = [MicroPartition.from_arrow(t) for t in data]
        if not parts:
            raise ValueError("from_arrow of empty list")
        return from_partitions(parts, parts[0].schema)
    raise TypeError(f"from_arrow expects pyarrow Table/RecordBatch, got {type(data)}")


def from_pandas(df) -> DataFrame:
    import pyarrow as pa

    return from_arrow(pa.Table.from_pandas(df))


def from_ray_dataset(ds) -> DataFrame:
    """Build a DataFrame from a Ray Dataset (reference:
    daft/dataframe/dataframe.py from_ray_dataset — gated on the optional
    `ray` dependency exactly as the reference gates its Ray interop)."""
    try:
        import ray  # noqa: F401
    except ImportError as e:
        raise ImportError("from_ray_dataset requires the optional `ray` "
                          "package, which is not installed") from e
    import pyarrow as pa

    tables = [ray.get(r) for r in ds.to_arrow_refs()]
    if not tables:
        return from_arrow(pa.table({}))
    return from_arrow(pa.concat_tables(tables) if len(tables) != 1 else tables[0])


def from_dask_dataframe(ddf) -> DataFrame:
    """Build a DataFrame from a Dask DataFrame (reference:
    daft/dataframe/dataframe.py from_dask_dataframe — gated on the optional
    `dask` dependency exactly as the reference)."""
    try:
        import dask  # noqa: F401
    except ImportError as e:
        raise ImportError("from_dask_dataframe requires the optional `dask` "
                          "package, which is not installed") from e
    return from_pandas(ddf.compute())


def from_glob_path(path: str) -> DataFrame:
    """DataFrame of file metadata (path, size, num_rows) for a glob —
    reference: daft/io/_glob.py."""
    paths = glob_paths(path)
    sizes = [file_size(p) for p in paths]
    return from_pydict({"path": paths, "size": sizes,
                        "num_rows": [None] * len(paths)})


# ---------------------------------------------------------------------------
# file readers
# ---------------------------------------------------------------------------

def read_parquet(path, schema_hints: Optional[Dict[str, DataType]] = None,
                 _split_row_groups: Optional[bool] = None) -> DataFrame:
    """Lazy parquet scan. Large files split into one ScanTask per row-group
    chunk (reference: ScanTask split/merge by size, daft-scan/src/lib.rs)."""
    import pyarrow.parquet as papq

    from .io.readers import row_group_stats
    from .stats import TableStats

    paths = glob_paths(path)
    if not paths:
        raise FileNotFoundError(f"no files for {path!r}")
    from .io.readers import file_size, open_parquet_file

    pf0 = open_parquet_file(paths[0])
    schema = Schema.from_arrow(pf0.schema_arrow)
    if schema_hints:
        schema = schema.apply_hints(Schema([Field(k, v) for k, v in schema_hints.items()]))
    cfg = get_context().execution_config
    tasks: List[ScanTask] = []
    for p in paths:
        md = pf0.metadata if p == paths[0] else open_parquet_file(p).metadata
        fsize = file_size(p)
        split = _split_row_groups
        if split is None:
            split = fsize > cfg.scan_tasks_max_size_bytes and md.num_row_groups > 1
        if split:
            # one task per row-group run, packed to ~min_size_bytes
            runs: List[List[int]] = []
            cur: List[int] = []
            cur_bytes = 0
            for rg in range(md.num_row_groups):
                cur.append(rg)
                cur_bytes += md.row_group(rg).total_byte_size
                if cur_bytes >= cfg.scan_tasks_min_size_bytes:
                    runs.append(cur)
                    cur, cur_bytes = [], 0
            if cur:
                runs.append(cur)
            for run in runs:
                nrows = sum(md.row_group(rg).num_rows for rg in run)
                nbytes = sum(md.row_group(rg).total_byte_size for rg in run)
                st = row_group_stats(md, run[0], schema)
                for rg in run[1:]:
                    st = st.merge(row_group_stats(md, rg, schema))
                tasks.append(ScanTask(p, FileFormat.PARQUET, schema, Pushdowns(),
                                      num_rows=nrows, size_bytes=nbytes, stats=st,
                                      row_group_ids=run))
        else:
            st: Optional[TableStats] = None
            if md.num_row_groups:
                st = row_group_stats(md, 0, schema)
                for rg in range(1, md.num_row_groups):
                    st = st.merge(row_group_stats(md, rg, schema))
            tasks.append(ScanTask(p, FileFormat.PARQUET, schema, Pushdowns(),
                                  num_rows=md.num_rows, size_bytes=fsize, stats=st))
    tasks = merge_scan_tasks_by_size(tasks, cfg.scan_tasks_min_size_bytes,
                                     cfg.scan_tasks_max_size_bytes)
    return DataFrame(ScanSource(schema, tasks))


def read_csv(path, delimiter: str = ",", has_headers: bool = True,
             column_names: Optional[List[str]] = None,
             schema_hints: Optional[Dict[str, DataType]] = None, **kw) -> DataFrame:
    from .io.readers import infer_csv_schema

    paths = glob_paths(path)
    schema = infer_csv_schema(paths[0], delimiter=delimiter, has_headers=has_headers,
                              column_names=column_names)
    if schema_hints:
        schema = schema.apply_hints(Schema([Field(k, v) for k, v in schema_hints.items()]))
    opts = {"delimiter": delimiter, "has_headers": has_headers,
            "column_names": column_names, **kw}
    tasks = [ScanTask(p, FileFormat.CSV, schema, Pushdowns(), storage_options=opts,
                      size_bytes=file_size(p)) for p in paths]
    cfg = get_context().execution_config
    tasks = merge_scan_tasks_by_size(tasks, cfg.scan_tasks_min_size_bytes,
                                     cfg.scan_tasks_max_size_bytes)
    return DataFrame(ScanSource(schema, tasks))


def read_json(path, schema_hints: Optional[Dict[str, DataType]] = None) -> DataFrame:
    from .io.readers import infer_json_schema

    paths = glob_paths(path)
    schema = infer_json_schema(paths[0])
    if schema_hints:
        schema = schema.apply_hints(Schema([Field(k, v) for k, v in schema_hints.items()]))
    tasks = [ScanTask(p, FileFormat.JSON, schema, Pushdowns(),
                      size_bytes=file_size(p)) for p in paths]
    cfg = get_context().execution_config
    tasks = merge_scan_tasks_by_size(tasks, cfg.scan_tasks_min_size_bytes,
                                     cfg.scan_tasks_max_size_bytes)
    return DataFrame(ScanSource(schema, tasks))




def read_deltalake(table_uri) -> DataFrame:
    """Read a Delta Lake table by replaying its transaction log (reference:
    daft/delta_lake/delta_lake_scan.py:26; no client library — the
    _delta_log JSON actions are parsed natively). Accepts a path or a
    UnityCatalogTable resolved by io.unity.UnityCatalog.load_table
    (reference: read_deltalake(unity_table), daft/io/_deltalake.py)."""
    from .io.catalogs import read_deltalake_scan
    from .io.unity import UnityCatalogTable

    if isinstance(table_uri, UnityCatalogTable):
        table_uri = table_uri.table_uri
    schema, tasks = read_deltalake_scan(table_uri)
    return DataFrame(ScanSource(schema, tasks))


def read_sql(sql: str, conn, params=None) -> DataFrame:
    """Run a SQL query through a DB-API connection (or sqlite:// URL / path)
    and load the result (reference: daft/sql/sql_scan.py:35)."""
    from .io.catalogs import read_sql_arrow

    return from_arrow(read_sql_arrow(sql, conn, params))


def read_iceberg(table_uri: str, snapshot_id=None) -> DataFrame:
    """Read a local Iceberg v1/v2 table by replaying manifest list ->
    manifests -> live data files (reference: daft/iceberg/iceberg_scan.py:84;
    no client library — the avro manifests are decoded natively by
    io/avro.py). Copy-on-write tables only."""
    from .io.catalogs import read_iceberg_scan

    schema, tasks = read_iceberg_scan(table_uri, snapshot_id)
    return DataFrame(ScanSource(schema, tasks))


def read_hudi(table_uri: str) -> DataFrame:
    """Read a local Hudi copy-on-write table by replaying its .hoodie commit
    timeline (reference: daft/hudi/hudi_scan.py:22)."""
    from .io.catalogs import read_hudi_scan

    schema, tasks = read_hudi_scan(table_uri)
    return DataFrame(ScanSource(schema, tasks))


def read_lance(url: str, storage_options=None) -> DataFrame:
    """Read a LanceDB dataset, one scan task per lance fragment (reference:
    daft/io/_lance.py:68 — like the reference, the lance data format is read
    through the optional `lance` client package, which must be installed)."""
    from .io.catalogs import read_lance_scan

    return read_lance_scan(url, storage_options=storage_options)


def from_scan_operator(op) -> DataFrame:
    """Build a DataFrame over a user-defined ScanOperator (reference:
    ScanOperatorHandle.from_python_scan_operator, daft/io/scan.py:20-50)."""
    from .io.pyscan import from_scan_operator as _fso

    return _fso(op)


# ---------------------------------------------------------------------------
# UDF + SQL entry points
# ---------------------------------------------------------------------------

def udf(return_dtype: DataType, num_cpus=None, num_gpus=None, memory_bytes=None,
        batch_size=None, concurrency=None, batching=None):
    """Decorator: make a batch UDF (reference: daft/udf.py:441).

    ``batching=True`` (or a dict of overrides) opts into the
    dynamic-batching executor — see ``batch_udf`` for the dedicated
    declaration and README "Batched inference" for semantics."""
    from .udf import _normalize_batching

    def deco(fn):
        return UDF(fn, return_dtype, num_cpus=num_cpus, num_gpus=num_gpus,
                   memory_bytes=memory_bytes, batch_size=batch_size,
                   concurrency=concurrency,
                   batching=_normalize_batching(batching))

    return deco


def batch_udf(*, return_dtype: DataType, max_rows=None, max_bytes=None,
              flush_ms=None, mode=None, device=False, concurrency=None,
              num_cpus=None, num_gpus=None, memory_bytes=None):
    """Decorator: declare a dynamically-batched UDF (daft_tpu/batch/,
    README "Batched inference"). The declaration is a contract that the fn
    is row-local; the engine may then coalesce morsels into device-friendly
    batches and re-split outputs byte-identically. Class targets become
    pinned model actors (weights loaded once per process, resident across
    queries)."""
    from .udf import batch_udf as _batch_udf

    return _batch_udf(return_dtype=return_dtype, max_rows=max_rows,
                      max_bytes=max_bytes, flush_ms=flush_ms, mode=mode,
                      device=device, concurrency=concurrency,
                      num_cpus=num_cpus, num_gpus=num_gpus,
                      memory_bytes=memory_bytes)


def sql(query: str, **catalog: DataFrame) -> DataFrame:
    from .sql import sql as _sql

    return _sql(query, **catalog)


def sql_expr(text: str) -> Expression:
    from .sql import sql_expr as _sql_expr

    return _sql_expr(text)


def last_profile():
    """The QueryProfile of the most recent profiled query
    (``df.collect(profile=True)`` / ``enable_profiling``), or None."""
    from .context import get_context as _gc

    return _gc().last_profile()


def metrics_text() -> str:
    """Prometheus-text dump of the process-level metrics registry
    (daft_tpu/profile/metrics.py) — the serving layer's scrape surface.
    Health/ledger gauges are refreshed first, so the dump always carries
    current memory pressure and breaker state."""
    from .obs.health import refresh_health_gauges
    from .profile import METRICS

    refresh_health_gauges()
    return METRICS.render_prometheus()


def query_log(limit: Optional[int] = None) -> List[dict]:
    """The flight recorder's QueryRecords (oldest first; newest ``limit``
    when given). One validated record per completed plan execution —
    success, error, timeout, cancel — appended always-on by the engine
    (``ExecutionConfig.enable_query_log``)."""
    from .obs.querylog import QUERY_LOG

    return QUERY_LOG.records(limit)


def health() -> dict:
    """One validated engine-health snapshot: breaker states, MemoryLedger
    balances, scheduler in-flight window, actor-pool/leaked-thread counts,
    live query progress (``"queries"``), query-log depth. Mirrored as
    gauges into ``metrics_text()``."""
    from .obs.health import engine_health

    return engine_health()


def query_progress(query_id: Optional[str] = None):
    """Live progress of running queries (daft_tpu/obs/cluster.py): ops
    completed/total, rows/bytes flowed, tasks in flight, per-worker
    dispatch state, streaming channel depths. With ``query_id``, one
    query's snapshot (None when it is not currently executing); without,
    the list of all running queries — the same data
    ``dt.health()["queries"]`` carries."""
    from .obs.cluster import queries_snapshot
    from .obs.cluster import query_progress as _one

    if query_id is not None:
        return _one(query_id)
    return queries_snapshot()


def engine_log_tail(n: int = 200, query_id: Optional[str] = None) -> List[dict]:
    """The newest structured engine-log records (daft_tpu/obs/log.py),
    optionally filtered to one query id."""
    from .obs.log import tail

    return tail(n, query_id=query_id)


def shutdown(timeout_s: float = 10.0) -> dict:
    """Graceful engine shutdown: drain every live ServingRuntime (stop
    admitting, finish in-flight queries, report stragglers), stop the
    actor pools, and wait — bounded — for engine worker threads to exit.
    Also registered atexit with a short timeout. Returns
    ``{"stragglers", "leaked_threads", "waited_s"}``."""
    # flush the warm-start artifact leg FIRST, while the caches are still
    # whole — the next process's zero-compile warm start rides on this
    # write landing (fail-open: a persist defect never blocks shutdown)
    try:
        from . import persist
        from .context import get_context

        cfg = get_context().execution_config
        if persist.enabled(cfg):
            persist.flush(cfg)
    except Exception:
        pass
    from .serve import shutdown as _shutdown

    return _shutdown(timeout_s=timeout_s)


__all__ = [
    "DataFrame",
    "GroupedDataFrame",
    "Expression",
    "Table",
    "MicroPartition",
    "UDF",
    "col",
    "lit",
    "element",
    "interval",
    "udf",
    "batch_udf",
    "sql",
    "sql_expr",
    "from_pydict",
    "from_pylist",
    "from_arrow",
    "from_pandas",
    "from_glob_path",
    "from_ray_dataset",
    "from_dask_dataframe",
    "from_partitions",
    "read_parquet",
    "read_csv",
    "read_json",
    "read_iceberg",
    "read_deltalake",
    "read_hudi",
    "read_lance",
    "from_scan_operator",
    "read_sql",
    "get_context",
    "last_profile",
    "metrics_text",
    "query_log",
    "health",
    "query_progress",
    "engine_log_tail",
    "ServingRuntime",
    "QueryHandle",
    "shutdown",
    "set_execution_config",
    "set_planning_config",
    "set_runner_native",
    "set_runner_mesh",
]
